"""Query planner/executor: TSQuery -> series selection -> TPU kernels -> results.

Reference behavior: /root/reference/src/core/TsdbQuery.java — UID resolution
(configureFromQuery :490), tag-filter evaluation + group-by discovery
(findGroupBys :675, GroupByAndAggregateCB :981-1114), span windowing, and the
SpanGroup tag intersection rules (SpanGroup.computeTags :348: keys with one
distinct value stay `tags`, conflicting keys become `aggregateTags`).

The per-datapoint iterator merge is replaced by ops.pipeline: each group-by
bucket becomes one padded [series, time] batch pushed through jit-compiled
downsample/rate/union kernels.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from opentsdb_tpu.models.tsquery import TSQuery, TSSubQuery
from opentsdb_tpu.obs import latattr
from opentsdb_tpu.obs import trace as obs_trace
from opentsdb_tpu.ops.downsample import (
    FixedWindows, EdgeWindows, AllWindow, pad_pow2)
from opentsdb_tpu.ops.pipeline import (
    PipelineSpec, DownsampleStep, run_pipeline, run_group_pipeline,
    run_union_batch_pipeline,
    run_group_rollup_avg_pipeline, run_grid_tail, build_batch,
    build_batch_direct, PAD_TS)
from opentsdb_tpu.ops.streaming import (
    StreamAccumulator, STREAMABLE_DS, is_sketch_ds, lanes_for)
from opentsdb_tpu.rollup.config import NoSuchRollupForInterval, RollupQuery
from opentsdb_tpu.storage.memstore import Series, SeriesKey
from opentsdb_tpu.uid import NoSuchUniqueName
from opentsdb_tpu.utils import datetime_util as DT

_NO_MATCH = object()  # sentinel: a literal filter can never match

# Downsample function -> (rollup lane, function applied over lane cells).
# Counts re-reduce with SUM; min/max/sum re-reduce with themselves
# (RollupUtils qualifiers hold one aggregator's cells per lane).
_ROLLUP_LANES = {
    "sum": ("sum", "sum"),
    "zimsum": ("sum", "zimsum"),
    "count": ("count", "sum"),
    "min": ("min", "min"),
    "mimmin": ("min", "mimmin"),
    "max": ("max", "max"),
    "mimmax": ("max", "mimmax"),
}


@dataclass
class Segment:
    """One data-source slice of a sub query's time range.

    The split-rollup machinery (SplitRollupQuery.java) reduced to data: a
    rollup table serves [start, boundary) under its SLA, raw data serves the
    blackout tail.  kind: "raw" | "rollup" | "rollup_avg".
    """
    kind: str
    start_ms: int
    end_ms: int
    lane: object = None        # MemStore: rollup lane (sum lane for rollup_avg)
    count_lane: object = None  # MemStore: count lane for rollup_avg
    ds_function: str | None = None   # downsample fn override over lane cells
    rollup_query: RollupQuery | None = None


@dataclass
class QueryResult:
    """One output object of /api/query (HttpJsonSerializer.java:742-815)."""
    metric: str
    tags: dict[str, str]
    aggregate_tags: list[str]
    tsuids: list[str]
    dps: list[tuple[int, object]]  # (ts_ms, value) value int or float or NaN
    annotations: list = field(default_factory=list)
    global_annotations: list = field(default_factory=list)
    index: int = 0

    def to_json(self, ms_resolution: bool = False, show_tsuids: bool = False,
                fill_policy: str = "none", show_query: bool = False,
                sub_query: TSSubQuery | None = None,
                no_annotations: bool = False,
                global_annotations: bool = False) -> dict:
        dps = {}
        for ts_ms, value in self.dps:
            key = str(ts_ms if ms_resolution else ts_ms // 1000)
            if isinstance(value, float) and value != value:  # NaN
                dps[key] = None if fill_policy == "null" else float("nan")
            else:
                dps[key] = value
        out = {
            "metric": self.metric,
            "tags": self.tags,
            "aggregateTags": self.aggregate_tags,
        }
        if show_query and sub_query is not None:
            out["query"] = sub_query.to_json()
        if show_tsuids:
            out["tsuids"] = sorted(self.tsuids)
        if not no_annotations and self.annotations:
            out["annotations"] = [a.to_json() for a in self.annotations]
        if global_annotations and self.global_annotations:
            out["globalAnnotations"] = [a.to_json()
                                        for a in self.global_annotations]
        out["dps"] = dps
        return out


class QueryRunner:
    """Executes TSQueries against a TSDB."""

    def __init__(self, tsdb):
        self.tsdb = tsdb
        # numeric execution telemetry for the last run() — merged into the
        # query's QueryStats and served at /api/stats/query (the
        # scanner-level stats of QueryStats.java:132, re-expressed for
        # batch execution: points scanned, streamed chunks, mesh devices)
        self.exec_stats: dict[str, float] = {}

    def _bump(self, key: str, value: float) -> None:
        self.exec_stats[key] = self.exec_stats.get(key, 0.0) + value

    # -- series selection ------------------------------------------------

    def _resolve_series(self, sub: TSSubQuery, store=None
                        ) -> list[tuple[Series, dict]]:
        """All series matching the sub query, with resolved tag maps."""
        tsdb = self.tsdb
        if store is None:
            store = tsdb.store
        if sub.tsuids:
            wanted = {t.upper() for t in sub.tsuids}
            out = []
            for series in store.all_series():
                if tsdb.tsuid(series.key) in wanted:
                    out.append((series, tsdb.resolve_key_tags(series.key)))
            return out

        metric_uid = tsdb.metrics.get_id(sub.metric)
        candidates = store.series_for_metric(metric_uid)
        uid_constraints = self._literal_uid_constraints(sub.filters)
        if uid_constraints is _NO_MATCH:
            return []
        out = []
        filter_tagks = {f.tagk for f in sub.filters}
        for series in candidates:
            if uid_constraints:
                key_tags = dict(series.key.tags)
                if any(key_tags.get(ku) not in vuids
                       for ku, vuids in uid_constraints):
                    continue
            tags = tsdb.resolve_key_tags(series.key)
            if sub.explicit_tags and set(tags) != filter_tagks:
                continue
            if all(f.match(tags) for f in sub.filters):
                out.append((series, tags))
        return out

    def _literal_uid_constraints(self, filters):
        """Compile literal filters to (tagk_uid, tagv_uid_set) pre-filters.

        The UID-space pruning role of the reference's in-scan row regex
        (TsdbQuery.createAndSetFilter :1683): series failing a literal_or
        constraint are skipped before any UID->string resolution.  Returns
        _NO_MATCH when a constraint cannot match anything (unknown tagk, or
        no listed value exists in the tagv dictionary).
        """
        tsdb = self.tsdb
        out = []
        for f in filters:
            values = f.literal_values()
            if values is None:
                continue
            try:
                ku = tsdb.tag_names.get_id(f.tagk)
            except NoSuchUniqueName:
                return _NO_MATCH
            vuids = set()
            for v in values:
                try:
                    vuids.add(tsdb.tag_values.get_id(v))
                except NoSuchUniqueName:
                    pass
            if not vuids:
                return _NO_MATCH
            out.append((ku, vuids))
        return out

    @staticmethod
    def _group(series_tags: list[tuple[Series, dict]], sub: TSSubQuery):
        """Group-by bucketing (TsdbQuery.GroupByAndAggregateCB :981)."""
        group_tagks = sub.group_by_tags()
        if sub.aggregator == "none":
            # NONE: no aggregation, each series is its own group.
            return {("__series__", i): [st]
                    for i, st in enumerate(series_tags)}
        if not group_tagks:
            return {(): series_tags} if series_tags else {}
        groups: dict[tuple, list] = {}
        for series, tags in series_tags:
            key_vals = tuple(tags.get(k) for k in group_tagks)
            if any(v is None for v in key_vals):
                continue  # series lacks a group-by tag -> excluded
            groups.setdefault(key_vals, []).append((series, tags))
        return groups

    @staticmethod
    def _compute_tags(members: list[tuple[Series, dict]]):
        """SpanGroup.computeTags (:348): single-valued keys -> tags,
        conflicting keys -> aggregateTags."""
        from opentsdb_tpu.expression.series import compute_tags
        return compute_tags([tags for _, tags in members])

    # -- execution -------------------------------------------------------

    def _windows_for(self, sub: TSSubQuery, query: TSQuery):
        spec = sub.downsample_spec
        if spec is None:
            return None
        if spec.run_all:
            return AllWindow(query.start_time, query.end_time)
        if spec.use_calendar:
            edges = DT.calendar_window_edges(
                query.start_time, query.end_time, spec.calendar_interval,
                spec.calendar_unit, spec.timezone)
            return EdgeWindows(tuple(edges))
        return FixedWindows.for_range(query.start_time, query.end_time,
                                      spec.interval_ms)

    # -- rollup source selection (TsdbQuery.transformDownSamplerToRollupQuery
    #    :1733, ROLLUP_USAGE :197, SplitRollupQuery) ----------------------

    def _rollup_candidates(self, sub: TSSubQuery):
        """Rollup intervals able to serve this sub query, best first."""
        tsdb = self.tsdb
        ds = sub.downsample_spec
        usage = (sub.rollup_usage or "ROLLUP_NOFALLBACK").upper()
        if (tsdb.rollup_config is None or tsdb.rollup_store is None
                or ds is None or ds.run_all or ds.use_calendar
                or ds.interval_ms <= 0 or usage == "ROLLUP_RAW"
                or sub.tsuids):
            return [], usage
        if ds.function != "avg" and ds.function not in _ROLLUP_LANES:
            return [], usage
        try:
            matches = tsdb.rollup_config.get_best_matches_ms(ds.interval_ms)
        except (NoSuchRollupForInterval, ValueError):
            return [], usage
        matches = [m for m in matches if not m.default_interval]
        if not matches:
            return [], usage
        if usage == "ROLLUP_NOFALLBACK":
            matches = matches[:1]
        return matches, usage

    def _segment_for_interval(self, sub: TSSubQuery, interval,
                              start_ms: int, end_ms: int) -> Segment | None:
        """A rollup Segment over [start, end] if the lanes hold data."""
        tsdb = self.tsdb
        ds = sub.downsample_spec
        try:
            metric_uid = tsdb.metrics.get_id(sub.metric)
        except NoSuchUniqueName:
            return None
        pre = sub.pre_aggregate
        if ds.function == "avg":
            sum_lane = tsdb.rollup_store.peek_lane(interval.interval, "sum",
                                                   pre)
            cnt_lane = tsdb.rollup_store.peek_lane(interval.interval, "count",
                                                   pre)
            if (sum_lane is None or cnt_lane is None
                    or not sum_lane.series_for_metric(metric_uid)
                    or not cnt_lane.series_for_metric(metric_uid)):
                return None
            rq = RollupQuery(interval, "avg", ds.interval_ms, sub.aggregator)
            return Segment("rollup_avg", start_ms, end_ms, lane=sum_lane,
                           count_lane=cnt_lane, ds_function="sum",
                           rollup_query=rq)
        lane_agg, ds_fn = _ROLLUP_LANES[ds.function]
        lane = tsdb.rollup_store.peek_lane(interval.interval, lane_agg, pre)
        if lane is None or not lane.series_for_metric(metric_uid):
            return None
        rq = RollupQuery(interval, ds.function, ds.interval_ms,
                         sub.aggregator)
        return Segment("rollup", start_ms, end_ms, lane=lane,
                       ds_function=ds_fn, rollup_query=rq)

    def _plan_segments(self, query: TSQuery, sub: TSSubQuery) -> list[Segment]:
        start_ms, end_ms = query.start_time, query.end_time
        raw = Segment("raw", start_ms, end_ms)
        candidates, usage = self._rollup_candidates(sub)
        chosen = None
        for interval in candidates:
            chosen = self._segment_for_interval(sub, interval, start_ms,
                                                end_ms)
            if chosen is not None:
                break
        if chosen is None:
            if not candidates or usage == "ROLLUP_FALLBACK_RAW":
                return [raw]
            # NOFALLBACK/FALLBACK with empty rollup lanes -> empty result,
            # never a silent raw scan (ROLLUP_USAGE :197-201).
            return []
        rq = chosen.rollup_query
        tsdb = self.tsdb
        if (tsdb.config.get_bool("tsd.rollups.split_query.enable")
                and rq.rollup_interval.delay_sla_ms > 0):
            now_ms = DT.current_time_millis()
            boundary = rq.last_guaranteed_ms(now_ms)
            ds = sub.downsample_spec
            # Align down to the downsample grid so no window spans sources.
            boundary -= boundary % ds.interval_ms
            if boundary <= start_ms:
                return [raw]            # whole range is blacked out
            if boundary <= end_ms:
                chosen.end_ms = boundary - 1
                return [chosen,
                        Segment("raw", boundary, end_ms)]
        return [chosen]

    # -- segment execution ----------------------------------------------

    def _run_segment(self, query: TSQuery, sub: TSSubQuery, seg: Segment,
                     global_notes: list, budget) -> dict[tuple, QueryResult]:
        tsdb = self.tsdb
        if seg.kind == "raw":
            store = tsdb.store
            if sub.pre_aggregate and tsdb.rollup_store is not None:
                pre = tsdb.rollup_store.peek_lane("", sub.aggregator, True)
                store = pre if pre is not None else store
        else:
            store = seg.lane
        with obs_trace.stage("scan", kind=seg.kind) as sp:
            series_tags = self._resolve_series(sub, store)
            groups = self._group(series_tags, sub)
            obs_trace.annotate(sp, series=len(series_tags),
                               groups=len(groups))
        windows = self._windows_for(sub, query)
        if windows is not None:
            return self._run_segment_grouped(query, sub, seg, groups,
                                             windows, global_notes, budget,
                                             store)
        return self._run_segment_union(query, sub, seg, groups, global_notes,
                                       budget)

    def _assemble_result(self, query: TSQuery, sub: TSSubQuery, members,
                         dps, global_notes) -> QueryResult:
        tsdb = self.tsdb
        group_tags, agg_tags = self._compute_tags(members)
        tsuids = [tsdb.tsuid(s.key) for s, _ in members]
        annotations = []
        if not query.no_annotations:
            for t in tsuids:
                annotations.extend(tsdb.store.get_annotations(
                    t, query.start_time, query.end_time))
        return QueryResult(
            metric=sub.metric or (
                tsdb.metrics.get_name(members[0][0].key.metric)
                if members else ""),
            tags=group_tags,
            aggregate_tags=agg_tags,
            tsuids=tsuids,
            dps=dps,
            annotations=annotations,
            global_annotations=global_notes,
            index=sub.index,
        )

    def _run_segment_grouped(self, query: TSQuery, sub: TSSubQuery,
                             seg: Segment, groups, windows,
                             global_notes: list, budget,
                             store=None) -> dict[tuple, QueryResult]:
        """All group-by buckets in ONE device dispatch (downsample queries).

        Round 1 looped over buckets in Python — one jitted call per group,
        10k dispatches for BASELINE config 3.  Every bucket now travels in a
        single [S_total, N] batch with a group id per row; on a multi-device
        topology the batch rows are sharded over the mesh (the SaltScanner
        fan-out, TsdbQuery.java:981-1114 reduced to one shard_map call).
        """
        tsdb = self.tsdb
        ds = sub.downsample_spec

        fix = tsdb.config.fix_duplicates
        # Counts first (lock + binary search, no copy): budget charging and
        # the streaming decision must not force the whole range into host
        # memory — a 1B-pt query would otherwise materialize twice (full
        # window copies AND chunk buffers).
        kept = []  # (group_key, members, per-member point counts)
        for group_key in sorted(groups, key=lambda k: tuple(map(str, k))):
            members = groups[group_key]
            counts = [s.window_count(seg.start_ms, seg.end_ms, fix)
                      for s, _ in members]
            # No datapoints in range -> no SpanGroup at all (the scanner
            # returns no spans, TsdbQuery.findSpans -> empty group map).
            points = sum(counts)
            if points:
                budget.charge(points)
                kept.append((group_key, members, counts))
        if not kept:
            return {}
        budget.check_deadline()
        # one "pipeline" span covers batch build + the fused dispatch;
        # begin/end (not a with-block) keeps the 5-path dispatch chain
        # un-reindented, and an exception simply leaves the span
        # unfinished inside a request-scoped trace
        psp = obs_trace.begin("pipeline", aggregator=sub.aggregator,
                              downsample=seg.ds_function or ds.function)
        # snapshot the mode-policy epoch BEFORE the dispatch: if the
        # autotune loop flips a strategy (exploration start/end, live
        # install) while this query executes, the post-dispatch
        # decision recomputation would describe the NEW policy while
        # the kernel ran the old one — such entries are dropped from
        # the calibration ring (see _trace_pipeline_stages)
        from opentsdb_tpu.ops.downsample import mode_policy_epoch
        policy_epoch = mode_policy_epoch()
        # The window plan materializes ONLY after the budget accepted the
        # scan: EdgeWindows.split builds a [W+1] edge vector sized by the
        # query's range/interval (calendar grids over a year at fine
        # intervals run to millions of edges) — a query the budget
        # refuses, or one that matches no data at all, must never build
        # it.
        window_spec, wargs = windows.split()

        gid = np.concatenate([
            np.full(len(members), i, np.int64)
            for i, (_, members, _) in enumerate(kept)])
        g_pad = pad_pow2(len(kept))
        spec = PipelineSpec(
            aggregator=sub.aggregator,
            downsample=DownsampleStep(
                seg.ds_function or ds.function, window_spec,
                ds.fill_policy, ds.fill_value),
            rate=sub.rate_options if sub.rate else None,
            int_mode=False,
            # gid above is concatenated group runs — non-decreasing by
            # construction; lets sorted reduce modes skip the permute
            rows_sorted=True)

        total_points = sum(sum(c) for _, _, c in kept)
        ds_fn = seg.ds_function or ds.function
        sketchable, hazard = self._sketch_eligible(seg, ds_fn, windows,
                                                   kept, len(gid), fix)
        if hazard:
            self.exec_stats["sketchHazardExact"] = 1.0
        stream_ok = (seg.kind != "rollup_avg"
                     and (ds_fn in STREAMABLE_DS or sketchable))
        self._bump("pointsScanned", total_points)
        self._bump("seriesScanned", len(gid))
        mesh = tsdb.query_mesh()
        use_mesh = (mesh is not None and len(gid) >= tsdb.config.get_int(
            "tsd.query.mesh.min_series"))
        n_chips = 1
        if use_mesh:
            from opentsdb_tpu.parallel.sharded import n_devices
            n_chips = n_devices(mesh)
        series_list = [s for _, members, _ in kept for s, _t in members]
        # ONE routing verdict for the whole fast-path arbitration
        # (rollup lane -> tiled -> agg rewrite -> device cache ->
        # streamed/mesh/host-lane/resident), computed by the SAME pure
        # plan_decision() the EXPLAIN engine consults — eligibility
        # gates, consult ordering, the shared grid_budget guard, and
        # the path derivation live once (query/plandecision.py), so
        # /api/query/explain and the dispatch below cannot drift.  The
        # decision's stable fingerprint is stamped into the pipeline
        # span and the flight-recorder plan event.
        from opentsdb_tpu.ops.downsample import precompact_base
        from opentsdb_tpu.ops.hostlane import cpu_device, execution_platform
        from opentsdb_tpu.query import plandecision as pdn
        ts_base = precompact_base(
            window_spec, getattr(windows, "first_window_ms", None))
        n_max = max(max(c) for _, _, c in kept)
        batcher = getattr(tsdb, "dispatch_batcher", None)
        ctx = pdn.RouteContext(
            seg_kind=seg.kind, ds_fn=ds_fn, aggregator=sub.aggregator,
            has_rate=bool(sub.rate), s=len(gid), n_max=int(n_max),
            wp=window_spec.count, groups=len(kept), g_pad=g_pad,
            total_points=int(total_points), sketchable=sketchable,
            stream_ok=stream_ok, use_mesh=use_mesh, n_chips=n_chips,
            windows_fixed=isinstance(windows, FixedWindows),
            store_is_raw=store is tsdb.store,
            has_store=store is not None,
            platform=execution_platform(),
            cpu_lane_ok=cpu_device() is not None,
            state_mb=tsdb.config.get_int("tsd.query.streaming.state_mb"),
            point_threshold=tsdb.config.get_int(
                "tsd.query.streaming.point_threshold"),
            host_lane_max=tsdb.config.get_int(
                "tsd.query.host_lane.max_points"),
            ts_base=ts_base,
            batch_ok=batcher is not None and batcher.enabled,
            batch_factor=tsdb.config.get_float(
                "tsd.query.batch.amortize_factor"))
        pd = pdn.plan_decision(
            tsdb, ctx, _ExecConsults(tsdb, ctx, seg, sub, windows,
                                     store, series_list, fix))
        if pd.lane_note is not None:
            obs_trace.annotate(psp, rollup=pd.lane_note)
        if pd.agg_note is not None:
            obs_trace.annotate(psp, agg_cache=pd.agg_note)
        obs_trace.annotate(psp, fingerprint=pd.fingerprint)
        # phase boundary: scan + batch shaping + the routing verdict
        # all land in "plan"; the fingerprint keys this request's
        # latency-attribution profile (first segment wins)
        latattr.mark("plan")
        latattr.set_fingerprint(pd.fingerprint)
        if pd.path == "refused":
            # over-budget and untileable: the shared structured 413
            # (the span is left unfinished inside the request trace,
            # exactly as the pre-extraction code did)
            self.exec_stats["tiledRefused"] = 1.0
            raise pd.refusal.exception()
        lane_plan, tiled_plan = pd.lane_plan, pd.tiled_plan
        agg_plan, agg_note, cached = pd.agg_plan, pd.agg_note, pd.cached
        would_stream, host_small = pd.would_stream, pd.host_small
        if cached is not None:
            self.exec_stats["deviceCacheHit"] = 1.0
            if ts_base is not None:
                import jax.numpy as jnp
                wargs = dict(wargs)
                wargs["ts_base"] = jnp.asarray(ts_base, jnp.int64)
        if host_small:
            self.exec_stats["hostLane"] = 1.0
        from opentsdb_tpu.ops.hostlane import host_lane

        batch_info = None
        if lane_plan is not None:
            # Standing fast path: serve the downsample grid from the
            # rollup lane's mergeable partials (storage/rollup.py) —
            # the raw points are never fetched, never streamed.  Exact
            # by derivation; annotated on the span's `rollup` tag; the
            # calibration ring skips lane-served executions like
            # rewrites/tiled runs (the monolithic stage breakdown does
            # not describe them).
            out_ts, out_val, out_mask = self._run_lane_serve(
                spec, seg, lane_plan, series_list, gid, g_pad, windows,
                window_spec, budget, fix, psp)
            self.exec_stats["rollupLane"] = 1.0
            if lane_plan.striped:
                self.exec_stats["rollupLaneStriped"] = 1.0
        elif tiled_plan is not None:
            # Out-of-core: series-tiled streaming with partial-grid
            # spill, window-striped tail replay (ops/tiling.py).  The
            # decision + pool traffic ride the span's `tiling` tag; the
            # calibration ring skips tiled executions like rewrites
            # (the monolithic stage breakdown does not describe them).
            from opentsdb_tpu.ops import tiling
            (out_ts, out_val, out_mask), tile_stats = tiling.run_tiled(
                tsdb, spec, seg, series_list, gid, g_pad, window_spec,
                wargs, ds_fn, lanes_for([ds_fn]), sketchable, fix,
                tiled_plan, budget, store=store)
            obs_trace.annotate(psp, tiling=tile_stats)
            self.exec_stats["tiledExecution"] = 1.0
            self._bump("spillBytes", float(tile_stats["spillBytes"]))
            self._bump("tiledTiles", float(tile_stats["tiles"]))
        elif agg_plan is not None:
            out_ts, out_val, out_mask = self._run_agg_rewrite(
                spec, agg_plan, series_list, gid, g_pad, windows,
                window_spec, host_small, budget)
        elif pd.path == "batched":
            # Fused multi-query dispatch (query/batcher.py): this
            # dispatch-bound plan rendezvouses with concurrent
            # compatible plans and executes as one stacked [Q, S, N]
            # kernel with host-side unpack — the per-dispatch floor is
            # paid once per bucket instead of once per query.  The
            # calibration ring skips batched executions like rewrites/
            # tiled runs (a stacked launch's measured time describes
            # no single member), so the span carries the decisions
            # directly.
            from opentsdb_tpu.query.limits import active_deadline
            ts, val, mask, _ = build_batch_direct(
                series_list, seg.start_ms, seg.end_ms, fix)
            (out_ts, out_val, out_mask), batch_info = \
                tsdb.dispatch_batcher.submit(

                    spec, ts, val, mask, gid, g_pad, wargs,
                    host_small, policy_epoch,
                    deadline=active_deadline())
            obs_trace.annotate(psp, batch=batch_info,
                               costmodel=pd.decisions)
            self.exec_stats["batched"] = 1.0
            if batch_info["stacked"]:
                self.exec_stats["batchedStacked"] = 1.0
                self._bump("batchedQ", float(batch_info["q"]))
        elif cached is None and would_stream:
            # Beyond the threshold the batch never materializes: bounded
            # chunks are copied straight out of the store into the device
            # accumulator (SaltScanner overlap analog, VERDICT r1 #4).
            max_len = max(max(c) for _, _, c in kept)
            out_ts, out_val, out_mask = self._stream_grouped(
                spec, seg, series_list, max_len, gid, g_pad, window_spec,
                wargs, sketch=sketchable)
        elif seg.kind == "rollup_avg":
            all_windows = self._materialize_windows(kept, seg, fix)
            ts, val, mask, _ = build_batch(all_windows)
            cnt_windows = []
            for _, members, _ in kept:
                for s, _tags in members:
                    cs = seg.count_lane.get_series(s.key)
                    if cs is None:
                        cnt_windows.append(
                            (np.empty(0, np.int64), np.empty(0, np.float64),
                             np.empty(0, np.int64), np.empty(0, bool)))
                    else:
                        cnt_windows.append(cs.window(
                            seg.start_ms, seg.end_ms,
                            tsdb.config.fix_duplicates))
            tc, vc, mc, _ = build_batch(cnt_windows)
            with host_lane(host_small):
                out_ts, out_val, out_mask = run_group_rollup_avg_pipeline(
                    spec, ts, val, mask, tc, vc, mc, gid, g_pad, wargs)
        else:
            if cached is not None:
                ts, val, mask = cached
            else:
                # single-copy fill straight out of the store buffers
                # (build_batch_direct): a 1M-pt query's window()+pack
                # double copy was ~30% of the host-lane query time
                ts, val, mask, _ = build_batch_direct(
                    [s for _, members, _ in kept for s, _t in members],
                    seg.start_ms, seg.end_ms, fix)
            if use_mesh:
                from opentsdb_tpu.parallel import (
                    sharded_query_pipeline, shard_rows)
                from opentsdb_tpu.parallel.sharded import (
                    n_devices, shard_rows_device)
                self.exec_stats["meshDevices"] = float(n_devices(mesh))
                fn = sharded_query_pipeline(mesh, spec, g_pad)
                if cached is not None:
                    # cache hit under the mesh: re-lay the device batch
                    # out across the chips (ICI scatter) instead of a
                    # fresh host upload
                    d_ts, d_val, d_mask, d_gid = shard_rows_device(
                        mesh, ts, val, mask, gid, pad_gid_value=g_pad)
                else:
                    d_ts, d_val, d_mask, d_gid = shard_rows(
                        mesh, ts, val, mask, gid, pad_gid_value=g_pad)
                out_ts, out_val, out_mask = fn(d_ts, d_val, d_mask, d_gid,
                                               wargs)
            else:
                with host_lane(host_small):
                    out_ts, out_val, out_mask = run_group_pipeline(
                        spec, ts, val, mask, gid, g_pad, wargs)

        # the arm above returned (dispatch enqueued; results may still
        # be device-resident) — the true sync lands in device_wait at
        # the asarray boundary below
        latattr.mark("dispatch")
        if psp is not None:
            obs_trace.device_wait(psp, (out_ts, out_val, out_mask))
            if agg_plan is None and tiled_plan is None \
                    and lane_plan is None and pd.path != "batched":
                # rewritten, tiled, lane-served AND batched segments
                # skip the predicted-vs-actual ledger: the monolithic
                # stage breakdown does not describe a block-decomposed,
                # tiled, lane-derived, or stacked-multi-member
                # execution, and pairing its prediction with a partial
                # (or shared) actual would poison the calibration ring
                self._trace_pipeline_stages(
                    psp, sub, seg, len(gid),
                    max(max(c) for _, _, c in kept), window_spec.count,
                    len(kept), host_small, policy_epoch,
                    decisions=pd.decisions)
        obs_trace.end(psp)
        recorder = getattr(tsdb, "flightrec", None)
        if recorder is not None:
            # ONE flight-recorder event per executed pipeline: which
            # path served it and what the fast-path consults decided —
            # the retained form of the span annotations above, so a
            # post-mortem reads routing decisions without any client
            # having asked for showStats.  The fingerprint is the
            # explain-vs-actual parity handle (query/plandecision.py).
            fields = {"path": pd.path, "metric": sub.metric,
                      "series": len(gid), "windows": window_spec.count,
                      "groups": len(kept), "points": int(total_points),
                      "deviceCacheHit": cached is not None,
                      "fingerprint": pd.fingerprint}
            if tsdb.rollup_lanes is not None:
                fields["rollup"] = ("hit" if lane_plan is not None
                                    else "miss")
            if agg_note is not None:
                fields["aggCache"] = agg_note
            if batch_info is not None:
                fields["batch"] = batch_info
            recorder.record("plan", **fields)
        with obs_trace.stage("extract"):
            out_ts = np.asarray(out_ts)
            out_val = np.asarray(out_val)
            out_mask = np.asarray(out_mask)
            # device->host materialization is where an async dispatch
            # actually blocks (tracing syncs earlier via device_wait,
            # in which case this delta is ~0)
            latattr.mark("device_wait")
            results: dict[tuple, QueryResult] = {}
            for i, (group_key, members, _) in enumerate(kept):
                dps = extract_dps(out_ts, out_val[i], out_mask[i],
                                  seg.start_ms, seg.end_ms, False,
                                  keep_nans=sub.fill_policy != "none")
                results[tuple(map(str, group_key))] = \
                    self._assemble_result(query, sub, members, dps,
                                          global_notes)
        return results

    def _trace_pipeline_stages(self, span, sub: TSSubQuery, seg: Segment,
                               s: int, n: int, w: int, g: int,
                               host_small: bool = False,
                               policy_epoch: int | None = None,
                               decisions: dict | None = None) -> None:
        """Logical stage children of the fused dispatch span + the
        costmodel predicted-vs-actual ledger entry.

        XLA fuses downsample/rate/groupby/aggregate into one kernel, so
        per-stage device truth does not exist at runtime; the measured
        device wait is APPORTIONED across the stages by the calibrated
        costmodel's per-stage predictions and the children say so
        (`estimated` tag).  The span is also annotated with every
        kernel-axis strategy DECISION (chosen mode, per-candidate
        predicted ms, decision source — defaults / file calibration /
        live fitter), and the (shape, modes, feature vector, predicted,
        actual) tuple lands in obs.jaxprof's segment ring — the corpus
        the online calibrator (ops/calibrate.py) fits from."""
        from opentsdb_tpu.obs import jaxprof
        from opentsdb_tpu.obs.registry import REGISTRY
        from opentsdb_tpu.ops.hostlane import execution_platform
        ds = sub.downsample_spec
        ds_fn = seg.ds_function or (ds.function if ds is not None else None)
        # per-SEGMENT platform: the exec_stats hostLane flag is sticky
        # across a run's segments and would misattribute later
        # device-dispatched segments as cpu, poisoning the calibration
        # ring with cpu-predicted vs device-actual pairs
        platform = "cpu" if host_small else execution_platform()
        # DISPATCH shapes: build_batch pads the point axis to pow2 and
        # the group count dispatches as g_pad — the kernels' mode
        # choosers see the padded values, so the decision report and
        # the ring's feature vectors must too (n=1000 would report
        # 'flat' while the n=1024 kernel picked a sub-block form).
        # The streamed path still approximates: it dispatches chunk-
        # sized batches while one entry covers the whole range.
        n = pad_pow2(max(int(n), 1))
        g = pad_pow2(max(int(g), 1))
        if decisions is None:
            # direct callers without a PlanDecision in hand; the
            # grouped executor passes plan_decision()'s reports through
            # so the span, the fingerprint, and the calibration ring
            # all describe ONE recomputation
            decisions = jaxprof.segment_decisions(
                platform, s, n, w, g, ds_fn, aggregator=sub.aggregator)
        obs_trace.annotate(span, costmodel=decisions)
        for axis, report in decisions.items():
            if not report["feasible"]:
                # the kernels' feasibility guards make this unreachable;
                # a nonzero counter means a guard regressed and an
                # OOM-class mode is about to dispatch — chaos_soak
                # --autotune fails the run on it
                REGISTRY.counter(
                    "tsd.costmodel.infeasible",
                    "Strategy decisions outside the feasible candidate "
                    "set (must stay 0)").labels(axis=axis).inc()
        breakdown = jaxprof.stage_breakdown(platform, s, n, w, g, ds_fn,
                                            bool(sub.rate),
                                            decisions=decisions)
        total_pred = sum(breakdown.values()) or 1.0
        for stage_name in ("downsample", "rate", "groupby", "aggregate"):
            share = breakdown.get(stage_name)
            if share is None:
                continue
            child = span.child(stage_name, estimated=True)
            child.device_ms = round(span.device_ms * share / total_pred, 3)
            child.wall_ms = child.device_ms
        tr = obs_trace.active()
        if tr is None or not tr.device_time:
            # wall-only tracing: span.device_ms is 0 by CONFIG, not by
            # measurement — recording predicted>0/actual=0 pairs would
            # poison the calibration ring
            return
        from opentsdb_tpu.ops.downsample import mode_policy_epoch
        if policy_epoch is not None and policy_epoch \
                != mode_policy_epoch():
            # the mode policy flipped while this query executed
            # (autotune exploration/install): the decisions above
            # describe the NEW policy, the measured time came from the
            # OLD kernels — the pair would poison the fit.  The span
            # keeps its (best-effort) annotation; the ring skips it.
            obs_trace.annotate(span, costmodel_stale=True)
            return
        jaxprof.record_segment(
            seg.kind, s, n, w, g, sum(breakdown.values()), span.device_ms,
            platform=platform,
            modes={axis: r["mode"] for axis, r in decisions.items()},
            features=jaxprof.segment_features(platform, s, n, w, g,
                                              bool(sub.rate), decisions),
            aggregator=sub.aggregator)
        self._bump("deviceTimeMs", round(span.device_ms, 3))
        self._bump("costmodelPredictedMs",
                   round(sum(breakdown.values()) * 1e3, 3))

    @staticmethod
    def _host_window_ids(windows, tsb):
        """Window id per timestamp, host-side, for every window plan."""
        if isinstance(windows, FixedWindows):
            return (np.asarray(tsb, np.int64)
                    - windows.first_window_ms) // windows.interval_ms
        if isinstance(windows, EdgeWindows):
            return np.searchsorted(np.asarray(windows.edges, np.int64),
                                   tsb, "right") - 1
        return np.zeros(len(tsb), np.int64)    # AllWindow: one cell

    @staticmethod
    def _materialize_windows(kept, seg, fix):
        """Full window copies for the sub-threshold (one-batch) paths."""
        return [s.window(seg.start_ms, seg.end_ms, fix)
                for _, members, _ in kept for s, _t in members]

    @staticmethod
    def _materialize_agg_piece(v, m, count: int):
        """Host copies of one computed piece's [S, count] grid slice
        (`_materialize` prefix: this is a sanctioned device->host
        result materialization, like the extract stage's)."""
        return (np.asarray(v)[:, :count], np.asarray(m)[:, :count])

    def _run_agg_rewrite(self, spec, plan, series_list, gid, g_pad,
                         windows, window_spec, host_small, budget):
        """Execute a partial-aggregate rewrite (storage/agg_cache.py).

        Cached blocks replay their stored [S, B] downsample grids;
        uncovered pieces dispatch the SAME downsample-only program a
        cold run uses (run_downsample_grid) over exactly their
        sub-range, so a warm answer is bit-identical to a cold one by
        construction.  The assembled [S, W] grid then runs the shared
        tail (rate -> group -> aggregate) — the streaming executor's
        finish program — and freshly computed full blocks are stored
        back (generation-guarded: a dirty mark that landed since
        planning discards the insert)."""
        import jax.numpy as jnp
        from opentsdb_tpu.ops.downsample import mode_policy_epoch
        from opentsdb_tpu.ops.hostlane import host_lane
        from opentsdb_tpu.ops.pipeline import (
            DownsampleStep, build_batch_direct, run_downsample_grid,
            run_grid_tail)
        tsdb = self.tsdb
        fix = tsdb.config.fix_duplicates
        step0 = spec.downsample
        epoch = mode_policy_epoch()
        interval = windows.interval_ms
        s = len(series_list)
        pieces_v: list = []
        pieces_m: list = []
        with host_lane(host_small):
            for piece in plan.pieces:
                if piece.cached is not None:
                    # cached entries hold their FULL row set; narrow
                    # to this query's rows unless they already match
                    # (the exact-repeat hot path serves zero-copy)
                    v, m = piece.cached
                    rows = piece.rows
                    identity = (v.shape[0] == len(rows)
                                and np.array_equal(
                                    rows, np.arange(len(rows))))
                    if not identity and piece.tier == "agg_device":
                        rdev = jnp.asarray(rows)
                        v = jnp.take(v, rdev, axis=0)
                        m = jnp.take(m, rdev, axis=0)
                    elif not identity:
                        v = v[rows]
                        m = m[rows]
                    pieces_v.append(v)
                    pieces_m.append(m)
                    self._bump("aggCacheHitWindows", piece.count)
                    continue
                budget.check_deadline()
                # delta fetch composes with the device series cache:
                # pinned HBM columns serve the piece's [S, n] batch as
                # an on-device gather (zero host copy); cold/stale
                # falls back to the host build.  Either source hands
                # the SAME values at the same pow2-padded shape to the
                # same program, so the block's bits do not depend on
                # which one answered.
                batch = None
                if tsdb.device_cache is not None:
                    batch = tsdb.device_cache.batch_for(
                        plan.store, plan.metric, series_list,
                        piece.fetch_lo, piece.fetch_hi, fix,
                        build=False)
                if batch is not None:
                    ts, val, mask = batch
                else:
                    ts, val, mask, _ = build_batch_direct(
                        series_list, piece.fetch_lo, piece.fetch_hi,
                        fix)
                sub_win = FixedWindows(interval, piece.first_ms,
                                       piece.count)
                wspec, wargs = sub_win.split()
                sub_step = DownsampleStep(step0.function, wspec,
                                          step0.fill_policy,
                                          step0.fill_value)
                _wts, v, m = run_downsample_grid(sub_step, ts, val,
                                                 mask, wargs)
                self._bump("aggCacheComputedWindows", piece.count)
                if piece.block is not None:
                    vn, mn = self._materialize_agg_piece(v, m,
                                                         piece.count)
                    tsdb.agg_cache.store_block(plan, piece,
                                               series_list, vn, mn,
                                               epoch)
                # edge pieces stay padded here; the host assembly
                # slices to piece.count after materializing (an eager
                # jnp slice would dispatch — and recompile — per call)
                pieces_v.append(v)
                pieces_m.append(m)
            w = windows.count
            wp = window_spec.count
            # Device concatenation only for the all-cached all-device
            # repeat (stable piece shapes -> the concat compiles once
            # per family).  Everything else assembles on the HOST:
            # sliding windows change the edge pieces' shapes every
            # refresh, and a jnp.concatenate would recompile per
            # distinct shape combination (measured ~0.5s/slide) while
            # np writes cost microseconds; the grid upload itself is
            # [S, Wp] — tiny next to the point data the cache avoids.
            device_ok = all(p.cached is not None
                            and p.tier == "agg_device"
                            for p in plan.pieces)
            if device_ok:
                pad = [jnp.zeros((s, wp - w), jnp.float64)] \
                    if wp > w else []
                mpad = [jnp.zeros((s, wp - w), bool)] if wp > w else []
                v_full = jnp.concatenate(pieces_v + pad, axis=1)
                m_full = jnp.concatenate(pieces_m + mpad, axis=1)
            else:
                v_full = np.zeros((s, wp), np.float64)
                m_full = np.zeros((s, wp), bool)
                col = 0
                for v, m, piece in zip(pieces_v, pieces_m, plan.pieces):
                    v_full[:, col:col + piece.count], \
                        m_full[:, col:col + piece.count] = \
                        self._materialize_agg_piece(v, m, piece.count)
                    col += piece.count
            # the monolithic grid's timestamps: first + i * interval
            # over the padded window count, int64 (window_timestamps)
            wts = (windows.first_window_ms
                   + np.arange(wp, dtype=np.int64) * interval)
            out = run_grid_tail(spec, jnp.asarray(wts), v_full, m_full,
                                jnp.asarray(gid), g_pad)
        if plan.cached_windows:
            self.exec_stats["aggCacheHit"] = 1.0
        return out

    def _sketch_eligible(self, seg: Segment, ds_fn: str, windows, kept,
                         n_rows: int, fix: bool) -> tuple[bool, bool]:
        """(sketchable, hazard_fallback) for one grouped segment —
        shared by the executor and the explain engine (read-only store
        walk, no dispatch).

        Auto-protect (VERDICT r3 #7): a (series, window) cell drifts
        ~merges/(2K) of its population in rank; when the densest cell
        would absorb more chunk merges than the configured bound
        (window span >> chunk span — the "0all over a year" shape),
        fall back to the exact path, which the scan budgets either
        serve materialized or refuse with the 413 contract.  The
        estimate is skew-exact (review r4): per series, the window ids
        of the streaming CHUNK BOUNDARIES (every n_chunk-th point,
        O(points/chunk) to fetch) are counted — a cell's merge count
        is that window's boundary multiplicity + 1, so points
        concentrated in one window are seen as the many merges they
        cause, not averaged away."""
        tsdb = self.tsdb
        sketchable = (is_sketch_ds(ds_fn) and tsdb.config.get_bool(
            "tsd.query.streaming.sketch_percentiles"))
        if not sketchable:
            return False, False
        max_merges = tsdb.config.get_int(
            "tsd.query.streaming.sketch_max_merges")
        if max_merges <= 0:
            return True, False
        chunk_points = max(tsdb.config.get_int(
            "tsd.query.streaming.chunk_points"), 1)
        n_chunk = pad_pow2(max(1024, chunk_points // max(n_rows, 1)))
        worst = 0
        for _, members, counts in kept:
            for (s, _t), c in zip(members, counts):
                if c <= n_chunk:
                    continue        # single chunk: no merges at all
                tsb = s.window_stride_timestamps(
                    seg.start_ms, seg.end_ms, n_chunk, fix)
                wids = self._host_window_ids(windows, tsb)
                if len(wids):
                    worst = max(worst, int(np.max(
                        np.unique(wids, return_counts=True)[1])))
        if worst + 1 > max_merges:
            return False, True
        return True, False

    def _run_lane_serve(self, spec, seg, plan, series_list, gid,
                        g_pad: int, windows, window_spec,
                        budget, fix: bool, psp):
        """Serve a lane-derivable plan from materialized rollup lanes.

        Interior full windows re-reduce from the lane's mergeable
        partials (storage/rollup.py derive_grid — exact; bitwise vs
        the raw kernel on integer data); the <= 2 edge windows with
        partial point populations recompute from raw points via the
        SAME downsample-only program the agg cache's delta pieces use;
        the assembled [S, Wp] grid runs the shared tail.  Over-budget
        grids reuse the PR 10 spill pool's window-striped tail replay
        with lane-derived tile grids (run_tiled tile_grid_fn)."""
        import jax.numpy as jnp
        from opentsdb_tpu.ops.downsample import (FILL_NONE, FILL_SCALAR,
                                                 FILL_ZERO)
        from opentsdb_tpu.ops.hostlane import cpu_device, host_lane
        from opentsdb_tpu.ops.pipeline import (
            DownsampleStep, build_batch_direct, run_downsample_grid,
            run_grid_tail)
        tsdb = self.tsdb
        ds_step = spec.downsample
        ds_fn = ds_step.function
        interval = windows.interval_ms
        first = windows.first_window_ms
        w = windows.count
        wp = window_spec.count
        s = len(series_list)
        # windows the lane cannot serve: the <= 2 partial edge windows
        edges = []
        if plan.wf_lo > 0:
            edges.append((0, seg.start_ms,
                          min(first + interval - 1, seg.end_ms)))
        if plan.wf_hi < w - 1:
            edges.append((w - 1, first + (w - 1) * interval,
                          seg.end_ms))
        # the grid's padded-column content under this fill policy,
        # mirroring apply_fill over non-live windows (values under a
        # False mask are never consumed; matching them keeps the grid
        # byte-comparable to the monolithic one)
        if ds_step.fill_policy == FILL_NONE:
            pad_val = np.nan
        elif ds_step.fill_policy == FILL_ZERO:
            pad_val = 0.0
        elif ds_step.fill_policy == FILL_SCALAR:
            pad_val = float(ds_step.fill_value)
        else:
            pad_val = np.nan

        def edge_cols(row_lo: int, row_hi: int):
            """[(window idx, vals[rows, 1], mask[rows, 1])] computed
            fresh from raw points — identical program to a cold run's."""
            out = []
            for (w_i, lo_ms, hi_ms) in edges:
                ts, val, mask, _ = build_batch_direct(
                    series_list[row_lo:row_hi], lo_ms, hi_ms, fix)
                sub_win = FixedWindows(interval,
                                       first + w_i * interval, 1)
                wspec2, wargs2 = sub_win.split()
                sub_step = DownsampleStep(ds_fn, wspec2,
                                          ds_step.fill_policy,
                                          ds_step.fill_value)
                _wt, v, m = run_downsample_grid(sub_step, ts, val,
                                                mask, wargs2)
                out.append((w_i, np.asarray(v)[:, :1],
                            np.asarray(m)[:, :1]))
            return out

        def assemble(row_lo: int, row_hi: int):
            rows = row_hi - row_lo
            v = np.full((rows, wp), pad_val, np.float64)
            m = np.zeros((rows, wp), bool)
            iv, im = tsdb.rollup_lanes.derive_grid(
                plan, ds_fn, ds_step.fill_policy, ds_step.fill_value,
                row_lo, row_hi)
            v[:, plan.wf_lo:plan.wf_hi + 1] = iv
            m[:, plan.wf_lo:plan.wf_hi + 1] = im
            for (w_i, ev, em) in edge_cols(row_lo, row_hi):
                v[:, w_i:w_i + 1] = ev
                m[:, w_i:w_i + 1] = em
            return v, m

        wts = first + np.arange(wp, dtype=np.int64) * interval
        budget.check_deadline()
        if not plan.striped:
            # small-grid fast lane: the serve's work is the [S, Wp]
            # grid (the raw points are never touched), so host-lane
            # eligibility keys on CELLS against the same threshold
            # the point-count paths use
            lane_host_small = (cpu_device() is not None
                               and 0 < s * wp <= tsdb.config.get_int(
                                   "tsd.query.host_lane.max_points"))
            with host_lane(lane_host_small):
                v_full, m_full = assemble(0, s)
                out = run_grid_tail(spec, jnp.asarray(wts), v_full,
                                    m_full, jnp.asarray(gid), g_pad)
            if lane_host_small:
                self.exec_stats["hostLane"] = 1.0
            return out
        # over-budget: the full [S, Wp] grid never goes to the device.
        # Moment-decomposable cross-series aggregators FOLD tile by
        # tile — each [S_tile, Wp] lane grid runs the row-local
        # contribution step + a straight-to-[G, W] partial reduce on
        # device, partials merge by +/min/max/| on the host, and one
        # finish reproduces moment_group_reduce's arithmetic on
        # identical operands (the mesh's combine_* decomposition
        # applied to tiles).  Everything else (dev, rank/order aggs)
        # keeps the PR 10 spill pool's window-striped tail replay:
        # contributions are row-local over the FULL width, so tiles
        # compute them on [S_tile, Wp] grids and the pool re-orders
        # their stripes for the window-local tail.
        from opentsdb_tpu.ops import tiling
        tp = plan.tile_plan
        agg_name = spec.aggregator
        # one HOST assembly feeds every striped mode: lane cells are
        # host-resident anyway, and [S, Wp] at 9 B/cell is smaller
        # than the lane partials backing it (28 B/cell)
        v_full, m_full = assemble(0, s)
        gid_np = np.asarray(gid, np.int64)
        extreme = agg_name in ("min", "mimmin", "max", "mimmax")
        foldable = agg_name in tiling.LANE_FOLDABLE
        # the device fold holds one tile's grid AND the [G, W]
        # partial-moment outputs on device — it sizes its OWN tiles
        # against what the budget leaves after the partials (the
        # replay path's tile sizing reserves stripe space instead).
        # The host-dense fold below holds NOTHING on device (pure
        # numpy) and needs no budget at all.
        budget_bytes = self.tsdb.config.get_int(
            "tsd.query.streaming.state_mb") * 2 ** 20
        fold_rows = (budget_bytes - 3 * g_pad * wp * 8) // (wp * 19)
        fold_dev_ok = foldable and fold_rows >= 1
        if foldable and spec.rate is None \
                and bool(np.all(m_full[:, :w])):
            # DENSE rate-free grid (every interior cell populated —
            # the regular-cadence common case): grid_contributions is
            # the identity (contrib == values, participate == mask,
            # exactly — its own lax.cond fast lane) and there is no
            # rate pass, so the per-tile device work degenerates to
            # group-partial sums the host computes directly at memcpy
            # speed.  Rate queries take the device fold below, whose
            # _tile_contrib applies rate row-locally per tile.
            # Arithmetic mirrors moment_group_reduce's finish on
            # identical operands — bit-identical on integer data; gid
            # is non-decreasing group runs (rows_sorted), so reduceat
            # folds each run.
            ok = m_full & ~np.isnan(v_full)
            starts = np.flatnonzero(np.diff(gid_np, prepend=-1))
            kg = len(starts)
            cnt = np.zeros((g_pad, wp), np.int64)
            present = np.zeros((g_pad, wp), np.int64)
            cnt[:kg] = np.add.reduceat(ok.astype(np.int64), starts,
                                       axis=0)
            present[:kg] = np.add.reduceat(m_full.astype(np.int64),
                                           starts, axis=0)
            if extreme:
                want_min = agg_name in ("min", "mimmin")
                ident = np.inf if want_min else -np.inf
                red = np.minimum.reduceat if want_min \
                    else np.maximum.reduceat
                out_val = np.full((g_pad, wp), ident, np.float64)
                out_val[:kg] = red(np.where(ok, v_full, ident),
                                   starts, axis=0)
            elif agg_name == "count":
                out_val = cnt.astype(np.float64)
            elif agg_name == "avg":
                tot = np.zeros((g_pad, wp), np.float64)
                tot[:kg] = np.add.reduceat(
                    np.where(ok, v_full, 0.0), starts, axis=0)
                out_val = tot / np.maximum(cnt, 1)
            else:
                out_val = np.zeros((g_pad, wp), np.float64)
                out_val[:kg] = np.add.reduceat(
                    np.where(ok, v_full, 0.0), starts, axis=0)
            if agg_name != "count":
                out_val = np.where(cnt > 0, out_val, np.nan)
            obs_trace.annotate(psp, rollup_fold="host_dense")
            return wts, out_val, present > 0
        if fold_dev_ok:
            # holes in the grid: interpolation/participation must run
            # (row-local, full-width) — fold tile by tile on device
            # into [G, W] partial moments (the mesh's combine_*
            # decomposition applied to tiles); merged partials finish
            # with moment_group_reduce's arithmetic
            cnt = np.zeros((g_pad, wp), np.int64)
            present = np.zeros((g_pad, wp), np.int64)
            tot = np.zeros((g_pad, wp), np.float64)
            lo_acc = np.full((g_pad, wp), np.inf, np.float64)
            hi_acc = np.full((g_pad, wp), -np.inf, np.float64)
            wts_dev = jnp.asarray(wts)
            fold_rows = min(int(fold_rows), s)
            for t_lo in range(0, s, fold_rows):
                t_hi = min(t_lo + fold_rows, s)
                budget.check_deadline()
                parts = tiling.run_lane_fold(
                    spec, g_pad, extreme, wts_dev,
                    v_full[t_lo:t_hi], m_full[t_lo:t_hi],
                    jnp.asarray(gid_np[t_lo:t_hi]))
                if extreme:
                    plo, phi, pc, pp = (np.asarray(a) for a in parts)
                    lo_acc = np.minimum(lo_acc, plo)
                    hi_acc = np.maximum(hi_acc, phi)
                else:
                    pt, pc, pp = (np.asarray(a) for a in parts)
                    tot += pt
                cnt += pc
                present += pp
            safe = np.maximum(cnt, 1)
            if extreme:
                out_val = lo_acc if agg_name in ("min", "mimmin") \
                    else hi_acc
            elif agg_name == "count":
                out_val = cnt.astype(np.float64)
            elif agg_name == "avg":
                out_val = tot / safe
            else:
                out_val = tot
            if agg_name != "count":
                out_val = np.where(cnt > 0, out_val, np.nan)
            obs_trace.annotate(psp, rollup_fold=True)
            return wts, out_val, present > 0

        def tile_grid(row_lo: int, row_hi: int):
            return (wts, v_full[row_lo:row_hi], m_full[row_lo:row_hi])

        (out_ts, out_val, out_mask), tile_stats = tiling.run_tiled(
            tsdb, spec, seg, series_list, gid, g_pad, window_spec,
            {}, ds_fn, (), False, fix, plan.tile_plan, budget,
            store=tsdb.store, tile_grid_fn=tile_grid)
        obs_trace.annotate(psp, tiling=tile_stats)
        self._bump("spillBytes", float(tile_stats["spillBytes"]))
        return out_ts, out_val, out_mask

    def _stream_grouped(self, spec: PipelineSpec, seg, series_list,
                        max_len: int, gid, g_pad: int, window_spec, wargs,
                        sketch: bool = False):
        """Chunked execution: fold bounded [S, n] slices into the device
        accumulator, then run the shared grid tail.

        Chunks are per-series point-index ranges (each series' own chunks
        are time-ordered, which is all the associative moment merge needs),
        so every chunk has the same [S, n_chunk] shape — one compile.  The
        host packs chunk k+1 while the device reduces chunk k (JAX async
        dispatch = the ScannerCB overlap, SaltScanner.java:463).

        Each chunk is copied straight out of the store (window_chunk) —
        the full range is NEVER materialized on the host, so host RAM
        stays O(store + chunk).  Like the reference's scanner over live
        HBase rows, the pass has no snapshot isolation: writes landing
        mid-query may or may not be seen (SaltScanner.java:269).
        """
        import jax.numpy as jnp
        tsdb = self.tsdb
        fix = tsdb.config.fix_duplicates
        s = len(series_list)
        chunk_points = max(tsdb.config.get_int(
            "tsd.query.streaming.chunk_points"), 1)
        n_chunk = pad_pow2(max(1024, chunk_points // max(s, 1)))

        # Streaming composes with the mesh (VERDICT r2 missing #3): beyond-
        # memory queries shard the accumulator rows over every chip, so the
        # per-chip footprint is O(S/n_chips * W + chunk) and the finish
        # combines over ICI — concurrent salt buckets × incremental
        # callbacks (SaltScanner.java:269 × :463) in one composition.
        lanes = lanes_for([spec.downsample.function])
        mesh = tsdb.query_mesh()
        use_sharded = (mesh is not None and s >= tsdb.config.get_int(
            "tsd.query.mesh.min_series"))
        # The accumulator grid is O(S x W x lane bytes): a fine downsample
        # over a huge range (10s windows x a year -> millions of windows)
        # would OOM the device mid-query.  The caller already routed
        # over-budget plans to the tiled executor (or raised); this
        # re-check through the SAME shared guard is defense in depth
        # for direct callers.  The limit is PER CHIP: the sharded path
        # splits rows over the mesh, so its estimate divides by the
        # device count.  The sketch lane dominates when present (K
        # float32 summary points + the count lane per cell).
        from opentsdb_tpu.ops.streaming import SKETCH_K
        from opentsdb_tpu.query.limits import grid_budget
        per_cell = 8 + 8 * len(lanes) + (4 * SKETCH_K if sketch else 0)
        n_chips = 1
        if use_sharded:
            from opentsdb_tpu.parallel.sharded import n_devices
            n_chips = n_devices(mesh)
        gbd = grid_budget(
            "streaming",
            tsdb.config.get_int("tsd.query.streaming.state_mb"),
            s * window_spec.count * per_cell // n_chips,
            s, window_spec.count, sketch=sketch)
        if gbd.over:
            raise gbd.exception()
        # Both accumulators are created AFTER the first chunk is packed:
        # its observed window span sizes the sliced-update window
        # (wider-than-data grids fold each chunk into an O(S*wc) state
        # slice instead of touching the whole [S, W] grid — the r04b
        # chip session measured 4.7s/chunk on config 2's 721k-window
        # grid with full-grid folds; the sharded form slices each chip's
        # [S_local, W] state the same way).
        acc = None          # StreamAccumulator | ShardedStreamAccumulator
        if use_sharded:
            from opentsdb_tpu.parallel.sharded import (n_devices,
                                                       padded_rows)
            s_rows = padded_rows(mesh, s)    # pack padded: no re-copy
            self.exec_stats["meshDevices"] = float(n_devices(mesh))
        else:
            s_rows = s

        def make_acc(wslice):
            if use_sharded:
                from opentsdb_tpu.parallel import ShardedStreamAccumulator
                return ShardedStreamAccumulator(
                    mesh, s, window_spec, wargs, sketch=sketch,
                    lanes=lanes, window_slice=wslice)
            return StreamAccumulator.create(
                s, window_spec, wargs, sketch=sketch, lanes=lanes,
                window_slice=wslice)

        # timestamp cursors, not index offsets: monotone progression means
        # no pre-existing point is ever streamed twice even when an out-of-
        # order write shifts buffer positions mid-query (see window_chunk)
        cursors: list[int | None] = [None] * s
        n_chunks_total = -(-max_len // n_chunk)
        self._bump("streamedChunks", n_chunks_total)
        use_slice = window_spec.kind == "fixed"
        first_ms = int(np.asarray(wargs["first"])) if use_slice else 0
        interval = window_spec.interval_ms
        for chunk_i in range(n_chunks_total):
            ts = np.full((s_rows, n_chunk), PAD_TS, np.int64)
            val = np.zeros((s_rows, n_chunk), np.float64)
            mask = np.zeros((s_rows, n_chunk), bool)
            tmin = tmax = None
            for i, series in enumerate(series_list):
                t, fv = series.window_chunk(seg.start_ms, seg.end_ms,
                                            cursors[i], n_chunk, fix)
                m = len(t)
                if m:
                    ts[i, :m] = t
                    val[i, :m] = fv
                    mask[i, :m] = True
                    cursors[i] = int(t[-1])
                    tmin = int(t[0]) if tmin is None else min(tmin,
                                                              int(t[0]))
                    tmax = int(t[-1]) if tmax is None else max(tmax,
                                                               int(t[-1]))
            if tmin is None:
                # a pointless chunk folds nothing: skip it — and, when
                # the accumulator doesn't exist yet, WITHOUT creating
                # it, so the window_slice sizing below sees the first
                # chunk that actually has points (ADVICE r4: an empty
                # first chunk used to pin window_slice=None and every
                # later chunk paid the full-grid O(S*W) fold)
                continue
            if acc is None:
                wslice = None
                if use_slice:
                    # 2x the first chunk's span: headroom for later
                    # chunks (series advance on their own cursors, so
                    # spans vary); a chunk that still overflows just
                    # takes the full-grid fold below
                    wslice = 2 * ((tmax - tmin) // interval + 2)
                acc = make_acc(wslice)
            w0 = None
            if acc.window_slice is not None and tmin is not None \
                    and (tmax - tmin) // interval + 2 <= acc.window_slice:
                w0 = (tmin - first_ms) // interval
            if use_sharded:
                acc.update(ts, val, mask, w0=w0)
            else:
                acc.update(jnp.asarray(ts), jnp.asarray(val),
                           jnp.asarray(mask), w0=w0)
            if (chunk_i + 1) % 16 == 0:
                # Backpressure: updates enqueue asynchronously, and a long
                # scan would otherwise stage hundreds of chunk transfers
                # (GBs) ahead of the device.  Fetching one scalar of the
                # accumulator state drains the queue to this point
                # (block_until_ready does not wait on the axon tunnel);
                # cadence 16 keeps the double-buffering overlap.
                np.asarray(acc.state["n"][:1, :1])

        if acc is None:     # zero chunks (empty range): empty state
            acc = make_acc(None)
        if acc.oob_count():
            # w0 = floor((chunk_min - first)/interval) with wc >= the
            # chunk's span makes this impossible; a nonzero count means
            # dropped points, never serve a wrong answer
            raise RuntimeError(
                "internal: %d points fell outside their declared "
                "streaming window slice" % acc.oob_count())
        if use_sharded:
            return acc.finish_tail(spec, gid, g_pad)
        step = spec.downsample
        wts, v, m = acc.finish(step.function, step.fill_policy,
                               step.fill_value)
        return run_grid_tail(spec, wts, v, m, jnp.asarray(gid), g_pad)

    # Cap on groups fused into one batched union dispatch (the tile
    # budget divides by the batch size, so bigger fusions trade tile
    # granularity for dispatch count).
    _UNION_BATCH_MAX = 64

    def _run_segment_union(self, query: TSQuery, sub: TSSubQuery,
                           seg: Segment, groups, global_notes: list,
                           budget) -> dict[tuple, QueryResult]:
        """Union-timestamp aggregation (no downsample step).

        Union timestamps differ per bucket (AggregationIterator semantics
        at the union of member timestamps, with int_mode preserving Java
        long arithmetic), but groups whose padded [S, N] batch shapes
        match fuse into ONE vmapped dispatch — a 10k-host fleet of
        same-cadence series answers in a handful of dispatches instead of
        10k (round 1's per-group loop, the last per-group dispatch path).
        """
        from opentsdb_tpu.ops.hostlane import cpu_device, host_lane
        from opentsdb_tpu.ops.union_agg import _UNION_TILE_CELLS

        tsdb = self.tsdb
        fix = tsdb.config.fix_duplicates
        results: dict[tuple, QueryResult] = {}
        host_max = tsdb.config.get_int("tsd.query.host_lane.max_points")

        def flush(int_mode: bool, chunk: list) -> None:
            """Dispatch up to _UNION_BATCH_MAX same-shaped groups and
            assemble their results (releases the held batches)."""
            psp = obs_trace.begin("pipeline", aggregator=sub.aggregator,
                                  union=True, groups=len(chunk))
            # fast lane per dispatch: the flush's real point count is the
            # summed mask (padding excluded)
            host_small = (host_max > 0 and cpu_device() is not None
                          and sum(int(c[4].sum()) for c in chunk)
                          <= host_max)
            if host_small:
                self.exec_stats["hostLane"] = 1.0
            spec = PipelineSpec(
                aggregator=sub.aggregator,
                downsample=None,
                rate=sub.rate_options if sub.rate else None,
                int_mode=int_mode)
            if len(chunk) == 1:
                _, _, ts, val, mask = chunk[0]
                with host_lane(host_small):
                    outs = [run_pipeline(spec, ts, val, mask, None)]
            else:
                bspec = PipelineSpec(
                    aggregator=spec.aggregator, downsample=None,
                    rate=spec.rate, int_mode=int_mode,
                    tile_cells=max(_UNION_TILE_CELLS // len(chunk), 1))
                with host_lane(host_small):
                    bt, bv, bm = run_union_batch_pipeline(
                        bspec,
                        np.stack([c[2] for c in chunk]),
                        np.stack([c[3] for c in chunk]),
                        np.stack([c[4] for c in chunk]))
                bt, bv, bm = (np.asarray(bt), np.asarray(bv),
                              np.asarray(bm))
                outs = [(bt[i], bv[i], bm[i]) for i in range(len(chunk))]
            if psp is not None:
                obs_trace.device_wait(psp, outs)
                # the union pipeline is one fused aggregate (+rate)
                # kernel — a single estimated child, full device share
                child = psp.child("aggregate", estimated=True)
                child.device_ms = round(psp.device_ms, 3)
                child.wall_ms = child.device_ms
            obs_trace.end(psp)
            for (group_key, members, *_), (o_ts, o_val, o_mask) \
                    in zip(chunk, outs):
                dps = extract_dps(np.asarray(o_ts), np.asarray(o_val),
                                  np.asarray(o_mask), seg.start_ms,
                                  seg.end_ms,
                                  int_mode and not sub.rate,
                                  keep_nans=sub.fill_policy != "none")
                results[tuple(map(str, group_key))] = \
                    self._assemble_result(query, sub, members, dps,
                                          global_notes)

        # materialize + budget-charge per group, bucketing by the shape
        # class (padded dims + int_mode) one dispatch can serve; full
        # buckets flush IMMEDIATELY so host memory holds at most
        # _UNION_BATCH_MAX batches per shape class (not the whole fleet)
        # and the deadline keeps interleaving with the dispatches.
        buckets: dict = {}
        for group_key in sorted(groups, key=lambda k: tuple(map(str, k))):
            members = groups[group_key]
            batch_windows = [
                s.window(seg.start_ms, seg.end_ms, fix)
                for s, _ in members]
            points = sum(len(w[0]) for w in batch_windows)
            if not points:
                continue
            budget.charge(points)
            budget.check_deadline()
            ts, val, mask, all_int = build_batch(batch_windows)
            int_mode = all_int and seg.kind == "raw"
            key = (ts.shape, int_mode)
            bucket = buckets.setdefault(key, [])
            bucket.append((group_key, members, ts, val, mask))
            if len(bucket) >= self._UNION_BATCH_MAX:
                flush(int_mode, buckets.pop(key))
                budget.check_deadline()
        for (_, int_mode), chunk in buckets.items():
            flush(int_mode, chunk)
            budget.check_deadline()
        return results

    # -- histogram queries (TsdbQuery.isHistogramQuery :806-812 routes
    #    percentiles/show_histogram_buckets to runHistogramAsync :788) ----

    def _run_histogram_sub(self, query: TSQuery, sub: TSSubQuery,
                           budget=None) -> list[QueryResult]:
        from opentsdb_tpu.histogram.kernels import (accumulate_rows,
                                                    percentile_rows)
        from opentsdb_tpu.histogram.store import assemble_columnar
        from opentsdb_tpu.ops.hostlane import cpu_device, host_lane
        tsdb = self.tsdb
        if tsdb.histogram_store is None:
            raise ValueError("histograms are not configured "
                             "(tsd.core.histograms.config)")
        metric_uid = tsdb.metrics.get_id(sub.metric)
        filter_tagks = {f.tagk for f in sub.filters}
        matched = []
        for series in tsdb.histogram_store.series_for_metric(metric_uid):
            tags = tsdb.resolve_key_tags(series.key)
            if sub.explicit_tags and set(tags) != filter_tagks:
                continue
            if all(f.match(tags) for f in sub.filters):
                matched.append((series, tags))
        groups = self._group(matched, sub)
        interval_ms = (sub.downsample_spec.interval_ms
                       if sub.downsample_spec is not None else 0)
        ordered = [(gk, [s for s, _ in groups[gk]]) for gk in
                   sorted(groups, key=lambda k: tuple(map(str, k)))]
        results: list[QueryResult] = []
        # budget/deadline BEFORE any assembly work, like the scalar path
        # (the limit must bound work done, review r4)
        total_points = 0
        for _, members in ordered:
            pts = sum(s.count_in_range(query.start_time, query.end_time)
                      for s in members)
            if pts and budget is not None:
                budget.charge(pts)
                budget.check_deadline()
            total_points += pts
        if not total_points:
            return results
        batch = assemble_columnar(ordered, query.start_time,
                                  query.end_time, interval_ms)
        if batch is None:
            return results
        # grid budget: rows x buckets cells of int64 must fit the same
        # device-state allowance the scalar paths honor (shared guard;
        # histograms never tile — the bucket scatter is one dispatch)
        from opentsdb_tpu.query.limits import grid_budget
        gbd = grid_budget(
            "histogram",
            tsdb.config.get_int("tsd.query.streaming.state_mb"),
            batch["n_rows"] * batch["n_buckets"] * 8,
            batch["n_rows"], batch["n_buckets"])
        if gbd.over:
            raise gbd.exception()

        # ONE dispatch for every group (VERDICT r3 #4): scatter entries
        # onto the [rows, B] grid, percentile-extract on device.  Small
        # queries take the host lane like the scalar paths.
        host_small = (cpu_device() is not None
                      and 0 < total_points <= tsdb.config.get_int(
                          "tsd.query.host_lane.max_points"))
        if host_small:
            self.exec_stats["hostLane"] = 1.0
        percs = [float(p) for p in (sub.percentiles or [])]
        with host_lane(host_small):
            grid = accumulate_rows(batch["seg"], batch["cnt"],
                                   batch["n_rows"], batch["n_buckets"])
            pvals = (percentile_rows(grid, batch["mid"],
                                     np.asarray(percs, np.float64))
                     if percs else None)
        counts_all = np.asarray(grid)
        pvals = None if pvals is None else np.asarray(pvals)

        for group_key, row_lo, row_hi, ts, used, _pts in batch["groups"]:
            members = groups[group_key]
            group_tags, agg_tags = self._compute_tags(members)
            tsuids = [tsdb.tsuid(s.key) for s, _ in members]
            if percs:
                for i, p in enumerate(sub.percentiles):
                    # metric_pct_<p> naming per the DataPoints adaptor
                    # (HistogramDataPointsToDataPointsAdaptor.java:42-44).
                    results.append(QueryResult(
                        metric="%s_pct_%s" % (sub.metric, _fmt_pct(p)),
                        tags=dict(group_tags),
                        aggregate_tags=list(agg_tags),
                        tsuids=list(tsuids),
                        dps=[(int(t), float(v)) for t, v in
                             zip(ts, pvals[i, row_lo:row_hi])],
                        index=sub.index))
            if sub.show_histogram_buckets:
                for b in used:
                    lo, hi = batch["bounds"][b]
                    results.append(QueryResult(
                        metric="%s_bucket_%g_%g" % (sub.metric, lo, hi),
                        tags=dict(group_tags),
                        aggregate_tags=list(agg_tags),
                        tsuids=list(tsuids),
                        dps=[(int(t), int(c)) for t, c in
                             zip(ts, counts_all[row_lo:row_hi, b])],
                        index=sub.index))
        return results

    def _new_budget(self, sub: TSSubQuery):
        """Scan budget + deadline for one sub query (QueryLimitOverride).

        Derived from the AMBIENT request deadline when one is active
        (rpc_manager minted it at request arrival): every sub query
        shares the request's clock and cancellation token instead of
        restarting tsd.query.timeout at planner time."""
        from opentsdb_tpu.query.limits import QueryBudget, active_deadline
        tsdb = self.tsdb
        limits = tsdb.query_limits
        limits.maybe_reload()
        return QueryBudget(limits, sub.metric or "",
                           tsdb.config.get_int("tsd.query.timeout"),
                           deadline=active_deadline())

    def run_sub(self, query: TSQuery, sub: TSSubQuery) -> list[QueryResult]:
        budget = self._new_budget(sub)
        if sub.percentiles or sub.show_histogram_buckets:
            return self._run_histogram_sub(query, sub, budget)
        segments = self._plan_segments(query, sub)
        # Query-scoped: fetch once, shared by every segment and group.
        global_notes = (self.tsdb.store.get_annotations(
            "", query.start_time, query.end_time)
            if query.global_annotations else [])
        merged: dict[tuple, QueryResult] = {}
        for seg in segments:
            for gk, qr in self._run_segment(query, sub, seg, global_notes,
                                            budget).items():
                cur = merged.get(gk)
                if cur is None:
                    merged[gk] = qr
                    continue
                # Split stitch (SplitRollupSpanGroup): segments are time-
                # disjoint, so concatenation in segment order is sorted.
                cur.dps = cur.dps + qr.dps
                new_tsuids = [t for t in qr.tsuids if t not in cur.tsuids]
                cur.tsuids.extend(new_tsuids)
                seen_notes = {id(a) for a in cur.annotations}
                cur.annotations.extend(
                    a for a in qr.annotations if id(a) not in seen_notes)
                cur.tags = {k: v for k, v in cur.tags.items()
                            if qr.tags.get(k) == v}
                cur.aggregate_tags = sorted(
                    set(cur.aggregate_tags) | set(qr.aggregate_tags))
        return [merged[k] for k in sorted(merged)]

    def run(self, query: TSQuery) -> list[QueryResult]:
        self.exec_stats = {}
        out = []
        for sub in query.queries:
            out.extend(self.run_sub(query, sub))
        return out


class _ExecConsults:
    """plan_decision()'s consult provider for the EXECUTOR: each hook
    does the real, stateful work (demand recording, repeat-count
    bookkeeping, the device gather) — the explain engine supplies the
    read-only twin (query/explain.py).  The routing logic itself lives
    in query/plandecision.py; this class only binds the planner's
    per-segment context onto the subsystem calls."""

    def __init__(self, tsdb, ctx, seg, sub, windows, store,
                 series_list, fix):
        self.tsdb = tsdb
        self.ctx = ctx
        self.seg = seg
        self.sub = sub
        self.windows = windows
        self.store = store
        self.series_list = series_list
        self.fix = fix

    def _metric(self) -> int:
        return self.series_list[0].key.metric

    def rollup_plan(self):
        ctx = self.ctx
        return self.tsdb.rollup_lanes.plan(
            self._metric(), self.series_list, self.windows,
            self.seg.start_ms, self.seg.end_ms, ctx.ds_fn,
            ctx.platform, ctx.s, ctx.n_max, ctx.g_pad, ctx.has_rate,
            total_points=ctx.total_points)

    def note_lane_served(self, plan) -> None:
        self.tsdb.rollup_lanes.note_served(plan)

    def note_lane_fallback(self) -> None:
        self.tsdb.rollup_lanes.note_striping_fallback()

    def tiled_refusal(self, reason: str) -> None:
        from opentsdb_tpu.ops import tiling
        tiling.count_refusal(reason)

    def tiled_plan(self, acc_cell: int):
        from opentsdb_tpu.ops import tiling
        ctx = self.ctx
        return tiling.plan_tiled(
            self.tsdb, s=ctx.s, w=ctx.wp, g_pad=ctx.g_pad,
            acc_cell_bytes=acc_cell, total_points=ctx.total_points,
            platform=ctx.platform)

    def agg_plan(self, platform: str):
        ctx = self.ctx
        ds = self.sub.downsample_spec
        return self.tsdb.agg_cache.plan(
            self.store, self._metric(), self.series_list, self.windows,
            self.seg.start_ms, self.seg.end_ms, ctx.ds_fn,
            ds.fill_policy, ds.fill_value, platform, ctx.s, ctx.n_max,
            ctx.g_pad, ctx.has_rate, total_points=ctx.total_points)

    def device_batch(self, build: bool, ts_base: int | None):
        return self.tsdb.device_cache.batch_for(
            self.store, self._metric(), self.series_list,
            self.seg.start_ms, self.seg.end_ms, self.fix, build=build,
            ts_base=ts_base)


def _fmt_pct(p: float) -> str:
    """Float.toString parity: 99 -> "99.0", 99.9 -> "99.9"."""
    return "%s" % float(p)


def extract_dps(out_ts: np.ndarray, out_val: np.ndarray, out_mask: np.ndarray,
                start_ms: int, end_ms: int, int_mode: bool,
                keep_nans: bool = False) -> list[tuple[int, object]]:
    """Device output -> (ts_ms, python value) pairs trimmed to the query range.

    The serializer-level trim mirrors HttpJsonSerializer (:848-852): points
    outside [start, end] are dropped.  NaNs survive only under fill policies
    that emit them.
    """
    ts = out_ts.ravel()
    val = out_val.ravel()
    mask = out_mask.ravel()
    keep = mask & (ts >= start_ms) & (ts <= end_ms)
    if not keep_nans:
        with np.errstate(invalid="ignore"):
            keep = keep & ~np.isnan(val.astype(np.float64))
    ts = ts[keep]
    val = val[keep]
    if not (int_mode and not np.issubdtype(val.dtype, np.floating)):
        val = val.astype(np.float64)
    # .tolist() converts at C speed (native ints/floats); a per-point
    # Python int()/float() loop costs ~0.5s per million output points
    return list(zip(ts.tolist(), val.tolist()))
