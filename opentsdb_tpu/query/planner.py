"""Query planner/executor: TSQuery -> series selection -> TPU kernels -> results.

Reference behavior: /root/reference/src/core/TsdbQuery.java — UID resolution
(configureFromQuery :490), tag-filter evaluation + group-by discovery
(findGroupBys :675, GroupByAndAggregateCB :981-1114), span windowing, and the
SpanGroup tag intersection rules (SpanGroup.computeTags :348: keys with one
distinct value stay `tags`, conflicting keys become `aggregateTags`).

The per-datapoint iterator merge is replaced by ops.pipeline: each group-by
bucket becomes one padded [series, time] batch pushed through jit-compiled
downsample/rate/union kernels.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from opentsdb_tpu.models.tsquery import TSQuery, TSSubQuery
from opentsdb_tpu.ops.downsample import FixedWindows, EdgeWindows, AllWindow
from opentsdb_tpu.ops.pipeline import (
    PipelineSpec, DownsampleStep, run_pipeline, build_batch)
from opentsdb_tpu.storage.memstore import Series, SeriesKey
from opentsdb_tpu.uid import NoSuchUniqueName
from opentsdb_tpu.utils import datetime_util as DT

_NO_MATCH = object()  # sentinel: a literal filter can never match


@dataclass
class QueryResult:
    """One output object of /api/query (HttpJsonSerializer.java:742-815)."""
    metric: str
    tags: dict[str, str]
    aggregate_tags: list[str]
    tsuids: list[str]
    dps: list[tuple[int, object]]  # (ts_ms, value) value int or float or NaN
    annotations: list = field(default_factory=list)
    global_annotations: list = field(default_factory=list)
    index: int = 0

    def to_json(self, ms_resolution: bool = False, show_tsuids: bool = False,
                fill_policy: str = "none", show_query: bool = False,
                sub_query: TSSubQuery | None = None,
                no_annotations: bool = False,
                global_annotations: bool = False) -> dict:
        dps = {}
        for ts_ms, value in self.dps:
            key = str(ts_ms if ms_resolution else ts_ms // 1000)
            if isinstance(value, float) and value != value:  # NaN
                dps[key] = None if fill_policy == "null" else float("nan")
            else:
                dps[key] = value
        out = {
            "metric": self.metric,
            "tags": self.tags,
            "aggregateTags": self.aggregate_tags,
        }
        if show_query and sub_query is not None:
            out["query"] = sub_query.to_json()
        if show_tsuids:
            out["tsuids"] = sorted(self.tsuids)
        if not no_annotations and self.annotations:
            out["annotations"] = [a.to_json() for a in self.annotations]
        if global_annotations and self.global_annotations:
            out["globalAnnotations"] = [a.to_json()
                                        for a in self.global_annotations]
        out["dps"] = dps
        return out


class QueryRunner:
    """Executes TSQueries against a TSDB."""

    def __init__(self, tsdb):
        self.tsdb = tsdb

    # -- series selection ------------------------------------------------

    def _resolve_series(self, sub: TSSubQuery) -> list[tuple[Series, dict]]:
        """All series matching the sub query, with resolved tag maps."""
        tsdb = self.tsdb
        if sub.tsuids:
            wanted = {t.upper() for t in sub.tsuids}
            out = []
            for series in tsdb.store.all_series():
                if tsdb.tsuid(series.key) in wanted:
                    out.append((series, tsdb.resolve_key_tags(series.key)))
            return out

        metric_uid = tsdb.metrics.get_id(sub.metric)
        candidates = tsdb.store.series_for_metric(metric_uid)
        uid_constraints = self._literal_uid_constraints(sub.filters)
        if uid_constraints is _NO_MATCH:
            return []
        out = []
        filter_tagks = {f.tagk for f in sub.filters}
        for series in candidates:
            if uid_constraints:
                key_tags = dict(series.key.tags)
                if any(key_tags.get(ku) not in vuids
                       for ku, vuids in uid_constraints):
                    continue
            tags = tsdb.resolve_key_tags(series.key)
            if sub.explicit_tags and set(tags) != filter_tagks:
                continue
            if all(f.match(tags) for f in sub.filters):
                out.append((series, tags))
        return out

    def _literal_uid_constraints(self, filters):
        """Compile literal filters to (tagk_uid, tagv_uid_set) pre-filters.

        The UID-space pruning role of the reference's in-scan row regex
        (TsdbQuery.createAndSetFilter :1683): series failing a literal_or
        constraint are skipped before any UID->string resolution.  Returns
        _NO_MATCH when a constraint cannot match anything (unknown tagk, or
        no listed value exists in the tagv dictionary).
        """
        tsdb = self.tsdb
        out = []
        for f in filters:
            values = f.literal_values()
            if values is None:
                continue
            try:
                ku = tsdb.tag_names.get_id(f.tagk)
            except NoSuchUniqueName:
                return _NO_MATCH
            vuids = set()
            for v in values:
                try:
                    vuids.add(tsdb.tag_values.get_id(v))
                except NoSuchUniqueName:
                    pass
            if not vuids:
                return _NO_MATCH
            out.append((ku, vuids))
        return out

    @staticmethod
    def _group(series_tags: list[tuple[Series, dict]], sub: TSSubQuery):
        """Group-by bucketing (TsdbQuery.GroupByAndAggregateCB :981)."""
        group_tagks = sub.group_by_tags()
        if sub.aggregator == "none":
            # NONE: no aggregation, each series is its own group.
            return {("__series__", i): [st]
                    for i, st in enumerate(series_tags)}
        if not group_tagks:
            return {(): series_tags} if series_tags else {}
        groups: dict[tuple, list] = {}
        for series, tags in series_tags:
            key_vals = tuple(tags.get(k) for k in group_tagks)
            if any(v is None for v in key_vals):
                continue  # series lacks a group-by tag -> excluded
            groups.setdefault(key_vals, []).append((series, tags))
        return groups

    @staticmethod
    def _compute_tags(members: list[tuple[Series, dict]]):
        """SpanGroup.computeTags (:348): single-valued keys -> tags,
        conflicting keys -> aggregateTags."""
        tag_set: dict[str, str] = {}
        discards: set[str] = set()
        for _, tags in members:
            for k, v in tags.items():
                if k in discards:
                    continue
                if k not in tag_set:
                    tag_set[k] = v
                elif tag_set[k] != v:
                    discards.add(k)
                    tag_set.pop(k)
        return tag_set, sorted(discards)

    # -- execution -------------------------------------------------------

    def _windows_for(self, sub: TSSubQuery, query: TSQuery):
        spec = sub.downsample_spec
        if spec is None:
            return None
        if spec.run_all:
            return AllWindow(query.start_time, query.end_time)
        if spec.use_calendar:
            edges = DT.calendar_window_edges(
                query.start_time, query.end_time, spec.calendar_interval,
                spec.calendar_unit, spec.timezone)
            return EdgeWindows(tuple(edges))
        return FixedWindows.for_range(query.start_time, query.end_time,
                                      spec.interval_ms)

    def run_sub(self, query: TSQuery, sub: TSSubQuery) -> list[QueryResult]:
        tsdb = self.tsdb
        series_tags = self._resolve_series(sub)
        groups = self._group(series_tags, sub)
        windows = self._windows_for(sub, query)

        if windows is not None:
            window_spec, wargs = windows.split()
        else:
            window_spec, wargs = None, None

        # Query-scoped, not group-scoped: fetch once outside the group loop.
        global_notes = (tsdb.store.get_annotations(
            "", query.start_time, query.end_time)
            if query.global_annotations else [])

        results = []
        for group_key in sorted(groups, key=lambda k: tuple(map(str, k))):
            members = groups[group_key]
            batch_windows = [
                s.window(query.start_time, query.end_time,
                         tsdb.config.fix_duplicates)
                for s, _ in members]
            ts, val, mask, all_int = build_batch(batch_windows)
            int_mode = all_int and sub.downsample_spec is None
            spec = PipelineSpec(
                aggregator=sub.aggregator,
                downsample=(DownsampleStep(
                    sub.downsample_spec.function, window_spec,
                    sub.downsample_spec.fill_policy,
                    sub.downsample_spec.fill_value)
                    if sub.downsample_spec is not None else None),
                rate=sub.rate_options if sub.rate else None,
                int_mode=int_mode)
            out_ts, out_val, out_mask = run_pipeline(spec, ts, val, mask,
                                                     wargs)

            dps = extract_dps(np.asarray(out_ts), np.asarray(out_val),
                              np.asarray(out_mask), query.start_time,
                              query.end_time,
                              int_mode and not sub.rate,
                              keep_nans=sub.fill_policy != "none")

            group_tags, agg_tags = self._compute_tags(members)
            tsuids = [tsdb.tsuid(s.key) for s, _ in members]
            annotations = []
            if not query.no_annotations:
                for t in tsuids:
                    annotations.extend(tsdb.store.get_annotations(
                        t, query.start_time, query.end_time))
            results.append(QueryResult(
                metric=sub.metric or (
                    tsdb.metrics.get_name(members[0][0].key.metric)
                    if members else ""),
                tags=group_tags,
                aggregate_tags=agg_tags,
                tsuids=tsuids,
                dps=dps,
                annotations=annotations,
                global_annotations=global_notes,
                index=sub.index,
            ))
        return results

    def run(self, query: TSQuery) -> list[QueryResult]:
        out = []
        for sub in query.queries:
            out.extend(self.run_sub(query, sub))
        return out


def extract_dps(out_ts: np.ndarray, out_val: np.ndarray, out_mask: np.ndarray,
                start_ms: int, end_ms: int, int_mode: bool,
                keep_nans: bool = False) -> list[tuple[int, object]]:
    """Device output -> (ts_ms, python value) pairs trimmed to the query range.

    The serializer-level trim mirrors HttpJsonSerializer (:848-852): points
    outside [start, end] are dropped.  NaNs survive only under fill policies
    that emit them.
    """
    ts = out_ts.ravel()
    val = out_val.ravel()
    mask = out_mask.ravel()
    keep = mask & (ts >= start_ms) & (ts <= end_ms)
    if not keep_nans:
        with np.errstate(invalid="ignore"):
            keep = keep & ~np.isnan(val.astype(np.float64))
    ts = ts[keep]
    val = val[keep]
    if int_mode and not np.issubdtype(val.dtype, np.floating):
        return [(int(t), int(v)) for t, v in zip(ts, val)]
    return [(int(t), float(v)) for t, v in zip(ts, val)]
