"""Rollup / pre-aggregation subsystem.

Reference behavior: /root/reference/src/rollup/ — RollupConfig.java (interval
registry + aggregation-ID map), RollupInterval.java (interval/table schema),
RollupQuery.java (query-time state + blackout SLA), RollupUtils.java
(qualifier codec, replaced here by columnar per-aggregator stores).
"""

from opentsdb_tpu.rollup.config import (
    RollupInterval, RollupConfig, RollupQuery,
    NoSuchRollupForInterval, NoSuchRollupForTable)
from opentsdb_tpu.rollup.store import RollupStore

__all__ = ["RollupInterval", "RollupConfig", "RollupQuery", "RollupStore",
           "NoSuchRollupForInterval", "NoSuchRollupForTable"]
