"""Rollup interval registry and query-time rollup state.

Reference behavior: /root/reference/src/rollup/RollupConfig.java (:60 —
forward/reverse interval maps, aggregation-ID registry, best-match interval
search :165-201), RollupInterval.java (:32 — interval string + span + table
names, default-interval flag, SLA lag :331) and RollupQuery.java (:26 —
sampling-rate comparison :186, blackout window check :206).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

from opentsdb_tpu.utils import datetime_util as DT

# Aggregators rollup tables may store (RollupUtils qualifier prefixes).
ROLLUP_AGGS = ("sum", "count", "min", "max")

DEFAULT_AGGREGATION_IDS = {"sum": 0, "count": 1, "min": 2, "max": 3}


class NoSuchRollupForInterval(ValueError):
    """No rollup configured for the interval (NoSuchRollupForIntervalException)."""

    def __init__(self, interval: str):
        super().__init__("No rollup interval configured for '%s'" % interval)


class NoSuchRollupForTable(ValueError):
    """No rollup configured for the table (NoSuchRollupForTableException)."""

    def __init__(self, table: str):
        super().__init__("No rollup configured for table '%s'" % table)


@dataclass(frozen=True)
class RollupInterval:
    """One configured rollup granularity (RollupInterval.java:32).

    `table` / `pre_agg_table` keep the reference's two-table split: temporal
    rollups vs group-by pre-aggregates (getTemporalTable :260 /
    getGroupbyTable :271).  `row_span` survives as documentation of layout
    only — the columnar store has no row width.
    """
    interval: str                 # e.g. "1h"
    table: str                    # temporal rollup table name
    pre_agg_table: str            # group-by (pre-agg) table name
    row_span: str = "1d"
    default_interval: bool = False  # true = the raw tsdb table
    delay_sla_ms: int = 0         # getMaximumLag analog, ms of lag allowed

    @property
    def interval_ms(self) -> int:
        return DT.parse_duration(self.interval)

    @property
    def interval_seconds(self) -> int:
        return self.interval_ms // 1000

    @property
    def unit(self) -> str:
        return DT.get_duration_units(self.interval)

    @property
    def unit_multiplier(self) -> int:
        return DT.get_duration_interval(self.interval)

    @staticmethod
    def from_json(obj: dict) -> "RollupInterval":
        return RollupInterval(
            interval=obj["interval"],
            table=obj.get("table", "tsdb-rollup-%s" % obj["interval"]),
            pre_agg_table=obj.get(
                "preAggregationTable",
                obj.get("pre_agg_table", "tsdb-rollup-agg-%s" % obj["interval"])),
            row_span=obj.get("rowSpan", obj.get("row_span", "1d")),
            default_interval=bool(obj.get("defaultInterval",
                                          obj.get("default_interval", False))),
            delay_sla_ms=int(obj.get("delaySla",
                                     obj.get("delay_sla_ms", 0))))

    def to_json(self) -> dict:
        return {
            "interval": self.interval,
            "table": self.table,
            "preAggregationTable": self.pre_agg_table,
            "rowSpan": self.row_span,
            "defaultInterval": self.default_interval,
            "delaySla": self.delay_sla_ms,
        }


@dataclass
class RollupConfig:
    """Registry of rollup intervals + the aggregator-ID map (RollupConfig.java:60)."""
    intervals: list[RollupInterval] = field(default_factory=list)
    aggregation_ids: dict[str, int] = field(
        default_factory=lambda: dict(DEFAULT_AGGREGATION_IDS))

    def __post_init__(self):
        self._forward: dict[str, RollupInterval] = {}
        self._by_table: dict[str, RollupInterval] = {}
        for ri in self.intervals:
            if ri.interval in self._forward:
                raise ValueError("Duplicate rollup interval: %s" % ri.interval)
            self._forward[ri.interval] = ri
            self._by_table[ri.table] = ri
            self._by_table[ri.pre_agg_table] = ri
        ids = set()
        for name, agg_id in self.aggregation_ids.items():
            if agg_id in ids:
                raise ValueError("Duplicate aggregation id: %d" % agg_id)
            if not 0 <= agg_id <= 127:
                raise ValueError("Aggregation id out of range: %d" % agg_id)
            ids.add(agg_id)

    # -- lookups (RollupConfig.getRollupInterval :140/:165) --

    def get_rollup_interval(self, interval: str) -> RollupInterval:
        if not interval:
            raise ValueError("Interval cannot be null or empty")
        ri = self._forward.get(interval)
        if ri is None:
            raise NoSuchRollupForInterval(interval)
        return ri

    def get_best_matches_ms(self, interval_ms: int) -> list[RollupInterval]:
        """All intervals evenly dividing the request, widest first.

        Mirrors getRollupInterval(long,String) :165-201: an exact match plus
        every coarser-compatible fallback, reverse-ordered so [0] is the best
        table to try and the rest back it up on empty results.  Millisecond
        math so sub-second downsample intervals never select a table whose
        cells straddle the window edges.
        """
        if interval_ms <= 0:
            raise ValueError("Interval must be positive")
        out = []
        for ri in self._forward.values():
            ms = ri.interval_ms
            if ms > 0 and interval_ms % ms == 0:
                out.append(ri)
        if not out:
            raise NoSuchRollupForInterval("%dms" % interval_ms)
        out.sort(key=lambda r: r.interval_ms, reverse=True)
        return out

    def get_best_matches(self, interval_seconds: int) -> list[RollupInterval]:
        """Seconds-granularity wrapper (the reference API's unit)."""
        return self.get_best_matches_ms(interval_seconds * 1000)

    def get_rollup_interval_for_table(self, table: str) -> RollupInterval:
        ri = self._by_table.get(table)
        if ri is None:
            raise NoSuchRollupForTable(table)
        return ri

    # -- aggregator ids (RollupConfig.getIdForAggregator :279) --

    def get_id_for_aggregator(self, aggregator: str) -> int:
        try:
            return self.aggregation_ids[aggregator.lower()]
        except KeyError:
            raise ValueError("No ID for aggregator: %s" % aggregator)

    def get_aggregator_for_id(self, agg_id: int) -> str:
        for name, i in self.aggregation_ids.items():
            if i == agg_id:
                return name
        raise ValueError("No aggregator mapped to ID: %d" % agg_id)

    # -- construction --

    @staticmethod
    def from_json(text_or_obj) -> "RollupConfig":
        obj = (json.loads(text_or_obj) if isinstance(text_or_obj, str)
               else text_or_obj)
        intervals = [RollupInterval.from_json(i)
                     for i in obj.get("intervals", [])]
        agg_ids = {k.lower(): int(v)
                   for k, v in obj.get("aggregationIds",
                                       DEFAULT_AGGREGATION_IDS).items()}
        return RollupConfig(intervals=intervals, aggregation_ids=agg_ids)

    @staticmethod
    def from_config(config) -> "RollupConfig | None":
        """Load from tsd.rollups.config (a path or inline JSON), if enabled."""
        if not config.get_bool("tsd.rollups.enable"):
            return None
        raw = config.get_string("tsd.rollups.config")
        if not raw:
            return RollupConfig(intervals=[
                RollupInterval("1m", "tsdb-rollup-1m", "tsdb-rollup-agg-1m",
                               row_span="1h"),
                RollupInterval("1h", "tsdb-rollup-1h", "tsdb-rollup-agg-1h",
                               row_span="1d"),
                RollupInterval("1d", "tsdb-rollup-1d", "tsdb-rollup-agg-1d",
                               row_span="1n"),
            ])
        if raw.lstrip().startswith("{"):
            return RollupConfig.from_json(raw)
        with open(raw) as fh:
            return RollupConfig.from_json(fh.read())

    def to_json(self) -> dict:
        return {"aggregationIds": dict(self.aggregation_ids),
                "intervals": [i.to_json() for i in self.intervals]}


@dataclass
class RollupQuery:
    """Query-time rollup selection (RollupQuery.java:26)."""
    rollup_interval: RollupInterval
    rollup_agg: str               # function applied inside the rollup cells
    sample_interval_ms: int       # the user's downsample interval
    group_by: str = "sum"         # cross-series aggregator

    def is_lower_sampling_rate(self) -> bool:
        """True when the rollup cells are finer than the requested interval
        (RollupQuery.isLowerSamplingRate :186) — a downsample pass is still
        needed on top of the rollup data."""
        return self.rollup_interval.interval_ms < self.sample_interval_ms

    def last_guaranteed_ms(self, now_ms: int) -> int:
        """Latest timestamp the rollup table is SLA-guaranteed to cover."""
        return now_ms - self.rollup_interval.delay_sla_ms

    def is_in_blackout(self, ts_ms: int, now_ms: int) -> bool:
        """RollupQuery.isInBlackoutPeriod (:206)."""
        if self.rollup_interval.delay_sla_ms <= 0:
            return False
        return ts_ms > self.last_guaranteed_ms(now_ms)
