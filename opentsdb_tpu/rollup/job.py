"""Offline rollup builder: raw store -> per-interval rollup lanes on the mesh.

The batch analog of feeding TSDB.addAggregatePoint from an external rollup
pipeline (/root/reference/src/core/TSDB.java:1359-1457): scan every raw
series, compute sum/count/min/max per rollup window on the device mesh
(parallel.sharded.sharded_rollup — series sharded across chips, time shards
combined with psum/pmin/pmax over ICI), then write the window cells into the
RollupStore lanes.  BASELINE config 5's 1B-point pass is this function over a
larger mesh.
"""

from __future__ import annotations

import numpy as np

from opentsdb_tpu.ops.downsample import FixedWindows
from opentsdb_tpu.ops.pipeline import build_batch, PAD_TS
from opentsdb_tpu.parallel.mesh import make_mesh
from opentsdb_tpu.parallel.sharded import sharded_rollup, shard_series


def run_rollup_job(tsdb, intervals: list[str] | None = None,
                   start_ms: int | None = None, end_ms: int | None = None,
                   mesh=None, batch_series: int = 1024) -> dict[str, int]:
    """Roll every raw series up into the given intervals; returns counts.

    Writes sum/count/min/max lanes for each interval so any supported
    downsample function (and avg via sum+count) can be served from rollups.
    """
    if tsdb.rollup_store is None:
        raise RuntimeError("Rollups are not enabled")
    if intervals is None:
        intervals = [ri.interval for ri in tsdb.rollup_config.intervals
                     if not ri.default_interval]
    if mesh is None:
        mesh = make_mesh()
    all_series = tsdb.store.all_series()
    if not all_series:
        return {i: 0 for i in intervals}

    if start_ms is None or end_ms is None:
        lo, hi = None, None
        for s in all_series:
            ts, _, _, _ = s.arrays()
            if len(ts):
                lo = int(ts.min()) if lo is None else min(lo, int(ts.min()))
                hi = int(ts.max()) if hi is None else max(hi, int(ts.max()))
        if lo is None:
            return {i: 0 for i in intervals}
        start_ms = lo if start_ms is None else start_ms
        end_ms = hi if end_ms is None else end_ms

    written: dict[str, int] = {}
    for interval in intervals:
        ri = tsdb.rollup_config.get_rollup_interval(interval)
        plan = FixedWindows.for_range(start_ms, end_ms, ri.interval_ms)
        spec, wargs = plan.split()
        step = sharded_rollup(mesh, spec)
        count = 0
        for base in range(0, len(all_series), batch_series):
            chunk = all_series[base:base + batch_series]
            windows = [s.window(start_ms, end_ms, True) for s in chunk]
            ts, val, mask, _ = build_batch(windows)
            val = val.astype(np.float64)
            gid = np.zeros(ts.shape[0], np.int32)
            ts_d, val_d, mask_d, _ = shard_series(mesh, ts, val, mask, gid)
            wts, tot, cnt, lo, hi = step(ts_d, val_d, mask_d, wargs)
            wts = np.asarray(wts)
            tot = np.asarray(tot)[:len(chunk)]
            cnt = np.asarray(cnt)[:len(chunk)]
            lo = np.asarray(lo)[:len(chunk)]
            hi = np.asarray(hi)[:len(chunk)]
            nwin = plan.count
            live = (wts[:nwin] != PAD_TS)
            for i, series in enumerate(chunk):
                has = (cnt[i, :nwin] > 0) & live
                if not has.any():
                    continue
                w = wts[:nwin][has]
                key = series.key
                lanes = tsdb.rollup_store
                lanes.lane(interval, "sum").add_batch(
                    key, w, tot[i, :nwin][has], False)
                lanes.lane(interval, "count").add_batch(
                    key, w, cnt[i, :nwin][has].astype(np.int64), True)
                lanes.lane(interval, "min").add_batch(
                    key, w, lo[i, :nwin][has], False)
                lanes.lane(interval, "max").add_batch(
                    key, w, hi[i, :nwin][has], False)
                count += int(has.sum())
        written[interval] = count
    return written
