"""Columnar rollup storage: one MemStore per (interval, aggregator) lane.

Reference behavior: rollup tables tsdb-rollup-<interval> keyed by the same
row-key schema with "agg:offset" qualifiers (RollupUtils.buildRollupQualifier,
/root/reference/src/rollup/RollupUtils.java:120-178) plus pre-agg "-agg"
tables.  The columnar rebuild drops the qualifier codec: each (interval,
aggregator) pair is its own MemStore keyed by the same SeriesKey, so a query
for `1h sum` is a plain store lookup and avg reads pair the sum and count
lanes (Downsampler.java:155-210 rollup branch).

Pre-aggregates (is_groupby, TSDB.addAggregatePointInternal) land in a
per-interval pre-agg lane set; interval-less pre-aggs use the reference's
"default table" convention and are stored under the raw interval "".
"""

from __future__ import annotations

import threading

from opentsdb_tpu.rollup.config import RollupConfig, ROLLUP_AGGS
from opentsdb_tpu.storage.memstore import MemStore, SeriesKey


class RollupStore:
    """All rollup + pre-agg lanes for one TSDB."""

    def __init__(self, config: RollupConfig, salt_buckets: int = 20):
        self.config = config
        self.salt_buckets = salt_buckets
        self._lanes: dict[tuple[str, str, bool], MemStore] = {}  # guarded-by: _lock
        self._lock = threading.Lock()

    def lane(self, interval: str, aggregator: str,
             pre_agg: bool = False) -> MemStore:
        """The MemStore holding `aggregator` cells of `interval` rollups."""
        aggregator = aggregator.lower()
        # Temporal rollup lanes must map to a configured aggregation id
        # (RollupUtils qualifier codec); pre-agg lanes accept any group-by
        # aggregator the registry knows (TSDB.java:1536-1542).
        if not pre_agg and aggregator not in self.config.aggregation_ids:
            raise ValueError("No ID for aggregator: %s" % aggregator)
        key = (interval, aggregator, pre_agg)
        with self._lock:
            store = self._lanes.get(key)
            if store is None:
                store = MemStore(salt_buckets=self.salt_buckets)
                self._lanes[key] = store
            return store

    def peek_lane(self, interval: str, aggregator: str,
                  pre_agg: bool = False) -> MemStore | None:
        with self._lock:
            return self._lanes.get((interval, aggregator.lower(), pre_agg))

    def add_point(self, key: SeriesKey, interval: str, aggregator: str,
                  ts_ms: int, value, is_int: bool,
                  pre_agg: bool = False) -> None:
        self.lane(interval, aggregator, pre_agg).add_point(
            key, ts_ms, value, is_int)

    def lanes(self) -> list[tuple[str, str, bool]]:
        with self._lock:
            return sorted(self._lanes)

    @property
    def total_datapoints(self) -> int:
        with self._lock:
            return sum(s.total_datapoints for s in self._lanes.values())

    def collect_stats(self) -> dict[str, float]:
        out: dict[str, float] = {}
        with self._lock:
            for (interval, agg, pre), store in self._lanes.items():
                name = "tsd.rollup.datapoints interval=%s agg=%s%s" % (
                    interval or "preagg", agg, " preagg" if pre else "")
                out[name] = store.total_datapoints
        return out

    @staticmethod
    def supported_aggs() -> tuple[str, ...]:
        return ROLLUP_AGGS
