"""Search subsystem: plugin SPI, query pojo, time-series lookup.

Reference behavior: /root/reference/src/search/ — SearchPlugin.java (SPI:
index/delete TSMeta/UIDMeta/Annotation + executeSearch), SearchQuery.java
(TSMETA/TSMETA_SUMMARY/TSUIDS/UIDMETA/ANNOTATION/LOOKUP types),
TimeSeriesLookup.java (storage-native series lookup by metric/tag pairs).
"""

from opentsdb_tpu.search.plugin import SearchPlugin, MemorySearchPlugin
from opentsdb_tpu.search.query import SearchQuery, parse_search_type
from opentsdb_tpu.search.lookup import TimeSeriesLookup

__all__ = ["SearchPlugin", "MemorySearchPlugin", "SearchQuery",
           "parse_search_type", "TimeSeriesLookup"]
