"""Storage-native time-series lookup (/api/search/lookup, `tsdb search`).

Reference behavior: /root/reference/src/search/TimeSeriesLookup.java — find
series matching a metric and/or tag pairs by scanning the meta/data tables;
`*` or missing values wildcard.  Here the store's series index answers
directly.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field


@dataclass
class LookupQuery:
    metric: str | None = None             # None or "*" = any
    tags: list[tuple[str | None, str | None]] = field(default_factory=list)
    limit: int = 25
    start_index: int = 0
    # The reference's useMeta flag picked the meta table over a data-table
    # scan; here the series index IS the lookup source, so the flag has no
    # analog and is not modeled.

    @staticmethod
    def parse(m_param: str) -> "LookupQuery":
        """`m=metric{tagk=tagv,...}` with * wildcards (SearchRpc :84-100)."""
        out = LookupQuery()
        spec = m_param.strip()
        if "{" in spec:
            if not spec.endswith("}"):
                raise ValueError("Missing '}' in lookup query: " + spec)
            metric_part, tag_part = spec[:-1].split("{", 1)
            for pair in tag_part.split(","):
                if not pair:
                    continue
                if "=" not in pair:
                    raise ValueError("Invalid tag pair: " + pair)
                k, v = pair.split("=", 1)
                out.tags.append((k if k not in ("", "*") else None,
                                 v if v not in ("", "*") else None))
        else:
            metric_part = spec
        out.metric = metric_part if metric_part not in ("", "*") else None
        return out


class TimeSeriesLookup:
    def __init__(self, tsdb, query: LookupQuery):
        self.tsdb = tsdb
        self.query = query

    def lookup(self) -> dict:
        start = time.time()
        tsdb = self.tsdb
        q = self.query
        if q.metric is not None:
            metric_uid = tsdb.metrics.get_id(q.metric)   # may raise 404able
            candidates = tsdb.store.series_for_metric(metric_uid)
        else:
            candidates = tsdb.store.all_series()
        results = []
        for series in candidates:
            tags = tsdb.resolve_key_tags(series.key)
            if not self._tags_match(tags, q.tags):
                continue
            results.append({
                "tsuid": tsdb.tsuid(series.key),
                "metric": tsdb.metrics.get_name(series.key.metric),
                "tags": tags,
            })
        results.sort(key=lambda r: (r["metric"], r["tsuid"]))
        total = len(results)
        page = results[q.start_index:q.start_index + q.limit] \
            if q.limit else results[q.start_index:]
        return {
            "type": "LOOKUP",
            "metric": q.metric or "*",
            "tags": [{"key": k or "*", "value": v or "*"}
                     for k, v in q.tags],
            "limit": q.limit,
            "startIndex": q.start_index,
            "totalResults": total,
            "results": page,
            "time": round((time.time() - start) * 1000.0, 3),
        }

    @staticmethod
    def _tags_match(tags: dict[str, str],
                    constraints: list[tuple[str | None, str | None]]) -> bool:
        for k, v in constraints:
            if k is not None and v is not None:
                if tags.get(k) != v:
                    return False
            elif k is not None:
                if k not in tags:
                    return False
            elif v is not None:
                if v not in tags.values():
                    return False
        return True
