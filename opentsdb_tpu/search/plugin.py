"""SearchPlugin SPI + a working in-memory implementation.

Reference behavior: /root/reference/src/search/SearchPlugin.java — the SPI
the TSD notifies on meta/annotation changes and delegates /api/search to.
The reference ships no bundled implementation (operators install
elasticsearch plugins); here MemorySearchPlugin provides substring search
over indexed documents so /api/search works out of the box, and stands as
the SPI reference implementation.
"""

from __future__ import annotations

import threading
import time


class SearchPlugin:
    """SPI surface (SearchPlugin.java)."""

    def initialize(self, tsdb) -> None:
        pass

    def shutdown(self) -> None:
        pass

    def version(self) -> str:
        return "3.0.0"

    def collect_stats(self, collector) -> None:
        pass

    def index_tsmeta(self, meta) -> None:
        raise NotImplementedError

    def delete_tsmeta(self, tsuid: str) -> None:
        raise NotImplementedError

    def index_uidmeta(self, meta) -> None:
        raise NotImplementedError

    def delete_uidmeta(self, kind_or_meta, uid: str | None = None) -> None:
        raise NotImplementedError

    def index_annotation(self, note) -> None:
        raise NotImplementedError

    def delete_annotation(self, note) -> None:
        raise NotImplementedError

    def execute_search(self, search_query):
        raise NotImplementedError


class MemorySearchPlugin(SearchPlugin):
    """Substring-matching in-memory index."""

    def __init__(self):
        # guarded-by: _lock
        self._tsmeta: dict[str, object] = {}
        self._uidmeta: dict[tuple[str, str], object] = {}  # guarded-by: _lock
        self._annotations: list = []  # guarded-by: _lock
        self._lock = threading.Lock()

    # -- indexing --

    def index_tsmeta(self, meta) -> None:
        with self._lock:
            self._tsmeta[meta.tsuid] = meta

    def delete_tsmeta(self, tsuid: str) -> None:
        with self._lock:
            self._tsmeta.pop(tsuid.upper(), None)

    def index_uidmeta(self, meta) -> None:
        with self._lock:
            self._uidmeta[(meta.type.lower(), meta.uid)] = meta

    def delete_uidmeta(self, kind_or_meta, uid: str | None = None) -> None:
        if uid is None:
            kind, uid = kind_or_meta.type, kind_or_meta.uid
        else:
            kind = kind_or_meta
        with self._lock:
            self._uidmeta.pop((kind.lower(), uid.upper()), None)

    def index_annotation(self, note) -> None:
        with self._lock:
            self._annotations = [
                a for a in self._annotations
                if not (a.tsuid == note.tsuid
                        and a.start_time == note.start_time)]
            self._annotations.append(note)

    def delete_annotation(self, note) -> None:
        with self._lock:
            self._annotations = [
                a for a in self._annotations
                if not (a.tsuid == note.tsuid
                        and a.start_time == note.start_time)]

    # -- search --

    @staticmethod
    def _matches(needle: str, *haystacks) -> bool:
        if not needle:
            return True
        needle = needle.lower()
        return any(needle in (h or "").lower() for h in haystacks)

    def execute_search(self, search_query):
        start = time.time()
        q = search_query.query
        stype = search_query.type
        hits: list = []
        with self._lock:
            if stype in ("TSMETA", "TSMETA_SUMMARY", "TSUIDS"):
                for meta in self._tsmeta.values():
                    names = [meta.tsuid, meta.display_name, meta.description,
                             meta.notes]
                    if meta.metric is not None:
                        names.append(meta.metric.name)
                    names.extend(t.name for t in meta.tags)
                    if self._matches(q, *names):
                        hits.append(meta)
                if stype == "TSMETA":
                    results = [m.to_json() for m in hits]
                elif stype == "TSUIDS":
                    results = [m.tsuid for m in hits]
                else:   # TSMETA_SUMMARY
                    results = []
                    for m in hits:
                        summary = {"tsuid": m.tsuid}
                        if m.metric is not None:
                            summary["metric"] = m.metric.name
                        tags = {}
                        for i in range(0, len(m.tags) - 1, 2):
                            tags[m.tags[i].name] = m.tags[i + 1].name
                        summary["tags"] = tags
                        results.append(summary)
            elif stype == "UIDMETA":
                for meta in self._uidmeta.values():
                    if self._matches(q, meta.name, meta.uid,
                                     meta.display_name, meta.description,
                                     meta.notes):
                        hits.append(meta)
                results = [m.to_json() for m in hits]
            elif stype == "ANNOTATION":
                for note in self._annotations:
                    if self._matches(q, note.description, note.notes,
                                     note.tsuid):
                        hits.append(note)
                results = [n.to_json() for n in hits]
            else:
                raise ValueError("Unsupported search type: " + stype)
        search_query.total_results = len(results)
        lo = search_query.start_index
        search_query.results = results[lo:lo + search_query.limit]
        search_query.time_ms = (time.time() - start) * 1000.0
        return search_query
