"""SearchQuery pojo (SearchQuery.java:44-60, parseSearchType :160-178)."""

from __future__ import annotations

from dataclasses import dataclass, field

SEARCH_TYPES = ("TSMETA", "TSMETA_SUMMARY", "TSUIDS", "UIDMETA",
                "ANNOTATION", "LOOKUP")


def parse_search_type(endpoint: str) -> str:
    normalized = endpoint.strip().upper()
    if normalized in SEARCH_TYPES:
        return normalized
    raise ValueError("Unknown search type: " + endpoint)


@dataclass
class SearchQuery:
    type: str = "TSMETA"
    query: str = ""
    limit: int = 25
    start_index: int = 0
    total_results: int = 0
    results: list = field(default_factory=list)
    time_ms: float = 0.0

    @staticmethod
    def from_json(body: dict, search_type: str) -> "SearchQuery":
        return SearchQuery(
            type=search_type,
            query=body.get("query", ""),
            limit=int(body.get("limit", 25)),
            start_index=int(body.get("startIndex", 0)))

    def to_json(self) -> dict:
        return {
            "type": self.type,
            "query": self.query,
            "limit": self.limit,
            "startIndex": self.start_index,
            "metric": None,
            "tags": None,
            "totalResults": self.total_results,
            "results": self.results,
            "time": round(self.time_ms, 3),
        }
