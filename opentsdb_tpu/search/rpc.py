"""/api/search/<type> handler (SearchRpc.java:52-130)."""

from __future__ import annotations

from opentsdb_tpu.search.lookup import LookupQuery, TimeSeriesLookup
from opentsdb_tpu.search.query import SearchQuery, parse_search_type
from opentsdb_tpu.tsd.http import BadRequestError, HttpQuery
from opentsdb_tpu.uid import NoSuchUniqueName


def handle_search(tsdb, query: HttpQuery) -> None:
    sub = query.api_subpath()
    endpoint = sub[0] if sub else ""
    try:
        stype = parse_search_type(endpoint)
    except ValueError:
        raise BadRequestError(
            "Unknown search endpoint: %s" % endpoint, status=404,
            details="Try one of tsmeta, tsmeta_summary, tsuids, uidmeta, "
                    "annotation or lookup")
    if stype == "LOOKUP":
        return _handle_lookup(tsdb, query)
    if tsdb.search_plugin is None:
        raise BadRequestError(
            "Searching is not enabled on this TSD", status=501,
            details="Set tsd.search.enable and tsd.search.plugin")
    if query.method == "POST" and query.request.body:
        body = query.serializer.parse_search_query_v1()
        sq = SearchQuery.from_json(body, stype)
    else:
        sq = SearchQuery(
            type=stype,
            query=query.get_query_string_param("query") or "",
            limit=int(query.get_query_string_param("limit") or 25),
            start_index=int(query.get_query_string_param("start_index")
                            or 0))
    result = tsdb.search_plugin.execute_search(sq)
    query.send_reply(query.serializer.format_search_results_v1(
        result.to_json()))


def _handle_lookup(tsdb, query: HttpQuery) -> None:
    if query.method == "POST" and query.request.body:
        body = query.json_body()
        lq = LookupQuery()
        lq.metric = body.get("metric")
        if lq.metric in ("", "*"):
            lq.metric = None
        for t in body.get("tags") or []:
            k = t.get("key")
            v = t.get("value")
            lq.tags.append((k if k not in (None, "", "*") else None,
                            v if v not in (None, "", "*") else None))
        lq.limit = int(body.get("limit", 25))
        lq.start_index = int(body.get("startIndex", 0))
    else:
        m = query.required_query_string_param("m")
        lq = LookupQuery.parse(m)
        lq.limit = int(query.get_query_string_param("limit") or 25)
        lq.start_index = int(query.get_query_string_param("start_index")
                             or 0)
    try:
        query.send_reply(TimeSeriesLookup(tsdb, lq).lookup())
    except NoSuchUniqueName as e:
        raise BadRequestError(str(e), status=404)
