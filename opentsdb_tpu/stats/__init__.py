"""Stats / telemetry subsystem.

Reference behavior: /root/reference/src/stats/ — StatsCollector.java (:35,
push-style emitter with host/global tags), QueryStats.java (:58, per-query
lifecycle telemetry + running/completed registry served at
/api/stats/query), Histogram.java (exponential-bucket latency histogram).
"""

from opentsdb_tpu.stats.collector import StatsCollector
from opentsdb_tpu.stats.query_stats import QueryStats, QueryStatsRegistry
from opentsdb_tpu.stats.histogram import LatencyHistogram

__all__ = ["StatsCollector", "QueryStats", "QueryStatsRegistry",
           "LatencyHistogram"]
