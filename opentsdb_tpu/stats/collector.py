"""Push-style stats emitter (StatsCollector.java:35).

A collector visits every subsystem, receives `record(name, value, xtratag)`
calls, and buffers them as datapoint dicts tagged with the host (and any
extra tags pushed onto the context stack).
"""

from __future__ import annotations

import socket
import time


class StatsCollector:
    """Collects `tsd.*` internal metrics as {metric, timestamp, value, tags}."""

    def __init__(self, prefix: str = "tsd", use_host_tag: bool = True):
        self.prefix = prefix
        self.records: list[dict] = []
        self._extra_tags: dict[str, str] = {}
        if use_host_tag:
            self._extra_tags["host"] = socket.gethostname()

    def add_extra_tag(self, name: str, value: str) -> None:
        self._extra_tags[name] = value

    def clear_extra_tag(self, name: str) -> None:
        self._extra_tags.pop(name, None)

    def record(self, name: str, value, xtratag: str | None = None) -> None:
        """One datapoint; `xtratag` is a "tag=value" literal like the
        reference's (StatsCollector.record :118)."""
        tags = dict(self._extra_tags)
        if xtratag:
            # exactly one '=': the reference rejects both the bare form
            # and "a=b=c" (which would silently fold "b=c" into the tag
            # value and mint an unqueryable tag)
            if xtratag.count("=") != 1:
                raise ValueError("invalid xtratag: %s (multiple '=' signs "
                                 "or none)" % xtratag)
            k, v = xtratag.split("=", 1)
            tags[k] = v
        self.records.append({
            "metric": "%s.%s" % (self.prefix, name),
            "timestamp": int(time.time()),
            "value": float(value) if isinstance(value, float) else int(value),
            "tags": tags,
        })

    def record_map(self, stats: dict[str, float]) -> None:
        """Record a {"name tag=v tag2=v2": value} map (TSDB.collectStats
        output shape: name plus optional space-separated xtratag)."""
        for key, value in stats.items():
            parts = key.split(" ")
            name = parts[0]
            tags = dict(self._extra_tags)
            for p in parts[1:]:
                if "=" in p:
                    k, v = p.split("=", 1)
                    tags[k] = v
                else:
                    # bare suffix like "metrics" -> kind tag (TSDB uses
                    # "tsd.uid.cache-hit metrics" style keys)
                    tags["kind"] = p
            self.records.append({
                "metric": "%s.%s" % (self.prefix, name.removeprefix("tsd.")),
                "timestamp": int(time.time()),
                "value": value,
                "tags": tags,
            })

    def emit_ascii(self) -> str:
        """Telnet `stats` format: `metric timestamp value tag=v ...` lines."""
        lines = []
        for r in self.records:
            tags = " ".join("%s=%s" % kv for kv in sorted(r["tags"].items()))
            value = r["value"]
            if isinstance(value, float) and value.is_integer():
                value = int(value)
            lines.append("%s %d %s%s" % (r["metric"], r["timestamp"], value,
                                         (" " + tags) if tags else ""))
        return "\n".join(lines) + ("\n" if lines else "")
