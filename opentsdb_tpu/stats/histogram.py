"""Exponential-bucket latency histogram (stats/Histogram.java).

Reference semantics: linear buckets of `interval` up to `cutoff`, then
buckets whose width doubles per step, a fixed total bucket count, with
percentile lookup by cumulative count.
"""

from __future__ import annotations

import threading


class LatencyHistogram:
    def __init__(self, num_buckets: int = 16, interval: int = 2,
                 cutoff: int = 16):
        if num_buckets < 1:
            raise ValueError("num_buckets must be >= 1")
        self.interval = interval
        self.cutoff = cutoff
        self.buckets = [0] * num_buckets  # guarded-by: _lock
        self._lock = threading.Lock()

    def _bucket_index(self, value: int) -> int:
        if value < self.cutoff:
            idx = value // self.interval
        else:
            # doubling-width region
            idx = self.cutoff // self.interval
            width = self.interval * 2
            floor = self.cutoff
            while value >= floor + width and idx < len(self.buckets) - 1:
                floor += width
                width *= 2
                idx += 1
        return min(idx, len(self.buckets) - 1)

    def add(self, value: int) -> None:
        if value < 0:
            raise ValueError("negative value: %d" % value)
        with self._lock:
            self.buckets[self._bucket_index(value)] += 1

    def percentile(self, p: int) -> int:
        """Upper bound of the bucket holding the p-th percentile count."""
        if not 0 < p <= 100:
            raise ValueError("invalid percentile: %d" % p)
        with self._lock:
            total = sum(self.buckets)
            if total == 0:
                return 0
            threshold = total * p / 100.0
            seen = 0
            floor = 0
            width = self.interval
            for i, count in enumerate(self.buckets):
                seen += count
                ceiling = floor + width
                if seen >= threshold:
                    return ceiling
                floor = ceiling
                if floor >= self.cutoff:
                    width *= 2
            return floor

    def print_ascii(self) -> str:
        with self._lock:
            return " ".join(str(c) for c in self.buckets)
