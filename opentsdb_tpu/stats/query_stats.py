"""Per-query telemetry + running/completed query registry.

Reference behavior: /root/reference/src/stats/QueryStats.java (:58) — each
/api/query execution registers itself, marks named pipeline milestones
(QueryStat enum :132), and lands in a completed ring buffer served by
/api/stats/query (getRunningAndCompleteStats :398).  Duplicate in-flight
queries are rejected (executed :228).
"""

from __future__ import annotations

import threading
import time
import itertools

COMPLETED_KEEP = 60


class DuplicateQueryException(RuntimeError):
    def __init__(self):
        super().__init__("Query is already executing for endpoint: /api/query")


class QueryStats:
    """Telemetry for one query execution."""

    _ids = itertools.count(1)

    def __init__(self, remote: str, query_json: dict | None,
                 headers: dict | None = None):
        self.query_id = next(QueryStats._ids)
        self.remote = remote
        self.query = query_json or {}
        self.headers = dict(headers or {})
        self.executed = 1
        self.start = time.time()
        self.end: float | None = None
        self.http_status = 200
        self.exception: str | None = None
        self.stats: dict[str, float] = {}
        # obs.trace.Trace of the serving request (rendered lazily at
        # snapshot time so the ring serves the FINISHED tree)
        self.trace = None

    def mark(self, stat: str, value_ms: float | None = None) -> None:
        """Record a milestone duration (QueryStats.markSerializationSuccessful
        and friends); default value is elapsed-so-far."""
        if value_ms is None:
            value_ms = (time.time() - self.start) * 1000.0
        self.stats[stat] = value_ms

    def done(self, status: int = 200, exception: str | None = None) -> None:
        self.end = time.time()
        self.http_status = status
        self.exception = exception

    def elapsed_ms(self) -> float:
        return ((self.end or time.time()) - self.start) * 1000.0

    def hash_key(self) -> tuple:
        def freeze(o):
            if isinstance(o, dict):
                return tuple(sorted((k, freeze(v)) for k, v in o.items()))
            if isinstance(o, list):
                return tuple(freeze(v) for v in o)
            return o
        return (self.remote.split(":")[0], freeze(self.query))

    def to_json(self, running: bool = False) -> dict:
        out = {
            "queryId": self.query_id,
            "remote": self.remote,
            "queryStart": int(self.start * 1000),
            "executed": self.executed,
            "user": self.headers.get("x-user", ""),
            "query": self.query,
            "stats": {k: round(v, 3) for k, v in self.stats.items()},
        }
        if self.trace is not None:
            out["trace"] = self.trace.to_json()
        if running:
            out["elapsed"] = round(self.elapsed_ms(), 3)
        else:
            out["elapsed"] = round(self.elapsed_ms(), 3)
            out["httpResponse"] = self.http_status
            if self.exception:
                out["exception"] = self.exception
        return out


class QueryStatsRegistry:
    """Running + completed query registries (QueryStats statics)."""

    def __init__(self, keep: int = COMPLETED_KEEP):
        self.keep = keep
        # guarded-by: _lock
        self._running: dict[tuple, QueryStats] = {}
        self._completed: list[QueryStats] = []  # guarded-by: _lock
        self._lock = threading.Lock()

    def start(self, qs: QueryStats) -> None:
        key = qs.hash_key()
        with self._lock:
            existing = self._running.get(key)
            if existing is not None:
                existing.executed += 1
                raise DuplicateQueryException()
            self._running[key] = qs

    def finish(self, qs: QueryStats, status: int = 200,
               exception: str | None = None) -> None:
        qs.done(status, exception)
        with self._lock:
            self._running.pop(qs.hash_key(), None)
            self._completed.append(qs)
            if len(self._completed) > self.keep:
                self._completed = self._completed[-self.keep:]

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "running": [q.to_json(running=True)
                            for q in self._running.values()],
                "completed": [q.to_json()
                              for q in reversed(self._completed)],
            }
