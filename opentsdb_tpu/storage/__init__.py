from opentsdb_tpu.storage.memstore import (
    MemStore,
    Series,
    SeriesKey,
    CompactionQueue,
)

__all__ = ["MemStore", "Series", "SeriesKey", "CompactionQueue"]
