# NOTE: DeviceSeriesCache is deliberately NOT re-exported here — importing
# it pulls jax, and the storage layer stays importable numpy-only (the
# persistence tooling and memstore tests rely on that).  Use the deep path:
# `from opentsdb_tpu.storage.device_cache import DeviceSeriesCache`.
from opentsdb_tpu.storage.memstore import (
    MemStore,
    Series,
    SeriesKey,
    CompactionQueue,
)

__all__ = ["MemStore", "Series", "SeriesKey", "CompactionQueue"]
