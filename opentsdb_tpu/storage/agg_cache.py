"""Materialized partial aggregates: block-cached downsample grids.

ROADMAP item 2 (the overlapping-window reuse tentpole): millions of
dashboard users issue the SAME metrics on overlapping, sliding windows
all day, yet every `/api/query` used to recompute its full
scan->downsample->aggregate pipeline from scratch.  This module caches
the expensive middle of that pipeline — the per-(series, window)
downsample grid — in alignable, reusable factors, in the Factor Windows
stance (arXiv:2008.12379): decompose each fixed-interval downsample
plan into aligned sub-window blocks, reuse every cached block, and
dispatch only the uncovered delta ranges.  Which factors are worth
materializing is decided per plan by the fitted costmodel
(`ops/costmodel.py` predict_* via obs.jaxprof.stage_breakdown) plus a
repeat-count admission rule, the Storyboard placement question
(arXiv:2002.03063) reduced to: populate once a plan family has proven
it repeats, serve from cache the moment anything is covered.

The cached unit
---------------

One **block** = B consecutive windows of one (store, metric, downsample
function, interval, fill, platform, series-set) plan family, aligned to
the ABSOLUTE window grid (block k covers windows [k*B, (k+1)*B) of the
epoch-anchored grid), holding the finished per-(series, window)
downsample values + mask exactly as `ops.downsample.downsample`
produced them for that block's sub-range.  Blocks are aligned, so every
overlapping/sliding query over the same plan family lands on the same
block keys — the Factor Windows alignment property.  Only windows fully
inside the query range are ever cached (edge windows see a partial
point population and are recomputed per query); rate / group-by /
cross-series aggregation always run fresh on the assembled grid (they
cross window and series boundaries), via the SAME `run_grid_tail`
program the streaming executor finishes with.

Bit-identity contract (the correctness gate)
--------------------------------------------

A cache hit must never change an answer: a warm query's result is
bit-identical to the same query against the same data with the cache
EMPTY, because a cold run executes the very same per-block compiled
programs whose outputs a warm run replays — same shapes, same kernels,
same platform (the execution platform is part of the block key, and the
mode-policy epoch is too, so an autotune flip can never splice
kernels).  tests/test_agg_cache.py pins cold == warm == invalidated-
and-recomputed bitwise on random float data, and cache-enabled ==
cache-disabled bitwise on exactly-representable data; against the
monolithic (cache-disabled) pipeline on arbitrary floats the decomposed
evaluation carries the same last-ulp reassociation latitude as the
streamed path (same 1e-9 contract, docs/caching.md).

Invalidation (incremental, on ingest)
-------------------------------------

The memstore write path calls `note_mutation(metric, lo_ms, hi_ms)`
AFTER the point lands (write-then-mark): by the time a write is acked
its mark exists, so any block built from a pre-write read fails its
generation check — an acked write is never served stale.  (The
inverse order had a hole: a plan snapshotting between the mark and the
write would carry the mark's generation and dodge it forever; with
write-then-mark, a mark no newer than a plan's snapshot implies its
write landed before the plan's reads.)  Marks are (generation,
time-range) records per (store, metric); a block entry is valid only
when no mark newer than its build generation overlaps its window
range, so an append at `now` invalidates ONLY the block under `now` —
historical blocks keep serving, which is what makes the cache survive
continuous ingest.  The mark ring is bounded: overflow raises the
floor generation, which conservatively invalidates everything older
(never serves stale).  tsdblint's cache-coherence analyzer holds the
declared backing store to its registered invalidator (`invalidate`
below); gutting the invalidator fails the tree
(tests/test_agg_cache.py::test_gutting_the_agg_invalidator_fails_lint).

Two tiers
---------

Host tier: every cached block, numpy, byte-budgeted
(`tsd.query.cache.mb`, LRU).  Device tier: blocks that keep hitting
(>= `tsd.query.cache.promote_hits`) get an HBM mirror beside
storage/device_cache.py's column cache (`tsd.query.cache.device_mb`,
own LRU) — when every piece of an assembled grid is device-resident
the tail dispatch consumes it with zero host->device traffic.

This module stays importable numpy-only (the device tier lazy-imports
jax), like the rest of storage/.
"""

from __future__ import annotations

import logging
import threading
from collections import deque
from dataclasses import dataclass, field

import numpy as np

from opentsdb_tpu.obs.registry import REGISTRY

_LOG = logging.getLogger("agg_cache")

# bytes per cached grid cell: float64 value + bool mask
_BYTES_PER_CELL = 9

# bound on retained (generation, range) dirty marks per store: overflow
# raises the floor generation (conservative full invalidation for older
# entries), so the ring can never grow with ingest volume
_MARK_RING = 512

# host batch-build cost per point (build_batch_direct: per-series lock +
# columnar copy into the padded batch) charged to BOTH sides of the
# rewrite-vs-recompute decision — the monolithic path copies every
# point, the rewrite only the uncovered delta, and a warm hit none.
# A rough memcpy+locking figure, deliberately conservative; the device
# stages use the calibrated costmodel, this host stage has no
# calibration channel (yet).
_HOST_BUILD_S_PER_POINT = 5e-9


def _pow2_at_least(n: int) -> int:
    p = 1
    while p < max(int(n), 1):
        p <<= 1
    return p


@dataclass
class _Block:
    """One cached block: the finished [S, B] downsample grid slice."""
    store: object            # strong ref — pins id(store)
    metric: int
    rows: dict               # Series object -> row index (identity keyed:
    #                          a deleted+recreated series never matches)
    val: np.ndarray          # [S, B] float64
    mask: np.ndarray         # [S, B] bool
    gen: int                 # build generation (mark-ring validation)
    lo_ms: int               # block window-range [lo_ms, hi_ms] inclusive
    hi_ms: int
    nbytes: int = 0
    # host-tier LRU order is the _blocks dict order (move-to-end on
    # every consult, evict from the front)
    hits: int = 0            # serves; promotion queues past the bar
    val_dev: object = None   # device-tier mirror (None = host only)
    mask_dev: object = None
    dev_tick: int = 0        # device-tier LRU clock


@dataclass
class PlanPiece:
    """One window-contiguous slice of a rewritten plan."""
    first_ms: int            # absolute ms of the piece's first window
    count: int               # windows in this piece
    fetch_lo: int            # inclusive point-fetch range
    fetch_hi: int
    block: int | None = None  # absolute block index (cacheable pieces)
    cached: tuple | None = None   # (val, mask) when served from cache
    tier: str = ""           # 'agg_host' | 'agg_device' for cache hits
    # device-tier hits carry the ENTRY's full row set; the planner
    # narrows to the query's rows with this index vector (on device)
    rows: object = None


@dataclass
class RewritePlan:
    """The executable decomposition `plan()` hands the planner."""
    pieces: list
    gen0: int                # generation snapshot taken at plan time
    family: tuple            # (store_id, metric, ds_fn, interval, fill...)
    store: object
    metric: int
    interval_ms: int
    platform: str
    decision: dict = field(default_factory=dict)

    @property
    def cached_windows(self) -> int:
        return sum(p.count for p in self.pieces if p.cached is not None)

    @property
    def computed_windows(self) -> int:
        return sum(p.count for p in self.pieces if p.cached is None)


class AggregateCache:
    """Two-tier block cache of per-(series, window) partial aggregates."""

    def __init__(self, config):
        block = config.get_int("tsd.query.cache.block_windows")
        # pow2 block span: block dispatch shapes stay jit-stable and the
        # padded window count equals the block count exactly
        self.block_windows = _pow2_at_least(block)
        self.max_bytes = config.get_int("tsd.query.cache.mb") * 2 ** 20
        self.device_max_bytes = config.get_int(
            "tsd.query.cache.device_mb") * 2 ** 20
        self.min_repeats = max(config.get_int(
            "tsd.query.cache.min_repeats"), 1)
        self.promote_hits = max(config.get_int(
            "tsd.query.cache.promote_hits"), 1)
        self.amortize_horizon = max(config.get_int(
            "tsd.query.cache.amortize_horizon"), 1)
        self.dispatch_overhead_s = config.get_int(
            "tsd.query.cache.dispatch_overhead_us") * 1e-6
        # flight recorder (obs/flightrec.py), attached by the TSDB
        # after construction: mark-ring overflows and device-tier
        # demotions are retained diagnostics
        self.recorder = None
        self._lock = threading.Lock()
        # the cached blocks — THE backing store of this cache; dropped
        # wholesale by `invalidate()` (targeted drops are generation-
        # based: see _marks below)
        # cache: agg-blocks invalidated-by: invalidate
        self._blocks = {}  # guarded-by: _lock
        # (store_id, metric, ds_fn, interval) -> {block keys}: the
        # admission estimate's coverage() walks one family, not the
        # whole store  # guarded-by: _lock
        self._family_index: dict[tuple, set] = {}
        # (store_id, metric) -> deque[(gen, lo_ms, hi_ms)] dirty marks
        self._marks: dict[tuple, deque] = {}  # guarded-by: _lock
        # (store_id, metric) -> floor generation: entries built before
        # it are unconditionally invalid (mark-ring overflow safety)
        self._floor: dict[tuple, int] = {}  # guarded-by: _lock
        self._gen = 0  # guarded-by: _lock
        # newest generation any plan() has snapshotted: marks younger
        # than it merge in place instead of appending (per-point ingest
        # would otherwise append one mark per write)  # guarded-by: _lock
        self._planned_gen = 0
        # ingest fast path: until the FIRST plan commits to this cache,
        # note_mutation returns without taking the lock — a deployment
        # whose queries never cache pays nothing per write.  Sticky
        # once set; written only under _lock (in plan(), strictly
        # BEFORE that plan's executor reads any store data), read
        # without it: a writer that sees False checked after its write
        # landed, so any later plan's reads see that write — no mark
        # needed.  GIL-ordered attribute access; never cleared.
        self._maybe_cached = False  # guarded-by: _lock (writes; reads race)
        self._host_bytes = 0  # guarded-by: _lock
        self._dev_tick = 0  # guarded-by: _lock
        self._dev_bytes = 0  # guarded-by: _lock
        # plan-family repeat counts (the Storyboard materialization
        # admission rule)  # guarded-by: _lock
        self._repeats: dict[tuple, int] = {}
        # block keys awaiting a device-tier mirror: served-enough
        # blocks queue here and the maintenance thread pays the
        # host->HBM upload (promote_pending), never the query path
        # guarded-by: _lock
        self._promote_pending: set = set()
        # stats (mirrored to /api/stats via collect_stats and to
        # prometheus via the tsd.query.cache.* registry families)
        # guarded-by: _lock
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.invalidations = 0
        self.rewrites = 0
        self.populated = 0

    # -- metrics helpers -------------------------------------------------

    @staticmethod
    def _count_hit(tier: str) -> None:
        REGISTRY.counter(
            "tsd.query.cache.hits",
            "Query-cache hits, by tier (device_series HBM columns, "
            "agg_host / agg_device partial-aggregate blocks)").labels(
                tier=tier).inc()

    @staticmethod
    def _count_miss(tier: str) -> None:
        REGISTRY.counter(
            "tsd.query.cache.misses",
            "Query-cache misses, by tier").labels(tier=tier).inc()

    @staticmethod
    def _count_eviction(tier: str) -> None:
        REGISTRY.counter(
            "tsd.query.cache.evictions",
            "Query-cache evictions, by tier").labels(tier=tier).inc()

    def _set_byte_gauges_locked(self) -> None:
        REGISTRY.gauge(
            "tsd.query.cache.bytes",
            "Query-cache resident bytes, by tier").labels(
                tier="agg_host").set(self._host_bytes)
        REGISTRY.gauge(
            "tsd.query.cache.bytes",
            "Query-cache resident bytes, by tier").labels(
                tier="agg_device").set(self._dev_bytes)
        REGISTRY.gauge(
            "tsd.query.cache.entries",
            "Query-cache resident entries, by tier").labels(
                tier="agg_host").set(len(self._blocks))

    # -- invalidation ----------------------------------------------------

    def note_mutation(self, metric: int, lo_ms: int | None,
                      hi_ms: int | None, store=None) -> None:
        """Ingest-side hook (memstore mutation listener): mark the
        affected (metric, sub-window) range dirty, called AFTER the
        write lands (write-then-mark — see the module docstring).
        Routes to `invalidate` — the registered invalidator the
        cache-coherence lint holds this cache to."""
        if not self._maybe_cached:
            # nothing has ever been (or is being) materialized: the
            # hot ingest path skips the cache lock entirely.  Sound
            # because this read happens after the caller's write
            # landed, and plan() raises the flag before its executor
            # reads any store data — see the flag's declaration.
            return
        self.invalidate(store=store, metric=metric, lo_ms=lo_ms,
                        hi_ms=hi_ms)

    def invalidate(self, store=None, metric: int | None = None,
                   lo_ms: int | None = None,
                   hi_ms: int | None = None) -> None:
        """THE invalidation entry point (registered in the `# cache:`
        declaration above `_blocks`).

        With a metric: record a dirty mark over [lo_ms, hi_ms] (None
        bounds = open) — block entries overlapping the range fail their
        generation check from now on, everything else keeps serving.
        Without a metric: drop everything (/api/dropcaches)."""
        overflowed = False
        with self._lock:
            if metric is None:
                self.invalidations += 1
                self._blocks = {}
                self._family_index.clear()
                self._marks.clear()
                self._floor.clear()
                self._promote_pending.clear()
                self._dev_bytes = 0
                self._host_bytes = 0
                self._gen += 1
                self._set_byte_gauges_locked()
            else:
                lo = -2 ** 62 if lo_ms is None else int(lo_ms)
                hi = 2 ** 62 if hi_ms is None else int(hi_ms)
                key = (id(store), metric)
                ring = self._marks.get(key)
                if ring is None:
                    ring = self._marks[key] = deque(maxlen=_MARK_RING)
                if ring and ring[-1][0] > self._planned_gen:
                    # no plan has snapshotted since the newest mark: no
                    # entry can carry a generation between it and now,
                    # so widening it in place invalidates exactly the
                    # same set — per-point ingest coalesces to one mark
                    # (and deliberately skips the counter: it IS the
                    # same mark)
                    g, plo, phi = ring[-1]
                    ring[-1] = (g, min(plo, lo), max(phi, hi))
                    return
                self.invalidations += 1
                self._gen += 1
                if len(ring) == _MARK_RING:
                    # overflow: everything at least as old as the
                    # evicted mark becomes unconditionally invalid
                    oldest = ring[0]
                    self._floor[key] = max(self._floor.get(key, 0),
                                           oldest[0])
                    overflowed = True
                ring.append((self._gen, lo, hi))
        if overflowed and self.recorder is not None:
            # diagnosable event: hot ingest outran the mark ring and a
            # floor generation now hides history for this metric —
            # warm repeats will recompute until blocks rebuild
            self.recorder.record("agg_mark_overflow", metric=metric)
        REGISTRY.counter(
            "tsd.query.cache.invalidations",
            "Query-cache invalidation marks (ingest dirty ranges, "
            "dropcaches), by tier").labels(tier="agg").inc()

    def _valid_locked(self, entry: _Block) -> bool:
        key = (id(entry.store), entry.metric)
        if entry.gen < self._floor.get(key, 0):
            return False
        ring = self._marks.get(key)
        if not ring:
            return True
        for gen, lo, hi in reversed(ring):
            if gen <= entry.gen:
                break
            if lo <= entry.hi_ms and hi >= entry.lo_ms:
                return False
        return True

    # -- planning --------------------------------------------------------

    # effects: observe-gated(observe)
    def plan(self, store, metric: int, series_list, windows,
             start_ms: int, end_ms: int, ds_fn: str,
             fill_policy: str, fill_value, platform: str,
             s: int, n_max: int, g_pad: int, has_rate: bool,
             total_points: int = 0, observe: bool = True):
        """Rewrite decision for one fixed-grid downsample segment.

        Returns (RewritePlan | None, decision dict).  None means
        recompute monolithically; the decision dict always comes back
        for the trace span (PR 6 contract: strategy decisions are
        visible per query).

        ``observe=False`` is the EXPLAIN engine's dry-run arm: the
        same verdict from the same state, with ZERO bookkeeping — the
        repeat count is read but not advanced (an explain must not
        walk a family toward ``min_repeats``), LRU recency and
        ``_planned_gen`` stay put, stale blocks are left for the real
        pass to reap, and no hit/miss/rewrite accounting fires.
        Because the executor's own ``plan()`` prices with the count
        BEFORE its increment, a dry-run at the same instant computes
        the identical decision (the explain-vs-actual parity pin)."""
        from opentsdb_tpu.obs import jaxprof
        from opentsdb_tpu.ops.downsample import (mode_policy_epoch,
                                                 pad_pow2)
        interval = windows.interval_ms
        first = windows.first_window_ms
        w = windows.count
        decision = {"decision": "recompute", "reason": "",
                    "coverage": 0.0, "cachedWindows": 0,
                    "computedWindows": w}
        a0 = first // interval                      # absolute window idx
        wf_lo = 0 if start_ms <= first else 1
        last_start = first + (w - 1) * interval
        wf_hi = w - 1 if last_start + interval - 1 <= end_ms else w - 2
        bw = self.block_windows
        if wf_hi < wf_lo:
            decision["reason"] = "no_full_windows"
            return None, decision
        a_lo, a_hi = a0 + wf_lo, a0 + wf_hi
        k_lo = -(-a_lo // bw)                       # ceil div
        k_hi = (a_hi + 1) // bw - 1
        if k_hi < k_lo:
            decision["reason"] = "no_full_blocks"
            return None, decision

        epoch = mode_policy_epoch()
        sig = hash(tuple(sorted(id(srs) for srs in series_list)))
        family = (id(store), metric, ds_fn, interval, fill_policy,
                  float(fill_value), platform, sig)

        pieces: list[PlanPiece] = []
        head_count = k_lo * bw - a0
        if head_count > 0:
            pieces.append(PlanPiece(
                first_ms=first, count=head_count,
                fetch_lo=start_ms,
                fetch_hi=first + head_count * interval - 1))
        hits: list[PlanPiece] = []
        hit_entries: list[tuple] = []   # (block key, _Block) of hits
        missing: list[PlanPiece] = []
        with self._lock:
            gen0 = self._gen
            if observe:
                # stop mark-coalescing at this generation: entries
                # built from this plan must be invalidated by any
                # LATER write
                self._planned_gen = max(self._planned_gen, gen0)
                # pop-then-set keeps the dict in recency order, so the
                # overflow eviction drops the STALEST families — a
                # burst of one-off ad-hoc families must not wipe the
                # hot dashboards' repeat counts (that would re-impose
                # min_repeats on everything at once)
                repeats = self._repeats.pop(family, 0)
                self._repeats[family] = repeats + 1
                while len(self._repeats) > 4096:
                    self._repeats.pop(next(iter(self._repeats)))
            else:
                repeats = self._repeats.get(family, 0)
            for k in range(k_lo, k_hi + 1):
                piece = PlanPiece(
                    first_ms=k * bw * interval, count=bw,
                    fetch_lo=k * bw * interval,
                    fetch_hi=(k + 1) * bw * interval - 1, block=k)
                key = family + (epoch, k)
                entry = self._blocks.get(key)
                if entry is not None and self._valid_locked(entry) and \
                        all(srs in entry.rows for srs in series_list):
                    rows = np.fromiter(
                        (entry.rows[srs] for srs in series_list),
                        np.int64, count=len(series_list))
                    if observe:
                        # LRU recency = dict order (move-to-end):
                        # eviction pops from the front in O(1) instead
                        # of a min() scan over every resident block
                        self._blocks.pop(key)
                        self._blocks[key] = entry
                    if entry.val_dev is not None:
                        if observe:
                            self._dev_tick += 1
                            entry.dev_tick = self._dev_tick
                        piece.cached = (entry.val_dev, entry.mask_dev)
                        piece.tier = "agg_device"
                    else:
                        # refs only under the lock — the fancy-index
                        # row copies happen after release (blocks are
                        # immutable once stored, and the copy is the
                        # expensive part a hot ingest path would
                        # otherwise wait on)
                        piece.cached = (entry.val, entry.mask)
                        piece.tier = "agg_host"
                    # device mirrors hold the FULL row set; narrow to
                    # the query's rows outside the lock (device gather)
                    piece.rows = rows
                    hits.append(piece)
                    hit_entries.append((key, entry))
                else:
                    if entry is not None and observe:
                        # stale or row-incomplete: drop so the rebuild
                        # below can take its slot
                        self._drop_locked(key)
                    missing.append(piece)
                pieces.append(piece)
        # hit pieces carry REFS + a row index; the executor narrows
        # them (outside this lock, only for plans that actually serve,
        # and not at all when the rows are the identity — the common
        # exact-repeat case serves blocks zero-copy)
        tail_off = (k_hi + 1) * bw - a0
        if tail_off < w:
            pieces.append(PlanPiece(
                first_ms=first + tail_off * interval,
                count=w - tail_off,
                fetch_lo=first + tail_off * interval,
                fetch_hi=end_ms))

        cached_windows = sum(p.count for p in hits)
        computed_windows = w - cached_windows
        decision.update(
            coverage=round(cached_windows / max(w, 1), 4),
            cachedWindows=cached_windows,
            computedWindows=computed_windows,
            blocks=k_hi - k_lo + 1, blockHits=len(hits),
            repeats=repeats)

        if hits and not missing and cached_windows >= w - 2:
            # full (or all-but-edge-window) coverage: nothing worth
            # pricing — serving the replay beats any recompute, and
            # the per-query stage_breakdown (~ms of pure decision
            # work) would tax exactly the hot path the cache exists
            # to shrink
            decision.update(decision="rewrite", reason="reuse")
            if observe:
                for p in hits:
                    self._count_hit(p.tier)
                with self._lock:
                    self._maybe_cached = True
                    self.rewrites += 1
                    self.hits += len(hits)
                    self._note_serves_locked(hit_entries)
            return RewritePlan(pieces=pieces, gen0=gen0, family=family,
                               store=store, metric=metric,
                               interval_ms=interval, platform=platform,
                               decision=decision), decision

        # costmodel: price the rewrite vs the monolithic recompute.
        # Both sides carry their device stages (the calibrated
        # predict_* via stage_breakdown), their host batch-build cost
        # (proportional to the points each side copies), and one
        # dispatch-overhead charge per dispatch they issue.
        wp = pad_pow2(w)
        build_s = total_points * _HOST_BUILD_S_PER_POINT
        full_bd = jaxprof.stage_breakdown(platform, s, pad_pow2(n_max),
                                          wp, g_pad, ds_fn, has_rate)
        ds_s = full_bd.get("downsample", 0.0)
        tail_s = sum(full_bd.values()) - ds_s
        pred_full = sum(full_bd.values()) + build_s \
            + self.dispatch_overhead_s
        pred_rw = tail_s + self.dispatch_overhead_s
        for p in pieces:
            if p.cached is not None:
                continue
            # per-piece downsample/build cost approximated as the
            # window-proportional share of the full plan's (one
            # stage_breakdown per plan, not per piece — the decision
            # runs on every eligible query and must stay cheap)
            share = p.count / max(w, 1)
            pred_rw += (ds_s + build_s) * share \
                + self.dispatch_overhead_s
        # a fully-warm repeat costs roughly the tail plus the edge
        # pieces; what a hit SAVES per query is the monolithic
        # downsample + build share minus that
        pred_warm = tail_s + 2 * self.dispatch_overhead_s
        per_hit_saving = pred_full - pred_warm
        decision["predictedRewriteMs"] = round(pred_rw * 1e3, 3)
        decision["predictedFullMs"] = round(pred_full * 1e3, 3)
        decision["perHitSavingMs"] = round(per_hit_saving * 1e3, 3)

        if cached_windows == 0:
            if repeats + 1 < self.min_repeats:
                decision["reason"] = "below_min_repeats"
                return None, decision
            # Storyboard's materialization question, amortized: the
            # populate overhead must be recoverable within the horizon
            # of expected repeats.  Dispatch-floor-dominated plans
            # (per-hit saving <= 0) honestly never cache.
            if per_hit_saving <= 0.0 or \
                    pred_rw - pred_full > \
                    self.amortize_horizon * per_hit_saving:
                decision["reason"] = "populate_unamortizable"
                return None, decision
            decision["reason"] = "cold_populate"
        elif pred_rw <= pred_full * 1.25:
            # serving cached factors beats recompute outright (25%
            # slack keeps a populated family from flapping on
            # prediction noise)
            decision["reason"] = "reuse"
        elif per_hit_saving > 0.0 and \
                pred_rw - pred_full <= \
                self.amortize_horizon * per_hit_saving:
            # partially invalidated (ingest dirtied some blocks):
            # recomputing the missing blocks costs more than one
            # monolithic pass NOW but restores full coverage — the
            # same amortization rule that admitted the cold populate
            # admits the heal, otherwise a family that keeps taking
            # writes would recompute monolithically forever
            decision["reason"] = "heal_populate"
        else:
            decision["reason"] = "recompute_cheaper"
            return None, decision
        decision["decision"] = "rewrite"
        # hit/miss accounting only for plans that actually serve — a
        # consulted-but-recomputed plan must not inflate the hit rate
        if observe:
            for p in hits:
                self._count_hit(p.tier)
            for _p in missing:
                self._count_miss("agg_host")
            with self._lock:
                # committing to materialize/serve: arm the ingest-side
                # mark path BEFORE the executor reads any store data
                self._maybe_cached = True
                self.rewrites += 1
                self.hits += len(hits)
                self.misses += len(missing)
                self._note_serves_locked(hit_entries)
        return RewritePlan(pieces=pieces, gen0=gen0, family=family,
                           store=store, metric=metric,
                           interval_ms=interval, platform=platform,
                           decision=decision), decision

    # -- population ------------------------------------------------------

    def store_block(self, plan: RewritePlan, piece: PlanPiece,
                    series_list, val: np.ndarray, mask: np.ndarray,
                    epoch: int) -> None:
        """Insert one computed block, unless a dirty mark younger than
        the plan's generation snapshot overlaps it (the mark could have
        landed after the block's points were read — conservatively
        discard; the next query recomputes)."""
        rows = {srs: i for i, srs in enumerate(series_list)}
        entry = _Block(store=plan.store, metric=plan.metric, rows=rows,
                       val=val, mask=mask, gen=plan.gen0,
                       lo_ms=piece.fetch_lo, hi_ms=piece.fetch_hi,
                       nbytes=val.shape[0] * val.shape[1]
                       * _BYTES_PER_CELL)
        key = plan.family + (epoch, piece.block)
        with self._lock:
            if not self._valid_locked(entry):
                return
            if entry.nbytes > self.max_bytes:
                return
            self._evict_for_locked(entry.nbytes)
            old = self._blocks.get(key)
            if old is not None:
                self._drop_locked(key)
            # insertion at the dict tail IS the LRU recency position
            self._blocks[key] = entry
            self._host_bytes += entry.nbytes
            self._family_index.setdefault(key[:4], set()).add(key)
            self.populated += 1
            self._set_byte_gauges_locked()

    def _note_serves_locked(self, hit_entries: list) -> None:
        """Record that these blocks actually SERVED an answer (plans
        that consult but recompute must not accrue hits — a never-
        serving block would otherwise earn a device mirror) and queue
        the ones past the promotion bar for the maintenance thread."""
        for key, entry in hit_entries:
            entry.hits += 1
            if entry.val_dev is None \
                    and entry.hits >= self.promote_hits \
                    and 0 < entry.nbytes <= self.device_max_bytes:
                # oversized blocks never queue: a mirror bigger than
                # the whole device budget would overcommit HBM and
                # then demote/re-upload forever
                self._promote_pending.add(key)

    def promote_pending(self, max_uploads: int = 8) -> int:
        """Mirror queued hot host-tier blocks into the device tier.

        Called from the maintenance thread (and directly by tests/
        benches standing in for it): the host->HBM uploads are paid
        OFF the query path, like the device series cache's refresh().
        Returns the number of blocks mirrored."""
        if self.device_max_bytes <= 0:
            return 0
        import jax
        done = 0
        for _ in range(max_uploads):
            with self._lock:
                if not self._promote_pending:
                    break
                key = self._promote_pending.pop()
                entry = self._blocks.get(key)
            if entry is None or entry.val_dev is not None:
                continue
            val_dev = jax.device_put(entry.val)
            mask_dev = jax.device_put(entry.mask)
            with self._lock:
                if self._blocks.get(key) is not entry:
                    continue        # evicted while uploading
                self._evict_device_for_locked(entry.nbytes)
                entry.val_dev = val_dev
                entry.mask_dev = mask_dev
                self._dev_tick += 1
                entry.dev_tick = self._dev_tick
                self._dev_bytes += entry.nbytes
                self._set_byte_gauges_locked()
                done += 1
        return done

    # -- eviction --------------------------------------------------------

    def _drop_locked(self, key: tuple) -> None:
        entry = self._blocks.pop(key, None)
        if entry is None:
            return
        self._host_bytes -= entry.nbytes
        if entry.val_dev is not None:
            self._dev_bytes -= entry.nbytes
        fam = self._family_index.get(key[:4])
        if fam is not None:
            fam.discard(key)
            if not fam:
                self._family_index.pop(key[:4], None)

    def _evict_for_locked(self, incoming: int) -> None:
        while self._blocks and \
                self._host_bytes + incoming > self.max_bytes:
            # dict order is LRU order (move-to-end on consult): the
            # front IS the least-recently-used block, O(1) per victim
            key = next(iter(self._blocks))
            self._drop_locked(key)
            self.evictions += 1
            self._count_eviction("agg_host")

    def _evict_device_for_locked(self, incoming: int) -> None:
        while self._dev_bytes + incoming > self.device_max_bytes:
            candidates = [(k, b) for k, b in self._blocks.items()
                          if b.val_dev is not None]
            if not candidates:
                break
            key, victim = min(candidates,
                              key=lambda kb: kb[1].dev_tick)
            victim.val_dev = None
            victim.mask_dev = None
            self._dev_bytes -= victim.nbytes
            self.evictions += 1
            self._count_eviction("agg_device")

    # -- admission-estimate support --------------------------------------

    def coverage(self, store, metric: int, interval_ms: int, ds_fn: str,
                 start_ms: int, end_ms: int) -> float:
        """Fraction of the plan's windows served from valid cached
        blocks, for tsd/admission.py's pre-admission cost estimate (the
        rewritten plan is what should be priced, not the original).
        Approximate: ignores fill/platform/series-set key components
        (scans every family of the (store, metric, ds_fn, interval))."""
        if interval_ms <= 0:
            return 0.0
        bw = self.block_windows
        first = start_ms - start_ms % interval_ms
        w = (end_ms - end_ms % interval_ms - first) // interval_ms + 1
        if w <= 0:
            return 0.0
        covered: set[int] = set()
        with self._lock:
            fam = self._family_index.get(
                (id(store), metric, ds_fn, interval_ms), ())
            for key in fam:
                entry = self._blocks.get(key)
                if entry is None:
                    continue
                k = key[-1]
                if k * bw * interval_ms >= first and \
                        (k + 1) * bw * interval_ms - 1 <= end_ms and \
                        self._valid_locked(entry):
                    covered.add(k)
        return min(len(covered) * bw / w, 1.0)

    # -- stats -----------------------------------------------------------

    def collect_stats(self) -> dict:
        with self._lock:
            host_bytes = self._host_bytes
            return {
                "tsd.query.agg_cache.hits": float(self.hits),
                "tsd.query.agg_cache.misses": float(self.misses),
                "tsd.query.agg_cache.evictions": float(self.evictions),
                "tsd.query.agg_cache.invalidations": float(
                    self.invalidations),
                "tsd.query.agg_cache.rewrites": float(self.rewrites),
                "tsd.query.agg_cache.populated": float(self.populated),
                "tsd.query.agg_cache.entries": float(len(self._blocks)),
                "tsd.query.agg_cache.bytes": float(host_bytes),
                "tsd.query.agg_cache.device_bytes": float(
                    self._dev_bytes),
            }

    def __len__(self) -> int:
        with self._lock:
            return len(self._blocks)
