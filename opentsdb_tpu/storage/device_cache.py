"""Device-resident series cache: hot columnar data lives in HBM.

The TPU-native analog of the reference's storage-side block caching (the
HBase BlockCache that made repeated scans of hot rows memory-speed; the
reference leans on it implicitly — every SaltScanner pass re-reads the
same regions, SaltScanner.java:269).  Here the roles are inverted: the
store is host RAM, the accelerator is across a PCIe/tunnel link, and the
dominant cost of a repeated `/api/query` is re-uploading the same raw
points every dispatch.  This cache pins each hot metric's columnar data
in device HBM once; subsequent queries gather their [S, N] window batch
ON DEVICE in a single dispatch — zero host->device traffic for the data
itself (only the tiny per-series start/length vectors travel).

Design:

  * One entry per metric: every series' normalized (ts, val) columns
    concatenated into two 1-D device buffers (padded to pow2 length to
    bound gather recompiles), plus host-side row offsets.
  * Consistency is by content-version, not locks: `Series.snapshot()`
    captures (data, version) atomically; at query time
    `Series.window_bounds()` returns (lo, hi, version) atomically.  A
    version mismatch on ANY requested series is a miss — the planner
    falls back to the host build path, and the entry is queued for a
    background refresh (the maintenance thread calls `refresh()`), so
    ingest-heavy metrics never pay rebuild costs on the query path.
  * Byte-budgeted LRU (`tsd.query.device_cache.mb`): entries evict
    least-recently-used first; metrics larger than the whole budget (or
    `tsd.query.device_cache.build_max_points`) are never cached — the
    streaming path owns beyond-memory scans.

Only the float lane is cached: the grouped downsample pipeline (the hot
path this accelerates) always runs in float (Downsampler.java:257 —
downsampled values are doubles).  Queries needing the exact-int lane
(raw union aggregation of all-int series) take the host path unchanged.
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass, field

import numpy as np

_LOG = logging.getLogger("device_cache")

# The padding contract (sentinel + pow2 growth) MUST stay bit-identical to
# build_batch's — the prefix downsample path relies on cached rows sorting
# exactly like host-built rows.  PAD_TS mirrors ops.pipeline.PAD_TS and
# pad_pow2 is lazy-imported from ops.downsample inside the functions that
# use it: a module-level import would pull jax into every storage import,
# and this module must stay importable numpy-only (tests assert the PAD_TS
# parity so the mirror cannot drift silently).
PAD_TS = np.iinfo(np.int64).max
# Pad sentinel for int32 pre-compacted batches (the ts_base gather):
# mirrors ops.downsample._I32_PAD under the same no-jax-import rule;
# the parity test pins the two (clean-batch detection and pad sorting
# both depend on the exact value).
I32_PAD_TS = np.int32(2**31 - 2)
_BYTES_PER_POINT = 16  # int64 ts + float64 val


def _pad_pow2(n: int, floor: int = 8) -> int:
    from opentsdb_tpu.ops.downsample import pad_pow2
    return pad_pow2(n, floor)


@dataclass
class _Entry:
    store: object      # the MemStore snapshotted (raw store or a rollup
    #                    lane) — entries are keyed by (store, metric), and
    #                    the strong ref also keeps id(store) stable
    metric: int
    row: dict          # SeriesKey -> row index
    series_objs: list  # row -> the Series OBJECT snapshotted: identity is
    #                    part of validity — a deleted+recreated series has an
    #                    equal key and a restarted version counter, and must
    #                    not validate against the old snapshot
    versions: list     # row -> version at snapshot
    offsets: np.ndarray  # [S+1] int64 start offsets into the buffers
    ts_dev: object     # device [P] int64 (pow2-padded, pads PAD_TS)
    val_dev: object    # device [P] float64
    nbytes: int = 0
    tick: int = 0      # LRU clock
    stale: bool = field(default=False)


class DeviceSeriesCache:
    """Byte-budgeted, version-validated device cache of metric columns."""

    def __init__(self, max_bytes: int, build_max_points: int = 200_000_000,
                 fix_duplicates: bool = True,
                 batch_max_bytes: int = 6 << 30):
        self.max_bytes = int(max_bytes)
        self.build_max_points = int(build_max_points)
        # The gather EXPANDS the packed buffer to a padded [S, N] batch;
        # row-length skew can make that much larger than the entry itself.
        # Batches estimated beyond this bound decline (the streaming path
        # serves them chunked instead of OOMing the device).
        self.batch_max_bytes = int(batch_max_bytes)
        # The store-wide duplicate policy: snapshots must normalize with
        # EXACTLY the policy reads use — with fix_duplicates off, a build
        # touching duplicate data must fail (and never silently dedup the
        # live series out from under fsck).
        self.fix_duplicates = bool(fix_duplicates)
        # keyed by (id(store), metric): the raw store and every rollup
        # lane share the metric-uid space but hold different data
        # guarded-by: _lock
        self._entries: dict[tuple, _Entry] = {}
        self._stale: dict[tuple, object] = {}  # key -> store  # guarded-by: _lock
        self._building: set[tuple] = set()  # guarded-by: _lock
        self._lock = threading.Lock()
        self._tick = 0  # guarded-by: _lock
        # stats (surfaced via /api/stats)
        # guarded-by: _lock
        self.hits = 0
        self.misses = 0
        self.builds = 0
        self.evictions = 0

    # -- sizing ----------------------------------------------------------

    @property
    def bytes_used(self) -> int:
        with self._lock:
            return sum(e.nbytes for e in self._entries.values())

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    # -- query path ------------------------------------------------------

    def batch_for(self, store, metric: int, series_list, start_ms: int,
                  end_ms: int, fix_duplicates: bool = True,
                  build: bool = True, ts_base: int | None = None):
        """Device [S, N] (ts, val, mask) for the series' windows, or None.

        A None return means cold/stale/over-budget — the caller uses its
        host build path.  `build=False` declines to construct a cold entry
        inline and queues it for the maintenance-thread `refresh()`
        instead — callers pass it when they have a cheaper cold path (the
        streaming scan overlaps transfer with compute; a blocking full-
        metric upload first would be strictly worse).  Staleness likewise
        only ever queues a background rebuild.

        `ts_base` (from ops.downsample.precompact_base) asks the gather
        to emit timestamps as int32 offsets from that base — the query
        dispatch then skips its per-point compaction pass entirely.  The
        caller guarantees the window grid spans < 2^31 ms from the base;
        pads land at the int32 clip ceiling (sorted past every edge,
        mirroring the int64 PAD_TS contract).
        """
        ekey = (id(store), metric)
        with self._lock:
            entry = self._entries.get(ekey)
        if entry is None:
            if not build:
                with self._lock:
                    self._stale[ekey] = store
                self._count("misses")
                return None
            entry = self._build(store, metric)
            if entry is None:
                self._count("misses")
                return None
        s = len(series_list)
        starts = np.empty(s, np.int64)
        lengths = np.empty(s, np.int64)
        for i, series in enumerate(series_list):
            row = entry.row.get(series.key)
            if row is None or entry.series_objs[row] is not series:
                # a series born after the snapshot — or deleted and
                # recreated under the same key (fresh object, restarted
                # version counter): either way the snapshot is invalid
                self._mark_stale(ekey, entry)
                self._count("misses")
                return None
            try:
                lo, hi, version = series.window_bounds(start_ms, end_ms,
                                                       fix_duplicates)
            except ValueError:
                self._count("misses")
                return None     # unresolved duplicates: host path raises
            if version != entry.versions[row]:
                self._mark_stale(ekey, entry)
                self._count("misses")
                return None
            starts[i] = entry.offsets[row] + lo
            lengths[i] = hi - lo
        n = _pad_pow2(max(int(lengths.max(initial=0)), 1))
        # ts8+val8+mask1, or ts4+val8+mask1 for int32 pre-compacted
        # batches — the budget must not decline batches the smaller
        # layout actually fits
        per_point = 13 if ts_base is not None else 17
        if s * n * per_point > self.batch_max_bytes:
            self._count("misses")
            return None
        with self._lock:
            self._tick += 1
            entry.tick = self._tick
            self.hits += 1
        self._emit_hit()
        return _gather_windows(entry.ts_dev, entry.val_dev,
                               starts, lengths, n, ts_base)

    # effects: reads-only
    def peek(self, store, metric: int, series_list, start_ms: int,
             end_ms: int, fix_duplicates: bool = True,
             build: bool = True, ts_base: int | None = None) -> bool:
        """Would :meth:`batch_for` return a device batch for this
        request, as of now — READ-ONLY: no gather dispatch, no cold
        inline build, no staleness marks, no hit/miss accounting.  The
        EXPLAIN engine's arm of the routing decision
        (query/plandecision.py).

        The cold-with-``build`` arm predicts the inline snapshot build
        from its size/identity preconditions (series set, point
        budget, byte budget) without snapshotting; duplicate data that
        would only surface inside ``Series.snapshot`` is approximated
        by the same per-series ``window_bounds`` probe ``batch_for``
        itself uses."""
        ekey = (id(store), metric)
        with self._lock:
            entry = self._entries.get(ekey)
            building = ekey in self._building
        if entry is None:
            if not build or building:
                return False
            # the _build_guarded preconditions, probed without copying
            series_objs = store.series_for_metric(metric)
            if not series_objs:
                return False
            total = sum(len(s) for s in series_objs)
            nbytes = _pad_pow2(max(total, 1), floor=1024) \
                * _BYTES_PER_POINT
            if total > self.build_max_points or nbytes > self.max_bytes:
                return False
            rows = {s.key: s for s in series_objs}
            resolve = rows.get
        else:
            def resolve(key, _row=entry.row, _objs=entry.series_objs):
                row = _row.get(key)
                return None if row is None else _objs[row]
        max_len = 0
        for i, series in enumerate(series_list):
            if resolve(series.key) is not series:
                return False
            try:
                lo, hi, version = series.window_bounds(
                    start_ms, end_ms, fix_duplicates)
            except ValueError:
                return False        # unresolved duplicates: host path
            if entry is not None \
                    and version != entry.versions[entry.row[series.key]]:
                return False
            max_len = max(max_len, hi - lo)
        n = _pad_pow2(max(int(max_len), 1))
        per_point = 13 if ts_base is not None else 17
        return len(series_list) * n * per_point <= self.batch_max_bytes

    # -- build / refresh -------------------------------------------------

    # tier-labeled prometheus families shared with the partial-
    # aggregate cache (storage/agg_cache.py): the same
    # tsd.query.cache.* names, tier="device_series" — so one scrape
    # shows every cache layer side by side (before this, the tallies
    # only lived in collect_stats()).

    @staticmethod
    def _emit_hit() -> None:
        from opentsdb_tpu.obs.registry import REGISTRY
        REGISTRY.counter(
            "tsd.query.cache.hits",
            "Query-cache hits, by tier").labels(
                tier="device_series").inc()

    @staticmethod
    def _emit_miss() -> None:
        from opentsdb_tpu.obs.registry import REGISTRY
        REGISTRY.counter(
            "tsd.query.cache.misses",
            "Query-cache misses, by tier").labels(
                tier="device_series").inc()

    @staticmethod
    def _emit_evictions(n: int) -> None:
        from opentsdb_tpu.obs.registry import REGISTRY
        REGISTRY.counter(
            "tsd.query.cache.evictions",
            "Query-cache evictions, by tier").labels(
                tier="device_series").inc(n)

    def _emit_bytes(self) -> None:
        from opentsdb_tpu.obs.registry import REGISTRY
        REGISTRY.gauge(
            "tsd.query.cache.bytes",
            "Query-cache resident bytes, by tier").labels(
                tier="device_series").set(self.bytes_used)
        REGISTRY.gauge(
            "tsd.query.cache.entries",
            "Query-cache resident entries, by tier").labels(
                tier="device_series").set(len(self))

    def _count(self, name: str) -> None:
        with self._lock:
            setattr(self, name, getattr(self, name) + 1)
        if name == "misses":
            self._emit_miss()

    def _mark_stale(self, ekey: tuple, entry: _Entry) -> None:
        with self._lock:
            entry.stale = True
            self._stale[ekey] = entry.store

    def _build(self, store, metric: int):
        """Snapshot every series of `metric` into device buffers.

        At most one build per (store, metric) runs at a time: concurrent
        queries on the same cold metric miss fast (host path) instead of
        each paying the snapshot + upload."""
        ekey = (id(store), metric)
        with self._lock:
            if ekey in self._building:
                return None
            self._building.add(ekey)
        try:
            return self._build_guarded(store, metric)
        finally:
            with self._lock:
                self._building.discard(ekey)

    def _build_guarded(self, store, metric: int):
        series_list = store.series_for_metric(metric)
        if not series_list:
            return None
        total = sum(len(s) for s in series_list)
        nbytes = _pad_pow2(max(total, 1), floor=1024) * _BYTES_PER_POINT
        if total > self.build_max_points or nbytes > self.max_bytes:
            return None
        parts_ts, parts_val, versions, row = [], [], [], {}
        offsets = np.zeros(len(series_list) + 1, np.int64)
        try:
            for i, series in enumerate(series_list):
                ts, val, version = series.snapshot(self.fix_duplicates)
                parts_ts.append(ts)
                parts_val.append(val)
                versions.append(version)
                row[series.key] = i
                offsets[i + 1] = offsets[i] + len(ts)
        except ValueError:
            return None     # duplicate data pending fsck: don't cache it
        total = int(offsets[-1])
        p = _pad_pow2(max(total, 1), floor=1024)
        ts_buf = np.full(p, PAD_TS, np.int64)
        val_buf = np.zeros(p, np.float64)
        if total:
            ts_buf[:total] = np.concatenate(parts_ts)
            val_buf[:total] = np.concatenate(parts_val)
        entry = _Entry(store=store, metric=metric, row=row,
                       series_objs=series_list,
                       versions=versions, offsets=offsets,
                       ts_dev=_to_device(ts_buf), val_dev=_to_device(val_buf),
                       nbytes=p * _BYTES_PER_POINT)
        ekey = (id(store), metric)
        with self._lock:
            evicted_before = self.evictions
            self._evict_for_locked(entry.nbytes)
            evicted = self.evictions - evicted_before
            self._tick += 1
            entry.tick = self._tick
            self._entries[ekey] = entry
            self._stale.pop(ekey, None)
            self.builds += 1
        if evicted:
            self._emit_evictions(evicted)
        self._emit_bytes()
        return entry

    def _evict_for_locked(self, incoming_bytes: int) -> None:
        used = sum(e.nbytes for e in self._entries.values())
        while self._entries and used + incoming_bytes > self.max_bytes:
            victim = min(self._entries.values(), key=lambda e: e.tick)
            self._entries.pop((id(victim.store), victim.metric))
            used -= victim.nbytes
            self.evictions += 1

    def refresh(self, store=None, max_rebuilds: int = 4) -> int:
        """Rebuild up to `max_rebuilds` stale entries (maintenance hook).

        Runs off the query path: the background thread pays the re-upload
        so queries only ever see a fast hit or a fast miss.  Each stale
        key remembers its own store (raw store or rollup lane); the
        `store` argument is accepted for call-site symmetry but unused.
        """
        del store
        with self._lock:
            pending = list(self._stale.items())[:max_rebuilds]
            for ekey, _ in pending:
                self._stale.pop(ekey, None)
                self._entries.pop(ekey, None)
        done = 0
        for (_, metric), st in pending:
            if self._build(st, metric) is not None:
                done += 1
        return done

    def invalidate(self, metric: int | None = None) -> None:
        """Drop one metric's entry, or everything (/api/dropcaches)."""
        with self._lock:
            if metric is None:
                self._entries.clear()
                self._stale.clear()
            else:
                for ekey in [k for k in self._entries if k[1] == metric]:
                    self._entries.pop(ekey, None)
                for ekey in [k for k in self._stale if k[1] == metric]:
                    self._stale.pop(ekey, None)
        self._emit_bytes()

    def collect_stats(self) -> dict:
        return {
            "tsd.query.device_cache.hits": float(self.hits),
            "tsd.query.device_cache.misses": float(self.misses),
            "tsd.query.device_cache.builds": float(self.builds),
            "tsd.query.device_cache.evictions": float(self.evictions),
            "tsd.query.device_cache.entries": float(len(self)),
            "tsd.query.device_cache.bytes": float(self.bytes_used),
        }


def _to_device(arr: np.ndarray):
    import jax
    return jax.device_put(arr)


# compiled gather programs keyed by (padded N, compaction flag) — the
# closure reads only module constants (PAD_TS / I32_PAD_TS), so there
# is nothing to invalidate  # cache: gather-programs invalidated-by: none
_GATHER_CACHE: dict = {}


def _gather_windows(ts_buf, val_buf, starts, lengths, n: int,
                    ts_base: int | None = None):
    """One-dispatch on-device batch assembly from the pinned buffers.

    out[i, j] = buf[starts[i] + j] masked to j < lengths[i]; pads mirror
    build_batch (PAD_TS timestamps keep rows sorted for the prefix path).
    Compiled once per (buffer length, N) — both pow2-padded.

    With `ts_base`, timestamps come back as int32 offsets from the base
    (the compaction fused into this gather — the query dispatch already
    paying for this data pass makes the sub+cast free, r4 attribution):
    pads sit at the int32 clip ceiling, past every window edge.
    """
    import jax
    import jax.numpy as jnp

    key = (n, ts_base is not None)
    fn = _GATHER_CACHE.get(key)
    if fn is None:
        i32_ceiling = I32_PAD_TS

        def gather(tb, vb, st, ln, base):
            j = jnp.arange(n, dtype=jnp.int64)
            idx = st[:, None] + j[None, :]
            m = j[None, :] < ln[:, None]
            safe = jnp.clip(idx, 0, tb.shape[0] - 1)
            if ts_base is None:
                ts = jnp.where(m, tb[safe], PAD_TS)
            else:
                off = jnp.clip(tb[safe] - base, 0, i32_ceiling) \
                    .astype(jnp.int32)
                ts = jnp.where(m, off, i32_ceiling)
            val = jnp.where(m, vb[safe], 0.0)
            return ts, val, m
        # memoized per (N, compaction) in _GATHER_CACHE just above — the
        # wrapper is constructed once per padded batch shape, not per call
        fn = jax.jit(gather)  # tsdblint: disable=jax-jit-per-call
        _GATHER_CACHE[key] = fn
    base = jnp.asarray(0 if ts_base is None else ts_base, jnp.int64)
    return fn(ts_buf, val_buf, jnp.asarray(starts), jnp.asarray(lengths),
              base)
