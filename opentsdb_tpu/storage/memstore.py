"""Columnar, chunk-aligned in-memory series store — the storage engine.

This plays the role HBase + the row-key/qualifier codec played for the
reference (schema contract: SURVEY.md §2.6; RowSeq/Span assembly:
/root/reference/src/core/RowSeq.java, Span.java).  Design differences are
deliberate and TPU-first:

  * Series are identified by (metric_uid, sorted (tagk,tagv) uid pairs) —
    the same logical row-key identity, without byte-encoded rows.
  * Data is columnar per series: int64 ms timestamps, float64 values and an
    int-ness bitmask in growable numpy buffers, so query assembly is a zero-
    copy slice + pad into device batches instead of per-cell decoding.
  * Out-of-order and duplicate points are normalized lazily at read time
    (sort + last-write-wins dedup), the job CompactionQueue.java (:340) and
    AppendDataPoints.java did at the storage layer.
  * A salt-equivalent shard id (hash of the series key, RowKey.java:141) is
    precomputed per series for mesh sharding.

Annotations (qualifier prefix 0x01, src/meta/Annotation.java:86) are stored
side-band per series key, collected during query assembly exactly like
SaltScanner collects them per row (SaltScanner.java:425-448).
"""

from __future__ import annotations

import itertools
import logging
import threading
import zlib

_LOG = logging.getLogger("storage")
from dataclasses import dataclass, field
from typing import Callable, Iterable, Sequence

import numpy as np

MAX_NUM_TAGS = 8        # Const.java:28
CHUNK_SPAN_MS = 3_600_000  # Const.java:95 — 3600s row span, kept for layout


@dataclass(frozen=True)
class SeriesKey:
    """Logical identity of one time series: metric UID + sorted tag UID pairs."""
    metric: int
    tags: tuple[tuple[int, int], ...]  # sorted (tagk_uid, tagv_uid)

    @staticmethod
    def make(metric: int, tags: dict[int, int]) -> "SeriesKey":
        return SeriesKey(metric, tuple(sorted(tags.items())))

    def tsuid(self, metric_width: int = 3, tagk_width: int = 3,
              tagv_width: int = 3) -> str:
        """Hex TSUID: metric + tagk/tagv pairs (UniqueId.getTSUIDFromKey)."""
        out = [self.metric.to_bytes(metric_width, "big").hex()]
        for k, v in self.tags:
            out.append(k.to_bytes(tagk_width, "big").hex())
            out.append(v.to_bytes(tagv_width, "big").hex())
        return "".join(out).upper()

    def salt(self, buckets: int = 20) -> int:
        """Deterministic shard id, the salt-bucket equivalent (RowKey.java:141)."""
        h = zlib.crc32(repr((self.metric, self.tags)).encode())
        return h % buckets


class Series:
    """One series' columnar data: growable timestamp/value/int-ness arrays.

    Values live in parallel float64 + int64 buffers: the int64 side keeps
    Java-long exactness above 2^53 for integer points (the reference stores
    VLE-encoded longs, Internal.vleEncodeLong :963); the float side feeds the
    TPU float pipeline without a per-query cast.
    """

    __slots__ = ("key", "_ts", "_val", "_ival", "_isint", "_n", "_sorted",
                 "_lock", "shard", "_version")

    INITIAL_CAPACITY = 64

    def __init__(self, key: SeriesKey, shard: int = 0):
        self.key = key
        self.shard = shard
        # guarded-by: _lock
        self._ts = np.empty(self.INITIAL_CAPACITY, dtype=np.int64)
        self._val = np.empty(self.INITIAL_CAPACITY, dtype=np.float64)  # guarded-by: _lock
        self._ival = np.zeros(self.INITIAL_CAPACITY, dtype=np.int64)  # guarded-by: _lock
        self._isint = np.empty(self.INITIAL_CAPACITY, dtype=bool)  # guarded-by: _lock
        self._n = 0  # guarded-by: _lock
        self._sorted = True  # guarded-by: _lock
        self._lock = threading.Lock()
        # Monotone content-version: bumped by every mutation that changes
        # visible data (appends, restore, deletes, dedup).  The device
        # series cache snapshots (data, version) atomically and treats any
        # later mismatch as staleness — see storage/device_cache.py.
        self._version = 0  # guarded-by: _lock

    def __len__(self) -> int:
        return self._n

    @property
    def dirty(self) -> bool:
        return not self._sorted

    @property
    def version(self) -> int:
        return self._version

    def _grow_locked(self, need: int) -> None:
        new_cap = max(need, len(self._ts) * 2, self.INITIAL_CAPACITY)
        self._ts = np.resize(self._ts, new_cap)
        self._val = np.resize(self._val, new_cap)
        self._ival = np.resize(self._ival, new_cap)
        self._isint = np.resize(self._isint, new_cap)

    def append(self, ts_ms: int, value, is_int: bool) -> None:
        with self._lock:
            if self._n == len(self._ts):
                self._grow_locked(self._n + 1)
            if self._sorted and self._n and ts_ms <= self._ts[self._n - 1]:
                self._sorted = False
            self._ts[self._n] = ts_ms
            self._val[self._n] = float(value)
            self._ival[self._n] = int(value) if is_int else 0
            self._isint[self._n] = is_int
            self._n += 1
            self._version += 1

    def append_batch(self, ts_ms: np.ndarray, values: np.ndarray,
                     is_int: np.ndarray | bool,
                     ival: np.ndarray | None = None) -> None:
        """Bulk ingest (TextImporter-style); arrays must be 1-D, same length.

        Pass `ival` (exact int64 values where is_int) for mixed batches
        whose integer points exceed 2^53 — a float64 `values` round-trip
        would lose them (Java-long exactness, Internal.vleEncodeLong :963).
        """
        m = len(ts_ms)
        if m == 0:
            return
        values = np.asarray(values)
        if np.isscalar(is_int) or isinstance(is_int, bool):
            isint = np.full(m, bool(is_int))
        else:
            isint = np.asarray(is_int, dtype=bool)
        if ival is not None:
            ival = np.asarray(ival, dtype=np.int64)
        elif np.issubdtype(values.dtype, np.integer):
            ival = values
        else:
            # Float-typed arrays may still carry integer points; the int
            # column must hold their exact values wherever isint is set.
            ival = np.where(isint, values.astype(np.int64), 0)
        # pure input-only work stays outside the lock — and outside the
        # write transition: a raise here must not interleave the column
        # writes below (failure_atomicity's all-writes-after-fallible)
        incoming_sorted = bool(m == 1 or bool(np.all(np.diff(ts_ms) > 0)))
        with self._lock:
            need = self._n + m
            if need > len(self._ts):
                self._grow_locked(need)
            self._ts[self._n:need] = ts_ms
            self._val[self._n:need] = values
            self._ival[self._n:need] = ival
            self._isint[self._n:need] = isint
            if self._sorted and (not incoming_sorted or
                                 (self._n and ts_ms[0] <= self._ts[self._n - 1])):
                self._sorted = False
            self._n = need
            self._version += 1

    def normalize(self, fix_duplicates: bool = True) -> None:
        """Sort by timestamp, resolving duplicates last-write-wins.

        The read-time equivalent of compaction's heap-merge + dedup
        (CompactionQueue.java:499 mergeDatapoints, policy
        tsd.storage.fix_duplicates).  With fix_duplicates False, duplicate
        timestamps raise like the reference's IllegalDataException.
        """
        with self._lock:
            self._normalize_locked(fix_duplicates)

    # effects: canonicalize
    def _normalize_locked(self, fix_duplicates: bool) -> None:
        # _sorted means strictly increasing (append flags <=-ties as dirty),
        # so a sorted series has no duplicates either — nothing to do.
        if self._sorted:
            return
        n = self._n
        # stable sort keeps insertion order within equal timestamps, so the
        # last write for a timestamp is the last element of its run.
        order = np.argsort(self._ts[:n], kind="stable")
        self._ts[:n] = self._ts[:n][order]
        self._val[:n] = self._val[:n][order]
        self._ival[:n] = self._ival[:n][order]
        self._isint[:n] = self._isint[:n][order]
        # Dedup BEFORE declaring the series clean: with fix_duplicates off
        # _dedup_sorted_locked raises, and the series must stay dirty so later reads
        # keep raising and fsck can still see + repair the duplicate.
        self._dedup_sorted_locked(fix_duplicates)
        self._sorted = True

    def _dedup_sorted_locked(self, fix_duplicates: bool) -> None:
        n = self._n
        if n < 2:
            return
        ts = self._ts[:n]
        dup = ts[1:] == ts[:-1]
        if not dup.any():
            return
        if not fix_duplicates:
            idx = int(np.argmax(dup))
            raise ValueError(
                "Duplicate timestamp %d in series %s (set "
                "tsd.storage.fix_duplicates=true to resolve)"
                % (int(ts[idx]), self.key))
        # sized by the series' own resident point count, not by any
        # request field  # tsdblint: disable=taint-unsanitized-alloc
        keep = np.ones(n, dtype=bool)
        keep[:-1] = ~dup  # keep the LAST point of each duplicate run
        m = int(keep.sum())
        self._ts[:m] = ts[keep]
        self._val[:m] = self._val[:n][keep]
        self._ival[:m] = self._ival[:n][keep]
        self._isint[:m] = self._isint[:n][keep]
        self._n = m
        self._version += 1

    def window(self, start_ms: int, end_ms: int, fix_duplicates: bool = True
               ) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        """Return copies of (ts, float_vals, int_vals, is_int) for
        start_ms <= ts <= end_ms.

        Copies, not views: normalize() mutates the buffers in place and a
        background compaction flush may run while a query thread reads.
        Normalization and the binary search happen under one lock hold so a
        concurrent out-of-order append cannot invalidate the sort mid-read.
        """
        with self._lock:
            lo, hi = self._window_bounds_locked(start_ms, end_ms,
                                                fix_duplicates)
            return (self._ts[lo:hi].copy(), self._val[lo:hi].copy(),
                    self._ival[lo:hi].copy(), self._isint[lo:hi].copy())

    def _window_bounds_locked(self, start_ms: int, end_ms: int,
                              fix_duplicates: bool) -> tuple[int, int]:
        """(lo, hi) buffer indexes of [start_ms, end_ms] — callers hold
        the lock.  The single definition of the window bound semantics
        shared by window(), window_count(), window_chunk() and
        delete_range()."""
        self._normalize_locked(fix_duplicates)
        n = self._n
        lo = int(np.searchsorted(self._ts[:n], start_ms, side="left"))
        hi = int(np.searchsorted(self._ts[:n], end_ms, side="right"))
        return lo, hi

    def window_bounds(self, start_ms: int, end_ms: int,
                      fix_duplicates: bool = True) -> tuple[int, int, int]:
        """(lo, hi, version) for [start_ms, end_ms] under one lock hold.

        The version lets the device cache validate that its snapshot still
        matches the live series AND that (lo, hi) index that snapshot: both
        are taken under the same lock, so no append can slip between them.
        """
        with self._lock:
            lo, hi = self._window_bounds_locked(start_ms, end_ms,
                                                fix_duplicates)
            return lo, hi, self._version

    def snapshot(self, fix_duplicates: bool = True
                 ) -> tuple[np.ndarray, np.ndarray, int]:
        """Normalized (ts, float_vals, version) copies under one lock hold.

        The device-cache build path: the returned version identifies
        exactly this content — any later mutation bumps it.
        """
        with self._lock:
            self._normalize_locked(fix_duplicates)
            n = self._n
            return (self._ts[:n].copy(), self._val[:n].copy(),
                    self._version)

    def window_count(self, start_ms: int, end_ms: int,
                     fix_duplicates: bool = True) -> int:
        """Points in [start_ms, end_ms] without materializing them
        (budget charging / streaming-path planning)."""
        with self._lock:
            lo, hi = self._window_bounds_locked(start_ms, end_ms,
                                                fix_duplicates)
            return hi - lo

    def window_stats(self, start_ms: int, end_ms: int,
                     fix_duplicates: bool = True) -> tuple[int, bool]:
        """(point count, every value integer-typed) for the range,
        without materializing it — the batch builder sizes and types the
        padded arrays from this before the single-copy fill
        (window_into)."""
        with self._lock:
            lo, hi = self._window_bounds_locked(start_ms, end_ms,
                                                fix_duplicates)
            return hi - lo, bool(np.all(self._isint[lo:hi]))

    def window_into(self, start_ms: int, end_ms: int, fix_duplicates: bool,
                    ts_row: np.ndarray, val_row: np.ndarray,
                    mask_row: np.ndarray, want_int: bool
                    ) -> tuple[int, bool]:
        """Copy this series' window STRAIGHT into pre-allocated batch row
        slices under one lock hold — the fused form of window() +
        build_batch's per-row pack, eliminating the intermediate copies
        (a 1M-point query pays ~25MB of window() copies it immediately
        repacks).  Returns (points written, int-contract held): the range
        can both grow AND change type between the caller's sizing pass
        and this one (no snapshot isolation, like the reference's scanner
        over live rows) — the count clamps to the row width, and when
        `want_int` but a float point has appeared in range, NOTHING is
        copied and ok_int=False tells the caller to rebuild its batch as
        float (reading _ival for a float point would silently yield 0).
        Tail padding is the CALLER's job."""
        with self._lock:
            lo, hi = self._window_bounds_locked(start_ms, end_ms,
                                                fix_duplicates)
            k = min(hi - lo, len(ts_row))
            if want_int and not bool(np.all(self._isint[lo:lo + k])):
                return 0, False
            ts_row[:k] = self._ts[lo:lo + k]
            src = self._ival if want_int else self._val
            val_row[:k] = src[lo:lo + k]
            mask_row[:k] = True
            return k, True

    def window_stride_timestamps(self, start_ms: int, end_ms: int,
                                 stride: int, fix_duplicates: bool = True
                                 ) -> np.ndarray:
        """Every stride-th timestamp in [start_ms, end_ms] — the streaming
        chunk-boundary positions, used by the planner's sketch-hazard
        estimate (O(points/stride), never materializes the window)."""
        with self._lock:
            lo, hi = self._window_bounds_locked(start_ms, end_ms,
                                                fix_duplicates)
            return self._ts[lo:hi:max(stride, 1)].copy()

    def window_chunk(self, start_ms: int, end_ms: int,
                     after_ts: int | None, limit: int,
                     fix_duplicates: bool = True
                     ) -> tuple[np.ndarray, np.ndarray]:
        """Copy up to `limit` window points with timestamp > `after_ts`
        (None = from the window start) — the streaming scan's cursor read.

        The cursor is a TIMESTAMP, not an index: concurrent out-of-order
        writes (or the dedup a normalize performs) shift buffer positions
        between calls, so an index cursor could double-read or skip
        pre-existing points.  Timestamp progression is monotone — each
        pre-existing point is returned at most once; a point landing
        behind the cursor mid-query is a new write, which the streaming
        pass's documented contract (like the reference's scanner over live
        rows, SaltScanner.java:269) already excludes from visibility
        guarantees.  Returns (ts, float_vals).
        """
        with self._lock:
            lo, hi = self._window_bounds_locked(start_ms, end_ms,
                                                fix_duplicates)
            n = self._n
            if after_ts is not None:
                lo = max(lo, int(np.searchsorted(self._ts[:n], after_ts,
                                                 side="right")))
            b = min(lo + max(limit, 0), hi)
            return self._ts[lo:b].copy(), self._val[lo:b].copy()

    def restore_arrays(self, ts: np.ndarray, val: np.ndarray,
                       ival: np.ndarray, isint: np.ndarray) -> None:
        """Load snapshot columns verbatim (persistence restore path).

        Replaces the series contents; the float and int columns are taken
        exactly as stored so no int<->float round trip occurs.
        """
        n = len(ts)
        # sortedness depends only on the incoming column: compute it
        # before the lock so the locked section is pure writes
        sorted_flag = bool(n <= 1 or bool(np.all(np.diff(ts) > 0)))
        with self._lock:
            if n > len(self._ts):
                self._grow_locked(n)
            self._ts[:n] = ts
            self._val[:n] = val
            self._ival[:n] = ival
            self._isint[:n] = isint
            self._n = n
            self._sorted = sorted_flag
            self._version += 1

    def arrays(self) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        """Copies of the full (ts, float_vals, int_vals, is_int) columns."""
        with self._lock:
            n = self._n
            return (self._ts[:n].copy(), self._val[:n].copy(),
                    self._ival[:n].copy(), self._isint[:n].copy())

    def delete_range(self, start_ms: int, end_ms: int,
                     fix_duplicates: bool = True) -> int:
        """Remove points with start_ms <= ts <= end_ms (query delete flag,
        TsdbQuery.setDelete / scanner DeleteRequest path)."""
        with self._lock:
            lo, hi = self._window_bounds_locked(start_ms, end_ms,
                                                fix_duplicates)
            n = self._n
            removed = hi - lo
            if removed <= 0:
                return 0
            keep = n - hi
            self._ts[lo:lo + keep] = self._ts[hi:n]
            self._val[lo:lo + keep] = self._val[hi:n]
            self._ival[lo:lo + keep] = self._ival[hi:n]
            self._isint[lo:lo + keep] = self._isint[hi:n]
            self._n = n - removed
            self._version += 1
            return removed

    @property
    def size_bytes(self) -> int:
        return self._n * (8 + 8 + 8 + 1)


@dataclass
class Annotation:
    """A note attached to a timespan, per-TSUID or global (meta/Annotation.java)."""
    start_time: int
    end_time: int = 0
    tsuid: str = ""
    description: str = ""
    notes: str = ""
    custom: dict[str, str] | None = None

    def to_json(self) -> dict:
        out = {
            "tsuid": self.tsuid,
            "startTime": self.start_time,
            "endTime": self.end_time,
            "description": self.description,
            "notes": self.notes,
            "custom": self.custom,
        }
        if not self.tsuid:
            out.pop("tsuid")
        return out


class CompactionQueue:
    """Tracks dirty (out-of-order) series and normalizes them in the background.

    Reference behavior: CompactionQueue.java (:57, flush :127) — a queue of
    dirty rows flushed by a background thread.  Here "compaction" is the
    sort+dedup normalization pass; data is already columnar.
    """

    def __init__(self, fix_duplicates: bool = True):
        self._dirty: dict[SeriesKey, Series] = {}  # guarded-by: _lock
        self._lock = threading.Lock()
        self.fix_duplicates = fix_duplicates
        self.compactions = 0
        self.errors = 0

    def add(self, series: Series) -> None:
        with self._lock:
            self._dirty[series.key] = series

    def flush(self, max_flushes: int | None = None) -> int:
        with self._lock:
            items = list(self._dirty.items())[:max_flushes]
            for key, _ in items:
                self._dirty.pop(key, None)
        for _, series in items:
            try:
                series.normalize(self.fix_duplicates)
                self.compactions += 1
            except ValueError as e:
                # Duplicate data with fix_duplicates off (CompactionQueue
                # error callback): log and move on; reads will surface the
                # error and fsck repairs it.
                self.errors += 1
                _LOG.error("Compaction failed: %s", e)
        return len(items)

    def __len__(self) -> int:
        with self._lock:
            return len(self._dirty)


class MemStore:
    """The series store: keyed columnar series + tag inverted index.

    Query-side role of SaltScanner/MultiGetQuery + the tsdb table: find series
    for a metric and tag constraints, hand back columnar windows.
    """

    def __init__(self, salt_buckets: int = 20, fix_duplicates: bool = True):
        self.salt_buckets = salt_buckets
        self.fix_duplicates = fix_duplicates
        # guarded-by: _lock
        self._series: dict[SeriesKey, Series] = {}
        self._by_metric: dict[int, set[SeriesKey]] = {}  # guarded-by: _lock
        self._lock = threading.RLock()
        self.compaction_queue = CompactionQueue(fix_duplicates)
        # annotations: tsuid-keyed and global lists  # guarded-by: _lock
        self._annotations: dict[str, list[Annotation]] = {}
        self.datapoints_added = 0
        # data-mutation listeners, (metric, lo_ms, hi_ms) per write —
        # the partial-aggregate cache's incremental invalidation hook
        # (storage/agg_cache.py).  Notified AFTER the write lands
        # (write-then-mark): by the time the write is acked its mark
        # exists, so any cached artifact built from a pre-write read
        # fails its generation check — no acked write is ever served
        # stale.  (Mark-before-write had a hole: a snapshot taken
        # after the mark but before the write would carry the mark's
        # generation and dodge it forever.)  The ordering is a checked
        # contract: tools/lint/ordering.py fails the tree if any path
        # reaches a mark with its write undischarged.
        # order: memstore-write before memstore-mark
        # guarded-by: _lock
        self._mutation_listeners: list = []

    # -- write path --

    def add_mutation_listener(self, fn: Callable) -> None:
        """Register fn(metric_uid, lo_ms | None, hi_ms | None), called
        after every data mutation lands (None bounds = the whole
        metric; write-then-mark — see _mutation_listeners)."""
        with self._lock:
            self._mutation_listeners.append(fn)

    def notify_mutation(self, metric: int, lo_ms: int | None,
                        hi_ms: int | None) -> None:
        """Tell listeners a (metric, time-range) HAS changed — call
        after the mutation lands (see _mutation_listeners above).

        Also the public entry for out-of-band mutators (the query
        delete flag, fsck repairs) that bypass add_point/add_batch."""
        for fn in tuple(self._mutation_listeners):
            fn(metric, lo_ms, hi_ms)

    def get_or_create_series(self, key: SeriesKey) -> Series:
        with self._lock:
            return self._get_or_create_series_locked(key)

    def _get_or_create_series_locked(self, key: SeriesKey) -> Series:
        series = self._series.get(key)
        if series is None:
            series = Series(key, shard=key.salt(self.salt_buckets))
            self._series[key] = series
            self._by_metric.setdefault(key.metric, set()).add(key)
        return series

    def add_point(self, key: SeriesKey, ts_ms: int, value: float,
                  is_int: bool) -> None:
        # counter bump shares the lookup's lock hold: one store-lock
        # acquisition per ingest call, not two
        with self._lock:
            series = self._get_or_create_series_locked(key)
            self.datapoints_added += 1
        series.append(ts_ms, value, is_int)          # order-event: memstore-write
        self.notify_mutation(key.metric, ts_ms, ts_ms)  # order-event: memstore-mark
        if series.dirty:
            self.compaction_queue.add(series)

    def add_batch(self, key: SeriesKey, ts_ms: np.ndarray, values: np.ndarray,
                  is_int: np.ndarray | bool,
                  ival: np.ndarray | None = None) -> None:
        with self._lock:
            series = self._get_or_create_series_locked(key)
            self.datapoints_added += len(ts_ms)
        series.append_batch(ts_ms, values, is_int, ival)  # order-event: memstore-write
        if len(ts_ms):
            self.notify_mutation(key.metric, int(np.min(ts_ms)),  # order-event: memstore-mark
                                 int(np.max(ts_ms)))
        if series.dirty:
            self.compaction_queue.add(series)

    # -- read path --

    def series_for_metric(self, metric: int) -> list[Series]:
        with self._lock:
            keys = self._by_metric.get(metric, set())
            return [self._series[k] for k in keys]

    def series_count_and_sample(self, metric: int,
                                limit: int) -> tuple[int, list[Series]]:
        """Series count + a bounded sample for a metric WITHOUT
        building the full per-metric list — the pre-admission
        cost-estimate path (tsd/admission.py) runs on every arrival
        and must hold the store lock for a bounded allocation, not an
        O(series-of-metric) copy."""
        with self._lock:
            keys = self._by_metric.get(metric, set())
            sample = [self._series[k]
                      for k in itertools.islice(keys, limit)]
            return len(keys), sample

    def select(self, metric: int,
               predicate: Callable[[SeriesKey], bool] | None = None) -> list[Series]:
        """All series of a metric passing a key predicate (tag-filter hook)."""
        out = []
        with self._lock:
            for key in self._by_metric.get(metric, ()):
                if predicate is None or predicate(key):
                    out.append(self._series[key])
        return out

    def get_series(self, key: SeriesKey) -> Series | None:
        with self._lock:
            return self._series.get(key)

    def all_series(self) -> list[Series]:
        with self._lock:
            return list(self._series.values())

    # -- annotations --

    def add_annotation(self, note: Annotation) -> None:
        with self._lock:
            self._annotations.setdefault(note.tsuid, []).append(note)

    def get_annotations(self, tsuid: str, start_ms: int, end_ms: int,
                        include_global: bool = False) -> list[Annotation]:
        out = []
        with self._lock:
            pools: list[list[Annotation]] = [self._annotations.get(tsuid, [])]
            if include_global and tsuid != "":
                pools.append(self._annotations.get("", []))
            for pool in pools:
                for note in pool:
                    if start_ms <= note.start_time <= end_ms:
                        out.append(note)
        out.sort(key=lambda a: a.start_time)
        return out

    def delete_annotation(self, tsuid: str, start_time: int) -> bool:
        with self._lock:
            pool = self._annotations.get(tsuid, [])
            before = len(pool)
            self._annotations[tsuid] = [a for a in pool
                                        if a.start_time != start_time]
            return len(self._annotations[tsuid]) != before

    def annotation_keys(self) -> list[str]:
        """Every tsuid holding annotations ("" = global)."""
        with self._lock:
            return list(self._annotations.keys())

    def delete_annotation_range(self, tsuids: Sequence[str] | None,
                                start_ms: int, end_ms: int,
                                global_notes: bool = False) -> int:
        deleted = 0
        with self._lock:
            keys: Iterable[str]
            if global_notes:
                keys = [""]
            elif tsuids:
                keys = tsuids
            else:
                keys = list(self._annotations.keys())
            for key in keys:
                pool = self._annotations.get(key, [])
                kept = [a for a in pool
                        if not (start_ms <= a.start_time <= end_ms)]
                deleted += len(pool) - len(kept)
                self._annotations[key] = kept
        return deleted

    # -- stats / admin --

    @property
    def num_series(self) -> int:
        with self._lock:
            return len(self._series)

    @property
    def total_datapoints(self) -> int:
        with self._lock:
            return sum(len(s) for s in self._series.values())

    @property
    def total_bytes(self) -> int:
        with self._lock:
            return sum(s.size_bytes for s in self._series.values())

    def drop_caches(self) -> None:
        pass  # no separate cache layer; present for /api/dropcaches parity

    def delete_series(self, key: SeriesKey) -> bool:
        with self._lock:
            series = self._series.pop(key, None)     # order-event: memstore-write
            if series is not None:
                keys = self._by_metric.get(key.metric)
                if keys is not None:
                    keys.discard(key)
                    if not keys:
                        self._by_metric.pop(key.metric, None)
        if series is None:
            return False
        self.notify_mutation(key.metric, None, None)  # order-event: memstore-mark
        return True
