"""ctypes binding for the native columnar chunk engine (native/engine.cpp).

The C++ engine plays the at-rest role HBase's block encoding + compaction
played for the reference (CompactionQueue.java:40-56 — pack cells so the
per-cell overhead amortizes): per-series sealed chunks hold
delta-of-delta/zig-zag varint timestamps and Gorilla-style XOR'd values,
with an is-int bitmap preserving Java-long exactness.

The Python hot path stays columnar numpy/JAX; the engine serves as the
compressed binary snapshot codec (storage/persist.py) — orders of magnitude
denser than the JSONL/npz round 1 shipped and loaded with one C pass.  The
shared library builds from source on first use (``make -C native``); every
entry point degrades to the pure-Python path when the toolchain is absent.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading

import numpy as np

LOG = logging.getLogger(__name__)

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(
        __file__)))), "native")
_LIB_NAME = "libtsdb_engine.so"

_lock = threading.Lock()
_lib = None
_load_attempted = False

_I64 = ctypes.c_int64
_I32 = ctypes.c_int32
_F64 = ctypes.c_double
_U8P = ctypes.POINTER(ctypes.c_uint8)
_I64P = ctypes.POINTER(ctypes.c_int64)
_F64P = ctypes.POINTER(ctypes.c_double)


def _configure(lib) -> None:
    lib.eng_create.restype = ctypes.c_void_p
    lib.eng_destroy.argtypes = [ctypes.c_void_p]
    lib.eng_series.argtypes = [ctypes.c_void_p, ctypes.c_char_p, _I32]
    lib.eng_series.restype = _I64
    lib.eng_num_series.argtypes = [ctypes.c_void_p]
    lib.eng_num_series.restype = _I32
    lib.eng_series_key.argtypes = [ctypes.c_void_p, _I64, _U8P, _I32]
    lib.eng_series_key.restype = _I32
    lib.eng_append_batch.argtypes = [
        ctypes.c_void_p, _I64, _I64P, _F64P, _I64P, _U8P, _I64]
    lib.eng_series_len.argtypes = [ctypes.c_void_p, _I64]
    lib.eng_series_len.restype = _I64
    lib.eng_series_bytes.argtypes = [ctypes.c_void_p, _I64]
    lib.eng_series_bytes.restype = _I64
    lib.eng_window.argtypes = [ctypes.c_void_p, _I64, _I64, _I64,
                               _I64P, _F64P, _I64P, _U8P, _I64]
    lib.eng_window.restype = _I64
    lib.eng_window_raw.argtypes = [ctypes.c_void_p, _I64,
                                   _I64P, _F64P, _I64P, _U8P, _I64]
    lib.eng_window_raw.restype = _I64
    lib.eng_delete_range.argtypes = [ctypes.c_void_p, _I64, _I64, _I64]
    lib.eng_delete_range.restype = _I64
    lib.eng_normalize.argtypes = [ctypes.c_void_p, _I64]
    lib.eng_total_bytes.argtypes = [ctypes.c_void_p]
    lib.eng_total_bytes.restype = _I64
    lib.eng_save.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.eng_save.restype = _I32
    lib.eng_load.argtypes = [ctypes.c_char_p]
    lib.eng_load.restype = ctypes.c_void_p
    # bulk put parser
    lib.eng_put_parse.argtypes = [ctypes.c_char_p, _I64]
    lib.eng_put_parse.restype = ctypes.c_void_p
    lib.eng_put_free.argtypes = [ctypes.c_void_p]
    lib.eng_put_npoints.argtypes = [ctypes.c_void_p]
    lib.eng_put_npoints.restype = _I64
    lib.eng_put_ngroups.argtypes = [ctypes.c_void_p]
    lib.eng_put_ngroups.restype = _I64
    for name, ptr in (("eng_put_ts", _I64P), ("eng_put_fval", _F64P),
                      ("eng_put_ival", _I64P), ("eng_put_isint", _U8P),
                      ("eng_put_group", ctypes.POINTER(_I32)),
                      ("eng_put_spans", _I64P)):
        fn = getattr(lib, name)
        fn.argtypes = [ctypes.c_void_p]
        fn.restype = ptr
    lib.eng_put_group_key.argtypes = [ctypes.c_void_p, _I64]
    lib.eng_put_group_key.restype = ctypes.c_char_p
    lib.eng_put_nerrors.argtypes = [ctypes.c_void_p]
    lib.eng_put_nerrors.restype = _I64
    lib.eng_put_error.argtypes = [ctypes.c_void_p, _I64, _I64P,
                                  ctypes.POINTER(ctypes.c_char_p)]
    lib.eng_put_error.restype = ctypes.c_char_p
    # telnet put-line batch parser
    lib.eng_telnet_parse.argtypes = [ctypes.c_char_p, _I64]
    lib.eng_telnet_parse.restype = ctypes.c_void_p
    lib.eng_telnet_free.argtypes = [ctypes.c_void_p]
    lib.eng_telnet_batch.argtypes = [ctypes.c_void_p]
    lib.eng_telnet_batch.restype = ctypes.c_void_p
    lib.eng_telnet_nlines.argtypes = [ctypes.c_void_p]
    lib.eng_telnet_nlines.restype = _I64
    lib.eng_telnet_status.argtypes = [ctypes.c_void_p]
    lib.eng_telnet_status.restype = ctypes.POINTER(ctypes.c_int8)
    lib.eng_telnet_spans.argtypes = [ctypes.c_void_p]
    lib.eng_telnet_spans.restype = _I64P
    lib.eng_telnet_point.argtypes = [ctypes.c_void_p]
    lib.eng_telnet_point.restype = ctypes.POINTER(_I32)


def _load_library():
    """Load (building if needed) the shared library; None on failure."""
    global _lib, _load_attempted
    with _lock:
        if _load_attempted:
            return _lib
        _load_attempted = True
        path = os.environ.get("TSDB_NATIVE_LIB") or os.path.join(
            _NATIVE_DIR, _LIB_NAME)
        if path.startswith(_NATIVE_DIR):
            src = os.path.join(_NATIVE_DIR, "engine.cpp")
            stale = (not os.path.exists(path)
                     or (os.path.exists(src)
                         and os.path.getmtime(src) > os.path.getmtime(path)))
            if stale:
                try:
                    subprocess.run(["make", "-C", _NATIVE_DIR, "-B"],
                                   capture_output=True, timeout=120,
                                   check=True)
                except (OSError, subprocess.SubprocessError) as e:
                    LOG.warning("native engine build failed (%s); falling "
                                "back to the pure-Python snapshot codec", e)
                    if not os.path.exists(path):
                        return None
        try:
            lib = ctypes.CDLL(path)
            _configure(lib)
            _lib = lib
        except (OSError, AttributeError) as e:
            # AttributeError: a stale prebuilt .so missing a newer export —
            # degrade to the pure-Python codec rather than crash.
            LOG.warning("native engine unavailable (%s)", e)
        return _lib


def available() -> bool:
    return _load_library() is not None


class NativeEngine:
    """One engine instance: keyed compressed series + binary save/load."""

    def __init__(self, handle=None):
        lib = _load_library()
        if lib is None:
            raise RuntimeError("native engine library unavailable")
        self._lib = lib
        self._handle = handle if handle is not None else lib.eng_create()

    @classmethod
    def load(cls, path: str) -> "NativeEngine":
        lib = _load_library()
        if lib is None:
            raise RuntimeError("native engine library unavailable")
        handle = lib.eng_load(path.encode())
        if not handle:
            raise IOError("cannot load native snapshot: " + path)
        return cls(handle=handle)

    def close(self) -> None:
        if self._handle:
            self._lib.eng_destroy(self._handle)
            self._handle = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -------------------------------------------------------------- #

    def series(self, key: bytes) -> int:
        """Stable id for a series key (created on first use)."""
        return self._lib.eng_series(self._handle, key, len(key))

    def num_series(self) -> int:
        return self._lib.eng_num_series(self._handle)

    def series_key(self, sid: int) -> bytes:
        n = self._lib.eng_series_key(
            self._handle, sid, ctypes.cast(ctypes.create_string_buffer(0),
                                           _U8P), 0)
        buf = ctypes.create_string_buffer(n)
        self._lib.eng_series_key(self._handle, sid,
                                 ctypes.cast(buf, _U8P), n)
        return buf.raw[:n]

    def append_batch(self, sid: int, ts: np.ndarray, fval: np.ndarray,
                     ival: np.ndarray, is_int: np.ndarray) -> None:
        n = len(ts)
        if n == 0:
            return
        ts = np.ascontiguousarray(ts, np.int64)
        fval = np.ascontiguousarray(fval, np.float64)
        ival = np.ascontiguousarray(ival, np.int64)
        is_int = np.ascontiguousarray(is_int, np.uint8)
        self._lib.eng_append_batch(
            self._handle, sid,
            ts.ctypes.data_as(_I64P), fval.ctypes.data_as(_F64P),
            ival.ctypes.data_as(_I64P), is_int.ctypes.data_as(_U8P), n)

    def series_len(self, sid: int) -> int:
        return self._lib.eng_series_len(self._handle, sid)

    def series_bytes(self, sid: int) -> int:
        return self._lib.eng_series_bytes(self._handle, sid)

    def total_bytes(self) -> int:
        return self._lib.eng_total_bytes(self._handle)

    def _materialize(self, fn, sid: int, *mid_args):
        """Shared column-buffer marshalling for the window reads.

        The buffers are sized by the series' RESIDENT length (store
        state, bounded by ingest), never by a request field — hence the
        taint suppressions."""
        cap = self.series_len(sid)
        ts = np.empty(cap, np.int64)     # tsdblint: disable=taint-unsanitized-alloc
        fval = np.empty(cap, np.float64)  # tsdblint: disable=taint-unsanitized-alloc
        ival = np.empty(cap, np.int64)   # tsdblint: disable=taint-unsanitized-alloc
        is_int = np.empty(cap, np.uint8)  # tsdblint: disable=taint-unsanitized-alloc
        n = fn(self._handle, sid, *mid_args,
               ts.ctypes.data_as(_I64P), fval.ctypes.data_as(_F64P),
               ival.ctypes.data_as(_I64P), is_int.ctypes.data_as(_U8P), cap)
        return (ts[:n], fval[:n], ival[:n], is_int[:n].astype(bool))

    def window(self, sid: int, start: int = -(1 << 62),
               end: int = 1 << 62):
        """Materialize [start, end] -> (ts, fval, ival, is_int) arrays."""
        return self._materialize(self._lib.eng_window, sid, start, end)

    def window_raw(self, sid: int):
        """Full materialization with duplicate timestamps preserved.

        Snapshot-restore path: a series persisted with unresolved duplicate
        timestamps (tsd.storage.fix_duplicates=false) must restore dirty so
        reads keep raising and fsck can repair it — eng_window's
        last-write-wins dedup would silently heal it.
        """
        return self._materialize(self._lib.eng_window_raw, sid)

    def delete_range(self, sid: int, start: int, end: int) -> int:
        return self._lib.eng_delete_range(self._handle, sid, start, end)

    def normalize(self, sid: int) -> None:
        self._lib.eng_normalize(self._handle, sid)

    def save(self, path: str) -> None:
        if self._lib.eng_save(self._handle, path.encode()) != 0:
            raise IOError("cannot write native snapshot: " + path)


class ParsedPutBatch:
    """Columnar view of one parsed /api/put body (native fast path).

    Wraps the C++ parse result: validated + normalized point columns, a
    distinct-series key table, and per-point error messages mirroring the
    Python path's exception strings.  Columns are COPIED out so the
    native buffer frees eagerly.
    """

    __slots__ = ("n", "ts", "fval", "ival", "isint", "group", "spans",
                 "errors", "group_keys")

    def __init__(self, lib, handle):
        n = lib.eng_put_npoints(handle)
        g = lib.eng_put_ngroups(handle)
        self.n = n

        def col(fn, dtype, count):
            ptr = fn(handle)
            return np.ctypeslib.as_array(ptr, shape=(count,)).copy() \
                if count else np.empty(0, dtype)

        self.ts = col(lib.eng_put_ts, np.int64, n)
        self.fval = col(lib.eng_put_fval, np.float64, n)
        self.ival = col(lib.eng_put_ival, np.int64, n)
        self.isint = col(lib.eng_put_isint, np.uint8, n).astype(bool)
        self.group = col(lib.eng_put_group, np.int32, n)
        self.spans = col(lib.eng_put_spans, np.int64, 2 * n).reshape(n, 2) \
            if n else np.empty((0, 2), np.int64)
        self.errors = []            # [(index, kind, message)]
        kind_p = ctypes.c_char_p()
        idx_p = ctypes.c_int64()
        # error/group counts are bounded by the points in the already-
        # received body — proportional, not amplified
        # tsdblint: disable=taint-unsanitized-alloc
        for j in range(lib.eng_put_nerrors(handle)):
            msg = lib.eng_put_error(handle, j, ctypes.byref(idx_p),
                                    ctypes.byref(kind_p))
            self.errors.append((int(idx_p.value),
                                (kind_p.value or b"").decode(),
                                (msg or b"").decode()))
        self.group_keys = []        # [(metric, {tagk: tagv})]
        # same already-received-body bound as the error loop above
        # tsdblint: disable=taint-unsanitized-alloc
        for gi in range(g):
            raw = lib.eng_put_group_key(handle, gi).decode()
            parts = raw.split("\x1f")
            tags = {}
            for pair in parts[1:]:
                k, _, v = pair.partition("\x1e")
                tags[k] = v
            self.group_keys.append((parts[0], tags))


LINE_OK, LINE_ERROR, LINE_FALLBACK = 0, 1, 2


class ParsedTelnetBatch:
    """Columnar view of one parsed telnet put-line block.

    `points` is the shared ParsedPutBatch column view; per-LINE arrays
    map each non-blank line to its outcome: OK/ERROR lines carry the
    point index they produced, FALLBACK lines (exotic grammar the parser
    refuses to mirror) carry their byte span so the caller can replay
    just those through the per-line Python handler.
    """

    __slots__ = ("points", "n_lines", "status", "spans", "point_index")

    def __init__(self, lib, handle):
        self.points = ParsedPutBatch(lib, lib.eng_telnet_batch(handle))
        n = int(lib.eng_telnet_nlines(handle))
        self.n_lines = n

        def col(fn, count):
            return np.ctypeslib.as_array(fn(handle), shape=(count,)).copy() \
                if count else np.empty(0, np.int64)

        self.status = col(lib.eng_telnet_status, n)
        self.spans = col(lib.eng_telnet_spans, 2 * n).reshape(n, 2) \
            if n else np.empty((0, 2), np.int64)
        self.point_index = col(lib.eng_telnet_point, n)


def parse_telnet_block(block: bytes):
    """Parse a block of telnet put lines natively; None -> Python path."""
    lib = _load_library()
    if lib is None or not hasattr(lib, "eng_telnet_parse"):
        return None
    handle = lib.eng_telnet_parse(block, len(block))
    if not handle:
        return None
    try:
        return ParsedTelnetBatch(lib, handle)
    except UnicodeDecodeError:
        return None
    finally:
        lib.eng_telnet_free(handle)


def parse_put_body(body: bytes):
    """Parse a /api/put JSON body natively; None -> use the Python path.

    None covers: library unavailable, malformed JSON (the Python path
    raises the user-visible parse error), and any construct whose Python
    semantics the native parser refuses to mirror (non-string tags,
    arbitrary-precision timestamps, ...).
    """
    lib = _load_library()
    if lib is None or not hasattr(lib, "eng_put_parse"):
        return None
    handle = lib.eng_put_parse(body, len(body))
    if not handle:
        return None
    try:
        return ParsedPutBatch(lib, handle)
    except UnicodeDecodeError:
        # group keys that aren't valid UTF-8 (the parser guards the
        # known producers of these, e.g. lone surrogates, but a decode
        # failure must degrade to the Python path, never to a 500)
        return None
    finally:
        lib.eng_put_free(handle)
