"""Disk persistence: snapshot + sequenced, CRC-framed write-ahead journal.

The durability role HBase's WAL played for the reference (SURVEY.md §5:
"durability is HBase's WAL... the TSD keeps no durable state").  With
`tsd.storage.directory` set, the TSD journals every ingest record to an
append-only framed WAL and can snapshot the full state (UID dictionaries,
scalar series columns, rollup lanes, histogram series, annotations,
uid/ts meta, tree definitions) into the directory; startup restores the
snapshot then replays the WAL tail.

WAL framing (the replication substrate — tsd/replication.py ships these
records to replicas and serves them at /api/replication/tail):

    <seq> <crc32-hex8> <payload-json>\n

  * ``seq`` is monotonic per node and NEVER reused — it survives
    snapshots (the manifest carries ``wal_next_seq``) so a replica's
    catch-up position stays meaningful across the owner's snapshot
    cycles.
  * ``crc32`` covers the payload bytes: a torn or bit-flipped interior
    record is DETECTED at replay/tail time instead of replayed —
    counted in ``tsd.storage.wal.corrupt_records``, and replay stops at
    the last valid record (the divergent tail is truncated; records
    past a hole are untrusted by construction).
  * the journal rotates into segments (``wal-<firstseq>.jsonl``,
    ``tsd.storage.wal.segment_mb`` each) so a replica can catch up
    from an arbitrary sequence number without the owner rescanning one
    unbounded file.

Layout under the directory:
    snapshot.json        everything JSON-able + the series manifest
    series.npz           columnar arrays, keys s<i>_{ts,val,ival,isint}
    rollup.npz           same shape per rollup lane series
    wal-<seq16>.jsonl    framed journal segments since the last snapshot
    wal.jsonl            legacy unframed journal (replayed if present)
"""

from __future__ import annotations

import json
import logging
import os
import threading
import zlib

import numpy as np

from opentsdb_tpu.obs.registry import REGISTRY
from opentsdb_tpu.utils import faults

LOG = logging.getLogger("storage.persist")

SNAPSHOT_JSON = "snapshot.json"
SERIES_NPZ = "series.npz"
ROLLUP_NPZ = "rollup.npz"
SERIES_BIN = "series.tsdb"   # native engine binary snapshot
WAL_FILE = "wal.jsonl"       # legacy single-file journal (pre-framing)
WAL_SEGMENT_PREFIX = "wal-"
WAL_SEGMENT_SUFFIX = ".jsonl"


def record_crc(payload: str) -> int:
    """The per-record checksum the frame carries (and replication
    re-verifies on apply): crc32 over the payload bytes."""
    return zlib.crc32(payload.encode("utf-8")) & 0xFFFFFFFF


def frame_line(seq: int, crc: int, payload: str) -> str:
    return "%d %08x %s\n" % (seq, crc, payload)


def parse_frame(line: str) -> tuple[int, int, str] | None:
    """(seq, crc, payload) for a framed line; None for the legacy
    unframed format (a bare JSON object — replayed crc-less)."""
    if line.startswith("{"):
        return None
    seq_s, crc_s, payload = line.split(" ", 2)
    return int(seq_s), int(crc_s, 16), payload


def _corrupt_counter():
    return REGISTRY.counter(
        "tsd.storage.wal.corrupt_records",
        "WAL records whose CRC32/frame failed verification at replay "
        "(interior corruption; replay stops at the last valid record)")


class WalCorruptionError(ValueError):
    """An interior WAL record failed its CRC or frame parse."""


def apply_record(tsdb, rec: dict) -> int:
    """Apply ONE journal record to a TSDB — the shared dispatch behind
    WAL replay AND replication apply (tsd/replication.py feeds shipped/
    tailed owner records through the same code path, so a replica's
    store is byte-for-byte what a local replay would build).

    Returns the failed-point count (0 = fully applied).  The caller
    owns the ``tsdb._replaying`` window (replay) or the replication
    accepting context; this function never re-journals."""
    kind = rec.get("k")
    failed = 0
    try:
        if kind == "p":
            tsdb._apply_point(rec["m"], rec["t"], rec["v"], rec["g"])
        elif kind == "pb":
            # bulk put record: one WAL line per /api/put body.
            # Successful points have already landed, so a partial
            # failure must not mark the whole line lost — count and log
            # the failed points only.
            _, errs = tsdb.add_points_bulk(rec["d"])
            if errs:
                failed += len(errs)
                for i, e in errs[:3]:
                    LOG.error(
                        "WAL bulk replay dropped point %d of a %d-point "
                        "record: %s", i, len(rec["d"]), e)
        elif kind == "pj":
            # raw /api/put body journaled by the native fast path:
            # re-parse through the same path (falling back to the python
            # bulk parser if the library is absent on restore).
            # Per-point PARSE errors replay deterministically and were
            # never stored — only storage-type failures count as dropped.
            body = rec["b"].encode("utf-8")
            out = tsdb.add_points_bulk_native(body)
            if out is None:
                dps = json.loads(rec["b"])
                if isinstance(dps, dict):
                    dps = [dps]
                _, errs = tsdb.add_points_bulk(dps)
            else:
                errs = out[1]
            storage_errs = [
                (i, e) for i, e in errs
                if not isinstance(e, (ValueError, TypeError))]
            if storage_errs:
                failed += len(storage_errs)
                for i, e in storage_errs[:3]:
                    LOG.error("WAL native-put replay dropped point %d: "
                              "%s", i, e)
        elif kind == "pt":
            # raw telnet put-line block from the native batch path.
            # Natively-refused (FALLBACK) lines were journaled by their
            # own per-point "p" records at ingest time, so only the
            # natively-landed lines replay here.  LINE_ERROR lines
            # replay their deterministic parse error and stored nothing
            # — only storage-type failures count as dropped.
            out = tsdb.add_telnet_batch_native(rec["b"].encode())
            if out is not None:
                storage_errs = [
                    (i, e) for i, e in out[1].items()
                    if not isinstance(e, (ValueError, TypeError))]
                if storage_errs:
                    failed += len(storage_errs)
                    for i, e in storage_errs[:3]:
                        LOG.error("WAL telnet replay dropped point %d: "
                                  "%s", i, e)
            else:
                # library absent on restore: walk put lines through the
                # point parser, bypassing add_point (which would
                # re-journal into the WAL being replayed)
                from opentsdb_tpu.tsd.rpcs import (
                    parse_tags, parse_telnet_timestamp)
                for raw in rec["b"].splitlines():
                    words = raw.split()
                    if len(words) < 5 or words[0] != "put":
                        continue
                    try:
                        tsdb._apply_point(
                            words[1], parse_telnet_timestamp(words[2]),
                            words[3], parse_tags(words[4:]))
                    except (ValueError, TypeError):
                        pass   # deterministic parse error: stored
                        #        nothing at ingest too
                    except Exception as e:
                        failed += 1
                        LOG.error("WAL telnet replay dropped a line: %s",
                                  e)
        elif kind == "r":
            tsdb._apply_aggregate_point(
                rec["m"], rec["t"], rec["v"], rec["g"], rec["gb"],
                rec.get("i"), rec.get("a"), rec.get("ga"))
        elif kind == "h":
            tsdb._apply_histogram_json(rec["m"], rec["t"], rec["d"],
                                       rec["g"])
        elif kind == "a":
            from opentsdb_tpu.storage.memstore import Annotation
            # Direct store write: add_annotation would re-journal into
            # the WAL currently being replayed.
            note = Annotation(**rec["n"])
            tsdb.store.add_annotation(note)
            if tsdb.search_plugin is not None:
                tsdb.search_plugin.index_annotation(note)
        elif kind == "rr":
            # replicated record: a peer's WAL record applied by
            # replication (tsd/replication.py), journaled locally so a
            # replica restart restores both the data and its per-origin
            # catch-up position.  With replication disabled on restore
            # the inner record still applies — the data must not vanish
            # because a config flag flipped.
            repl = getattr(tsdb, "replication", None)
            if repl is not None:
                repl.restore_applied(rec["o"], rec["q"], rec["c"],
                                     rec.get("sh"), rec["r"])
            else:
                failed += apply_record(tsdb, rec["r"])
    except Exception as e:
        # Torn tail lines are handled by the framing layer; systematic
        # apply failures must be visible.
        failed += 1
        LOG.error("WAL replay failed for record %r: %s",
                  str(rec)[:200], e)
    return failed


class DiskPersistence:
    def __init__(self, tsdb, directory: str):
        self.tsdb = tsdb
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self._wal_lock = threading.Lock()
        self._wal = None  # guarded-by: _wal_lock
        self._wal_file_path = None  # guarded-by: _wal_lock
        self._wal_bytes = 0  # guarded-by: _wal_lock
        self.wal_records = 0  # guarded-by: _wal_lock
        # next sequence number to assign — monotonic for the node's
        # lifetime, snapshot resets included
        self._next_seq = 1  # guarded-by: _wal_lock
        self._segment_bytes = max(
            tsdb.config.get_int("tsd.storage.wal.segment_mb"), 1) * 2 ** 20
        # opt-in per-append disk barrier (tsd.storage.wal.fsync): every
        # journaled record is crash-durable before the write acks; off,
        # durability rides the wal_sync_interval cadence
        self._fsync_per_append = tsdb.config.get_bool(
            "tsd.storage.wal.fsync")

    # ------------------------------------------------------------------ #
    # WAL                                                                #
    # ------------------------------------------------------------------ #

    def _legacy_path(self) -> str:
        return os.path.join(self.directory, WAL_FILE)

    def _segment_path(self, first_seq: int) -> str:
        return os.path.join(
            self.directory,
            "%s%016d%s" % (WAL_SEGMENT_PREFIX, first_seq,
                           WAL_SEGMENT_SUFFIX))

    def _segments(self) -> list[tuple[int, str]]:
        """(first_seq, path) for every framed segment, seq order."""
        out = []
        for name in os.listdir(self.directory):
            if name.startswith(WAL_SEGMENT_PREFIX) \
                    and name.endswith(WAL_SEGMENT_SUFFIX):
                mid = name[len(WAL_SEGMENT_PREFIX):
                           -len(WAL_SEGMENT_SUFFIX)]
                try:
                    out.append((int(mid), os.path.join(self.directory,
                                                       name)))
                except ValueError:
                    continue
        out.sort()
        return out

    @property
    def last_seq(self) -> int:
        with self._wal_lock:
            return self._next_seq - 1

    def journal(self, record: dict) -> tuple[int, int]:
        """Append one ingest record; flushed per write (the WAL
        contract).  Returns the assigned ``(seq, crc)`` — what
        replication ships and the tail endpoint serves."""
        faults.check("wal.append")
        payload = json.dumps(record, separators=(",", ":"))
        crc = record_crc(payload)
        with self._wal_lock:
            seq = self._next_seq
            self._next_seq += 1
            try:
                if self._wal is None or \
                        self._wal_bytes >= self._segment_bytes:
                    if self._wal is not None:
                        old, self._wal = self._wal, None
                        old.close()
                    self._wal_file_path = self._segment_path(seq)
                    self._wal = open(self._wal_file_path, "a",
                                     buffering=1)
                    self._wal_bytes = os.path.getsize(self._wal_file_path)
                line = frame_line(seq, crc, payload)
                self._wal.write(line)
                self._wal_bytes += len(line.encode("utf-8"))
                self.wal_records += 1
                if self._fsync_per_append:
                    os.fsync(self._wal.fileno())
            except BaseException:
                # un-assign: nothing reached the log under this seq, so
                # give it back — a burned sequence number would read as
                # a permanent gap to every replica tailing this WAL
                self._next_seq = seq
                raise
        return seq, crc

    def read_since(self, since: int, max_bytes: int = 4 * 2 ** 20
                   ) -> tuple[list[tuple[int, int, str]], int, int]:
        """Framed records with seq > ``since``, oldest first, bounded by
        ``max_bytes`` of payload — the /api/replication/tail substrate.

        Returns ``(records, last_seq, first_seq)``: ``last_seq`` is
        this node's newest assigned sequence number (so a caller can
        tell a bounded page from a complete tail) and ``first_seq`` the
        oldest sequence the WAL still holds — a snapshot resets the
        journal while seqs keep climbing, so a replica positioned below
        ``first_seq - 1`` must fast-forward instead of waiting forever
        for records that now live only in the snapshot.  A corrupt
        interior record ends the page at the last valid record (counted
        like replay — a replica must never apply bytes past a hole).

        Only the coordinates are read under ``_wal_lock``; the segment
        scan itself runs lock-free so a multi-MB tail page never stalls
        ``journal()`` — the ingest ack path.  Rotated segments are
        immutable, and the ACTIVE segment is read only up to the
        locked-snapshot byte count (always a line boundary: journal()
        writes whole lines under the lock), so a mid-append torn line
        can never masquerade as corruption."""
        out: list[tuple[int, int, str]] = []
        budget = max_bytes
        with self._wal_lock:
            last_seq = self._next_seq - 1
            segments = self._segments()
            first_seq = segments[0][0] if segments else self._next_seq
            active_path = self._wal_file_path
            active_len = self._wal_bytes
        for i, (first, path) in enumerate(segments):
            nxt = segments[i + 1][0] if i + 1 < len(segments) else None
            if nxt is not None and nxt <= since + 1:
                continue        # whole segment at or below the mark
            limit = active_len if path == active_path else None
            read = 0
            try:
                with open(path, encoding="utf-8") as fh:
                    for raw in fh:
                        read += len(raw.encode("utf-8"))
                        if limit is not None and read > limit:
                            break   # bytes past the locked snapshot:
                            #         an append in progress, next page
                        line = raw.rstrip("\n")
                        if not line:
                            continue
                        try:
                            frame = parse_frame(line)
                            if frame is None:
                                continue    # legacy record: no seq
                            seq, crc, payload = frame
                            if record_crc(payload) != crc:
                                raise WalCorruptionError(path)
                        except (ValueError, WalCorruptionError):
                            _corrupt_counter().inc()
                            LOG.error(
                                "WAL tail read: corrupt record in "
                                "%s; serving up to the last valid "
                                "record", path)
                            return out, last_seq, first_seq
                        if seq <= since:
                            continue
                        out.append((seq, crc, payload))
                        budget -= len(payload)
                        if budget <= 0:
                            return out, last_seq, first_seq
            except OSError:
                continue        # rotated/reset underneath us
        return out, last_seq, first_seq

    def sync_wal(self) -> None:
        """fsync the WAL so acknowledged writes survive an OS crash.

        Line buffering (journal above) flushes to the OS per record —
        process-crash-safe; this adds the disk barrier, called on a cadence
        by the maintenance thread (tsd.storage.wal_sync_interval) instead
        of per-write so the ingest path never pays it.
        """
        faults.check("wal.fsync")
        with self._wal_lock:
            if self._wal is not None:
                os.fsync(self._wal.fileno())

    def _reset_wal(self) -> None:
        """Drop every journal file (post-snapshot).  ``_next_seq`` is
        deliberately NOT reset: sequence numbers are the replication
        stream's coordinates and must stay monotonic across snapshots."""
        with self._wal_lock:
            if self._wal is not None:
                self._wal.close()
                self._wal = None
                self._wal_file_path = None
                self._wal_bytes = 0
            legacy = self._legacy_path()
            if os.path.exists(legacy):
                os.remove(legacy)
            for _first, path in self._segments():
                os.remove(path)
            self.wal_records = 0

    def _trim_torn_tail(self, path: str) -> None:
        """Truncate a newline-less final line (crash mid-append) BEFORE
        replay and before appends resume.  Left in place, the next
        journal() would concatenate its record onto the torn fragment —
        destroying the first acknowledged post-restart write and turning
        the tail into a mid-file-corruption false alarm on the replay
        after that."""
        size = os.path.getsize(path)
        if size == 0:
            return
        with open(path, "rb+") as fh:
            fh.seek(-1, os.SEEK_END)
            if fh.read(1) == b"\n":
                return
            # scan back for the last newline in chunks
            pos = size
            keep = 0
            while pos > 0:
                step = min(65536, pos)
                pos -= step
                fh.seek(pos)
                chunk = fh.read(step)
                nl = chunk.rfind(b"\n")
                if nl != -1:
                    keep = pos + nl + 1
                    break
            LOG.warning(
                "WAL replay: truncating torn final line (crash "
                "mid-append, %d bytes past the last complete record)",
                size - keep)
            fh.truncate(keep)
            fh.flush()
            os.fsync(fh.fileno())

    def replay_wal(self) -> int:
        """Re-ingest journaled records (startup recovery).

        Legacy ``wal.jsonl`` (unframed) replays first, then the framed
        segments in sequence order.  A framed record that fails its CRC
        or frame parse is interior corruption: it is counted
        (``tsd.storage.wal.corrupt_records``), replay STOPS at the last
        valid record, and the journal is truncated there — records past
        a hole are untrusted and must not be replayed (nor served to a
        catching-up replica)."""
        tsdb = self.tsdb
        count = 0
        failed = 0
        legacy = self._legacy_path()
        segments = self._segments()
        if not segments and not os.path.exists(legacy):
            return 0
        if segments:
            self._trim_torn_tail(segments[-1][1])
        elif os.path.exists(legacy):
            self._trim_torn_tail(legacy)
        # seqs must never be reused even when a corrupt tail is being
        # truncated below — scan the frames for the highest assigned
        # seq BEFORE any discard decision
        max_seq = self._scan_max_seq(segments)
        tsdb._replaying = True
        try:
            if os.path.exists(legacy):
                c, f, _ = self._replay_lines(legacy, framed=False)
                count += c
                failed += f
            for i, (_first, path) in enumerate(segments):
                c, f, corrupt = self._replay_lines(path, framed=True)
                count += c
                failed += f
                if corrupt:     # stop at the last valid record; the
                    #             truncation already happened in
                    #             _replay_lines — later segments are
                    #             past the hole and equally untrusted
                    for _n, later in segments[i + 1:]:
                        LOG.error(
                            "WAL replay: discarding segment %s past the "
                            "corrupt record", later)
                        os.remove(later)
                    break
        finally:
            tsdb._replaying = False
        with self._wal_lock:
            self._next_seq = max(self._next_seq, max_seq + 1)
        if failed:
            LOG.error("WAL replay dropped %d of %d records; see prior "
                      "errors", failed, count + failed)
        return count

    @staticmethod
    def _scan_max_seq(segments: list[tuple[int, str]]) -> int:
        """Highest sequence number any frame claims, corrupt payloads
        included — the floor for ``_next_seq`` so a truncated tail can
        never cause a seq to be minted twice (replica positions and CRC
        chains key on them)."""
        max_seq = 0
        for first, path in segments:
            max_seq = max(max_seq, first)
            try:
                with open(path, encoding="utf-8") as fh:
                    for line in fh:
                        try:
                            frame = parse_frame(line.rstrip("\n"))
                        except ValueError:
                            continue
                        if frame is not None:
                            max_seq = max(max_seq, frame[0])
            except OSError:
                continue
        return max_seq

    def _replay_lines(self, path: str, framed: bool = True
                      ) -> tuple[int, int, bool]:
        """Replay one journal file.  Returns ``(count, failed,
        corrupted)``; ``corrupted`` True means a framed record failed
        its CRC/frame parse — replay stopped at the last valid record
        and the file was truncated at the hole (appends must not land
        after garbage, and a catching-up replica must not be served
        it)."""
        tsdb = self.tsdb
        count = 0
        failed = 0
        # _trim_torn_tail already removed the genuine crash artifact (a
        # newline-less torn tail) before this runs, so a bad CRC or
        # unparseable line here — tail included — is a fully-written
        # record that got garbled: corruption worth alarming on.
        lineno = 0
        offset = 0
        with open(path, encoding="utf-8") as fh:
            for raw in fh:
                line_bytes = len(raw.encode("utf-8"))
                lineno += 1
                line = raw.strip()
                if not line:
                    offset += line_bytes
                    continue
                rec = None
                try:
                    frame = parse_frame(line) if framed else None
                    if frame is not None:
                        seq, crc, payload = frame
                        if record_crc(payload) != crc:
                            raise WalCorruptionError(
                                "crc mismatch at line %d" % lineno)
                        rec = json.loads(payload)
                    else:
                        if framed and not line.startswith("{"):
                            raise WalCorruptionError(
                                "unparseable frame at line %d" % lineno)
                        rec = json.loads(line)
                except (ValueError, WalCorruptionError) as e:
                    if framed:
                        _corrupt_counter().inc()
                        LOG.error(
                            "WAL replay: corrupt record at %s:%d (%s); "
                            "stopping at the last valid record and "
                            "truncating the hole", path, lineno, e)
                        self._truncate_at(path, offset)
                        return count, failed, True
                    failed += 1
                    LOG.error(
                        "WAL replay: skipped unparseable line %d "
                        "(corruption — crash-torn tails are trimmed "
                        "before replay): %r", lineno, line[:80])
                    offset += line_bytes
                    continue
                offset += line_bytes
                f = apply_record(tsdb, rec)
                if f == 0:
                    count += 1
                else:
                    failed += f
                if frame is not None and rec.get("k") != "rr" \
                        and rec.get("sh") is not None \
                        and getattr(tsdb, "replication", None) is not None:
                    # rebuild the own-origin CRC chain the live ship
                    # path maintains (anti-entropy compares it)
                    tsdb.replication.note_local_replayed(
                        frame[0], frame[1], rec["sh"])
        return count, failed, False

    @staticmethod
    def _truncate_at(path: str, offset: int) -> None:
        with open(path, "rb+") as fh:
            fh.truncate(offset)
            fh.flush()
            os.fsync(fh.fileno())

    # ------------------------------------------------------------------ #
    # Snapshot                                                           #
    # ------------------------------------------------------------------ #

    def snapshot(self) -> None:
        tsdb = self.tsdb
        manifest: dict = {
            "version": 1,
            # the WAL seq high-water mark: seqs stay monotonic across
            # the snapshot's WAL reset (replication positions key on
            # them)
            "wal_next_seq": self._next_seq,
            "uids": {
                "metric": tsdb.metrics.snapshot(),
                "tagk": tsdb.tag_names.snapshot(),
                "tagv": tsdb.tag_values.snapshot(),
            },
            "series": [],
            "rollup": [],
            "annotations": [],
            "histograms": [],
            "uidmeta": [],
            "tsmeta": [],
            "trees": [],
        }
        if self._use_native():
            # Compressed binary codec (native/engine.cpp): delta-of-delta
            # timestamps + Gorilla-style XOR values in sealed chunks —
            # replaces the npz series dumps with one C pass.
            manifest["series_codec"] = "native"
            self._snapshot_native()
        else:
            self._snapshot_npz(manifest)

        for tsuid in tsdb.store.annotation_keys():
            for note in tsdb.store.get_annotations(
                    tsuid, 0, 1 << 62):
                manifest["annotations"].append({
                    "start_time": note.start_time,
                    "end_time": note.end_time,
                    "tsuid": note.tsuid,
                    "description": note.description,
                    "notes": note.notes,
                    "custom": note.custom,
                })

        if tsdb.histogram_store is not None:
            for series in tsdb.histogram_store.all_series():
                points = series.window(0, 1 << 62)
                manifest["histograms"].append({
                    "metric": series.key.metric,
                    "tags": list(series.key.tags),
                    "points": [(t, h.to_json()) for t, h in points],
                })

        for meta in tsdb.meta_store.all_uidmeta():
            manifest["uidmeta"].append(meta.to_json())
        for meta in tsdb.meta_store.all_tsmeta():
            entry = meta.to_json()
            entry.pop("metric", None)
            entry.pop("tags", None)
            manifest["tsmeta"].append(entry)
        for tree in tsdb.tree_store.all_trees():
            manifest["trees"].append(tree.to_json(include_rules=True))

        tmp = os.path.join(self.directory, SNAPSHOT_JSON + ".tmp")
        with open(tmp, "w") as fh:
            json.dump(manifest, fh)
        os.replace(tmp, os.path.join(self.directory, SNAPSHOT_JSON))
        self._reset_wal()

    def _use_native(self) -> bool:
        from opentsdb_tpu.storage import native_engine
        return (self.tsdb.config.get_bool("tsd.storage.native_snapshot")
                and native_engine.available())

    def _series_bin_path(self) -> str:
        return os.path.join(self.directory, SERIES_BIN)

    def _snapshot_native(self) -> None:
        """All series (main store + rollup lanes) into one engine file."""
        from opentsdb_tpu.storage.native_engine import NativeEngine
        tsdb = self.tsdb
        with NativeEngine() as eng:
            def put(series, lane_key=None):
                ident = {"m": series.key.metric,
                         "t": list(series.key.tags)}
                if lane_key is not None:
                    ident["l"] = list(lane_key)
                sid = eng.series(json.dumps(
                    ident, separators=(",", ":")).encode())
                ts, val, ival, isint = series.arrays()
                eng.append_batch(sid, ts, val, ival,
                                 isint.astype(np.uint8))

            for series in tsdb.store.all_series():
                put(series)
            if tsdb.rollup_store is not None:
                for lane_key in tsdb.rollup_store.lanes():
                    lane = tsdb.rollup_store.peek_lane(*lane_key)
                    for series in lane.all_series():
                        put(series, lane_key)
            tmp = self._series_bin_path() + ".tmp"
            eng.save(tmp)
            os.replace(tmp, self._series_bin_path())

    def _snapshot_npz(self, manifest: dict) -> None:
        tsdb = self.tsdb
        arrays: dict[str, np.ndarray] = {}
        for i, series in enumerate(tsdb.store.all_series()):
            ts, val, ival, isint = series.arrays()
            manifest["series"].append({
                "metric": series.key.metric,
                "tags": list(series.key.tags),
            })
            arrays["s%d_ts" % i] = ts
            arrays["s%d_val" % i] = val
            arrays["s%d_ival" % i] = ival
            arrays["s%d_isint" % i] = isint
        np.savez_compressed(
            os.path.join(self.directory, SERIES_NPZ), **arrays)

        rollup_arrays: dict[str, np.ndarray] = {}
        if tsdb.rollup_store is not None:
            idx = 0
            for (interval, agg, pre) in tsdb.rollup_store.lanes():
                lane = tsdb.rollup_store.peek_lane(interval, agg, pre)
                for series in lane.all_series():
                    ts, val, ival, isint = series.arrays()
                    manifest["rollup"].append({
                        "interval": interval, "agg": agg, "pre": pre,
                        "metric": series.key.metric,
                        "tags": list(series.key.tags),
                    })
                    rollup_arrays["s%d_ts" % idx] = ts
                    rollup_arrays["s%d_val" % idx] = val
                    rollup_arrays["s%d_ival" % idx] = ival
                    rollup_arrays["s%d_isint" % idx] = isint
                    idx += 1
        np.savez_compressed(
            os.path.join(self.directory, ROLLUP_NPZ), **rollup_arrays)

    def _restore_native(self) -> None:
        from opentsdb_tpu.storage.memstore import SeriesKey
        from opentsdb_tpu.storage.native_engine import NativeEngine
        tsdb = self.tsdb
        with NativeEngine.load(self._series_bin_path()) as eng:
            for sid in range(eng.num_series()):
                ident = json.loads(eng.series_key(sid))
                # raw read: unresolved duplicates must survive the
                # round-trip so the series restores dirty (fsck repairs)
                ts, fval, ival, isint = eng.window_raw(sid)
                key = SeriesKey(ident["m"],
                                tuple(tuple(t) for t in ident["t"]))
                lane_key = ident.get("l")
                if lane_key is None:
                    target = tsdb.store
                elif tsdb.rollup_store is not None:
                    target = tsdb.rollup_store.lane(*lane_key)
                else:
                    continue  # rollups disabled since the snapshot
                target.get_or_create_series(key).restore_arrays(
                    ts, fval, ival, isint)

    # ------------------------------------------------------------------ #
    # Restore                                                            #
    # ------------------------------------------------------------------ #

    def restore(self) -> bool:
        """Load the snapshot (if any) then replay the WAL tail."""
        path = os.path.join(self.directory, SNAPSHOT_JSON)
        loaded = False
        if os.path.exists(path):
            with open(path) as fh:
                manifest = json.load(fh)
            self._restore_manifest(manifest)
            loaded = True
        self.replay_wal()
        return loaded

    def _restore_manifest(self, manifest: dict) -> None:
        from opentsdb_tpu.histogram import SimpleHistogram
        from opentsdb_tpu.meta.objects import TSMeta, UIDMeta
        from opentsdb_tpu.storage.memstore import Annotation, SeriesKey
        from opentsdb_tpu.tree.objects import Tree, TreeRule
        tsdb = self.tsdb
        with self._wal_lock:
            self._next_seq = max(self._next_seq,
                                 int(manifest.get("wal_next_seq", 1)))
        tsdb.metrics.restore(manifest["uids"]["metric"])
        tsdb.tag_names.restore(manifest["uids"]["tagk"])
        tsdb.tag_values.restore(manifest["uids"]["tagv"])

        if manifest.get("series_codec") == "native":
            from opentsdb_tpu.storage import native_engine
            if not native_engine.available():
                raise RuntimeError(
                    "snapshot was written by the native engine but "
                    "libtsdb_engine.so is unavailable (build native/ or "
                    "set TSDB_NATIVE_LIB)")
            self._restore_native()

        series_path = os.path.join(self.directory, SERIES_NPZ)
        if manifest["series"] and os.path.exists(series_path):
            with np.load(series_path) as arrays:
                for i, entry in enumerate(manifest["series"]):
                    key = SeriesKey(entry["metric"],
                                    tuple(tuple(t) for t in entry["tags"]))
                    tsdb.store.get_or_create_series(key).restore_arrays(
                        arrays["s%d_ts" % i], arrays["s%d_val" % i],
                        arrays["s%d_ival" % i], arrays["s%d_isint" % i])

        rollup_path = os.path.join(self.directory, ROLLUP_NPZ)
        if manifest["rollup"] and tsdb.rollup_store is not None \
                and os.path.exists(rollup_path):
            with np.load(rollup_path) as arrays:
                for i, entry in enumerate(manifest["rollup"]):
                    key = SeriesKey(entry["metric"],
                                    tuple(tuple(t) for t in entry["tags"]))
                    lane = tsdb.rollup_store.lane(
                        entry["interval"], entry["agg"], entry["pre"])
                    lane.get_or_create_series(key).restore_arrays(
                        arrays["s%d_ts" % i], arrays["s%d_val" % i],
                        arrays["s%d_ival" % i], arrays["s%d_isint" % i])

        for note in manifest["annotations"]:
            tsdb.store.add_annotation(Annotation(**note))

        if manifest["histograms"] and tsdb.histogram_store is not None:
            for entry in manifest["histograms"]:
                key = SeriesKey(entry["metric"],
                                tuple(tuple(t) for t in entry["tags"]))
                for t, hist_json in entry["points"]:
                    tsdb.histogram_store.add_point(
                        key, t, SimpleHistogram.from_pojo(hist_json))

        for m in manifest["uidmeta"]:
            meta = tsdb.meta_store.ensure_uidmeta(
                m["type"].lower(), m["uid"], m["name"])
            meta.display_name = m.get("displayName", "")
            meta.description = m.get("description", "")
            meta.notes = m.get("notes", "")
            meta.created = m.get("created", 0)
            meta.custom = m.get("custom")
        for m in manifest["tsmeta"]:
            meta = tsdb.meta_store.ensure_tsmeta(m["tsuid"])
            meta.display_name = m.get("displayName", "")
            meta.description = m.get("description", "")
            meta.notes = m.get("notes", "")
            meta.created = m.get("created", 0)
            meta.custom = m.get("custom")
            meta.units = m.get("units", "")
            meta.data_type = m.get("dataType", "")
            meta.retention = m.get("retention", 0)
            meta.last_received = m.get("lastReceived", 0)
            meta.total_dps = m.get("totalDatapoints", 0)

        for t in manifest["trees"]:
            tree = Tree(tree_id=t["treeId"], name=t.get("name", ""),
                        description=t.get("description", ""),
                        notes=t.get("notes", ""),
                        strict_match=bool(t.get("strictMatch")),
                        enabled=bool(t.get("enabled")),
                        store_failures=bool(t.get("storeFailures")),
                        created=t.get("created", 0))
            with tsdb.tree_store._lock:
                tsdb.tree_store._trees[tree.tree_id] = tree
                from opentsdb_tpu.tree.objects import Branch
                tsdb.tree_store._branches.setdefault(
                    (tree.tree_id, ()), Branch(tree.tree_id, ()))
            for r in t.get("rules", []):
                tree.add_rule(TreeRule.from_json(r))

    def close(self) -> None:
        with self._wal_lock:
            if self._wal is not None:
                self._wal.close()
                self._wal = None
