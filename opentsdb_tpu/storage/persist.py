"""Disk persistence: snapshot + write-ahead journal.

The durability role HBase's WAL played for the reference (SURVEY.md §5:
"durability is HBase's WAL... the TSD keeps no durable state").  With
`tsd.storage.directory` set, the TSD journals every ingest record to an
append-only JSONL WAL and can snapshot the full state (UID dictionaries,
scalar series columns, rollup lanes, histogram series, annotations,
uid/ts meta, tree definitions) into the directory; startup restores the
snapshot then replays the WAL tail.

Layout under the directory:
    snapshot.json       everything JSON-able + the series manifest
    series.npz          columnar arrays, keys s<i>_{ts,val,ival,isint}
    rollup.npz          same shape per rollup lane series
    wal.jsonl           journal since the last snapshot
"""

from __future__ import annotations

import json
import logging
import os
import threading

import numpy as np

from opentsdb_tpu.utils import faults

LOG = logging.getLogger("storage.persist")

SNAPSHOT_JSON = "snapshot.json"
SERIES_NPZ = "series.npz"
ROLLUP_NPZ = "rollup.npz"
SERIES_BIN = "series.tsdb"   # native engine binary snapshot
WAL_FILE = "wal.jsonl"


class DiskPersistence:
    def __init__(self, tsdb, directory: str):
        self.tsdb = tsdb
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self._wal_lock = threading.Lock()
        self._wal = None  # guarded-by: _wal_lock
        self.wal_records = 0  # guarded-by: _wal_lock
        # opt-in per-append disk barrier (tsd.storage.wal.fsync): every
        # journaled record is crash-durable before the write acks; off,
        # durability rides the wal_sync_interval cadence
        self._fsync_per_append = tsdb.config.get_bool(
            "tsd.storage.wal.fsync")

    # ------------------------------------------------------------------ #
    # WAL                                                                #
    # ------------------------------------------------------------------ #

    def _wal_path(self) -> str:
        return os.path.join(self.directory, WAL_FILE)

    def journal(self, record: dict) -> None:
        """Append one ingest record; flushed per write (the WAL contract)."""
        faults.check("wal.append")
        line = json.dumps(record, separators=(",", ":"))
        with self._wal_lock:
            if self._wal is None:
                self._wal = open(self._wal_path(), "a", buffering=1)
            self._wal.write(line + "\n")
            self.wal_records += 1
            if self._fsync_per_append:
                os.fsync(self._wal.fileno())

    def sync_wal(self) -> None:
        """fsync the WAL so acknowledged writes survive an OS crash.

        Line buffering (journal above) flushes to the OS per record —
        process-crash-safe; this adds the disk barrier, called on a cadence
        by the maintenance thread (tsd.storage.wal_sync_interval) instead
        of per-write so the ingest path never pays it.
        """
        faults.check("wal.fsync")
        with self._wal_lock:
            if self._wal is not None:
                os.fsync(self._wal.fileno())

    def _reset_wal(self) -> None:
        with self._wal_lock:
            if self._wal is not None:
                self._wal.close()
                self._wal = None
            path = self._wal_path()
            if os.path.exists(path):
                os.remove(path)
            self.wal_records = 0

    def _trim_torn_tail(self, path: str) -> None:
        """Truncate a newline-less final line (crash mid-append) BEFORE
        replay and before appends resume.  Left in place, the next
        journal() would concatenate its record onto the torn fragment —
        destroying the first acknowledged post-restart write and turning
        the tail into a mid-file-corruption false alarm on the replay
        after that."""
        size = os.path.getsize(path)
        if size == 0:
            return
        with open(path, "rb+") as fh:
            fh.seek(-1, os.SEEK_END)
            if fh.read(1) == b"\n":
                return
            # scan back for the last newline in chunks
            pos = size
            keep = 0
            while pos > 0:
                step = min(65536, pos)
                pos -= step
                fh.seek(pos)
                chunk = fh.read(step)
                nl = chunk.rfind(b"\n")
                if nl != -1:
                    keep = pos + nl + 1
                    break
            LOG.warning(
                "WAL replay: truncating torn final line (crash "
                "mid-append, %d bytes past the last complete record)",
                size - keep)
            fh.truncate(keep)
            fh.flush()
            os.fsync(fh.fileno())

    def replay_wal(self) -> int:
        """Re-ingest journaled records (startup recovery)."""
        path = self._wal_path()
        if not os.path.exists(path):
            return 0
        self._trim_torn_tail(path)
        tsdb = self.tsdb
        count = 0
        failed = 0
        tsdb._replaying = True
        try:
            count, failed = self._replay_lines(path)
        finally:
            tsdb._replaying = False
        if failed:
            LOG.error("WAL replay dropped %d of %d records; see prior "
                      "errors", failed, count + failed)
        return count

    def _replay_lines(self, path: str) -> tuple[int, int]:
        tsdb = self.tsdb
        count = 0
        failed = 0
        # _trim_torn_tail already removed the genuine crash artifact (a
        # newline-less torn tail) before this runs, so an unparseable
        # line here — tail included — is a fully-written record that
        # got garbled: corruption worth alarming on, counted in the
        # dropped-records total.  Replay continues either way so one
        # bad line doesn't take down every later acknowledged write.
        lineno = 0
        with open(path) as fh:
            for line in fh:
                lineno += 1
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    failed += 1
                    LOG.error(
                        "WAL replay: skipped unparseable line %d "
                        "(corruption — crash-torn tails are trimmed "
                        "before replay): %r", lineno, line[:80])
                    continue
                kind = rec.get("k")
                try:
                    if kind == "p":
                        tsdb._apply_point(rec["m"], rec["t"], rec["v"],
                                          rec["g"])
                    elif kind == "pb":
                        # bulk put record: one WAL line per /api/put body.
                        # Successful points have already landed, so a
                        # partial failure must not mark the whole line
                        # lost — count and log the failed points only.
                        _, errs = tsdb.add_points_bulk(rec["d"])
                        if errs:
                            failed += len(errs)
                            for i, e in errs[:3]:
                                LOG.error(
                                    "WAL bulk replay dropped point %d "
                                    "of a %d-point record: %s", i,
                                    len(rec["d"]), e)
                    elif kind == "pj":
                        # raw /api/put body journaled by the native fast
                        # path: re-parse through the same path (falling
                        # back to the python bulk parser if the library
                        # is absent on restore).  Per-point PARSE errors
                        # replay deterministically and were never stored
                        # — only storage-type failures count as dropped.
                        body = rec["b"].encode("utf-8")
                        out = tsdb.add_points_bulk_native(body)
                        if out is None:
                            dps = json.loads(rec["b"])
                            if isinstance(dps, dict):
                                dps = [dps]
                            _, errs = tsdb.add_points_bulk(dps)
                        else:
                            errs = out[1]
                        storage_errs = [
                            (i, e) for i, e in errs
                            if not isinstance(e, (ValueError, TypeError))]
                        if storage_errs:
                            failed += len(storage_errs)
                            for i, e in storage_errs[:3]:
                                LOG.error("WAL native-put replay dropped "
                                          "point %d: %s", i, e)
                    elif kind == "pt":
                        # raw telnet put-line block from the native batch
                        # path.  Natively-refused (FALLBACK) lines were
                        # journaled by their own per-point "p" records at
                        # ingest time, so only the natively-landed lines
                        # replay here.  LINE_ERROR lines replay their
                        # deterministic parse error and stored nothing —
                        # only storage-type failures count as dropped.
                        out = tsdb.add_telnet_batch_native(rec["b"].encode())
                        if out is not None:
                            storage_errs = [
                                (i, e) for i, e in out[1].items()
                                if not isinstance(e, (ValueError,
                                                      TypeError))]
                            if storage_errs:
                                failed += len(storage_errs)
                                for i, e in storage_errs[:3]:
                                    LOG.error("WAL telnet replay dropped "
                                              "point %d: %s", i, e)
                        else:
                            # library absent on restore: walk put lines
                            # through the point parser, bypassing
                            # add_point (which would re-journal into the
                            # WAL being replayed)
                            from opentsdb_tpu.tsd.rpcs import (
                                parse_tags, parse_telnet_timestamp)
                            for raw in rec["b"].splitlines():
                                words = raw.split()
                                if len(words) < 5 or words[0] != "put":
                                    continue
                                try:
                                    tsdb._apply_point(
                                        words[1],
                                        parse_telnet_timestamp(words[2]),
                                        words[3], parse_tags(words[4:]))
                                except (ValueError, TypeError):
                                    pass   # deterministic parse error:
                                    #        stored nothing at ingest too
                                except Exception as e:
                                    failed += 1
                                    LOG.error("WAL telnet replay dropped "
                                              "a line: %s", e)
                    elif kind == "r":
                        tsdb._apply_aggregate_point(
                            rec["m"], rec["t"], rec["v"], rec["g"],
                            rec["gb"], rec.get("i"), rec.get("a"),
                            rec.get("ga"))
                    elif kind == "h":
                        tsdb._apply_histogram_json(rec["m"], rec["t"],
                                                   rec["d"], rec["g"])
                    elif kind == "a":
                        from opentsdb_tpu.storage.memstore import Annotation
                        # Direct store write: add_annotation would re-journal
                        # into the WAL currently being replayed.
                        note = Annotation(**rec["n"])
                        tsdb.store.add_annotation(note)
                        if tsdb.search_plugin is not None:
                            tsdb.search_plugin.index_annotation(note)
                    count += 1
                except Exception as e:
                    # Torn tail lines are silent (JSONDecodeError above);
                    # systematic apply failures must be visible.
                    failed += 1
                    if failed <= 10:
                        LOG.error("WAL replay failed for record %r: %s",
                                  line[:200], e)
        return count, failed

    # ------------------------------------------------------------------ #
    # Snapshot                                                           #
    # ------------------------------------------------------------------ #

    def snapshot(self) -> None:
        tsdb = self.tsdb
        manifest: dict = {
            "version": 1,
            "uids": {
                "metric": tsdb.metrics.snapshot(),
                "tagk": tsdb.tag_names.snapshot(),
                "tagv": tsdb.tag_values.snapshot(),
            },
            "series": [],
            "rollup": [],
            "annotations": [],
            "histograms": [],
            "uidmeta": [],
            "tsmeta": [],
            "trees": [],
        }
        if self._use_native():
            # Compressed binary codec (native/engine.cpp): delta-of-delta
            # timestamps + Gorilla-style XOR values in sealed chunks —
            # replaces the npz series dumps with one C pass.
            manifest["series_codec"] = "native"
            self._snapshot_native()
        else:
            self._snapshot_npz(manifest)

        for tsuid in tsdb.store.annotation_keys():
            for note in tsdb.store.get_annotations(
                    tsuid, 0, 1 << 62):
                manifest["annotations"].append({
                    "start_time": note.start_time,
                    "end_time": note.end_time,
                    "tsuid": note.tsuid,
                    "description": note.description,
                    "notes": note.notes,
                    "custom": note.custom,
                })

        if tsdb.histogram_store is not None:
            for series in tsdb.histogram_store.all_series():
                points = series.window(0, 1 << 62)
                manifest["histograms"].append({
                    "metric": series.key.metric,
                    "tags": list(series.key.tags),
                    "points": [(t, h.to_json()) for t, h in points],
                })

        for meta in tsdb.meta_store.all_uidmeta():
            manifest["uidmeta"].append(meta.to_json())
        for meta in tsdb.meta_store.all_tsmeta():
            entry = meta.to_json()
            entry.pop("metric", None)
            entry.pop("tags", None)
            manifest["tsmeta"].append(entry)
        for tree in tsdb.tree_store.all_trees():
            manifest["trees"].append(tree.to_json(include_rules=True))

        tmp = os.path.join(self.directory, SNAPSHOT_JSON + ".tmp")
        with open(tmp, "w") as fh:
            json.dump(manifest, fh)
        os.replace(tmp, os.path.join(self.directory, SNAPSHOT_JSON))
        self._reset_wal()

    def _use_native(self) -> bool:
        from opentsdb_tpu.storage import native_engine
        return (self.tsdb.config.get_bool("tsd.storage.native_snapshot")
                and native_engine.available())

    def _series_bin_path(self) -> str:
        return os.path.join(self.directory, SERIES_BIN)

    def _snapshot_native(self) -> None:
        """All series (main store + rollup lanes) into one engine file."""
        from opentsdb_tpu.storage.native_engine import NativeEngine
        tsdb = self.tsdb
        with NativeEngine() as eng:
            def put(series, lane_key=None):
                ident = {"m": series.key.metric,
                         "t": list(series.key.tags)}
                if lane_key is not None:
                    ident["l"] = list(lane_key)
                sid = eng.series(json.dumps(
                    ident, separators=(",", ":")).encode())
                ts, val, ival, isint = series.arrays()
                eng.append_batch(sid, ts, val, ival,
                                 isint.astype(np.uint8))

            for series in tsdb.store.all_series():
                put(series)
            if tsdb.rollup_store is not None:
                for lane_key in tsdb.rollup_store.lanes():
                    lane = tsdb.rollup_store.peek_lane(*lane_key)
                    for series in lane.all_series():
                        put(series, lane_key)
            tmp = self._series_bin_path() + ".tmp"
            eng.save(tmp)
            os.replace(tmp, self._series_bin_path())

    def _snapshot_npz(self, manifest: dict) -> None:
        tsdb = self.tsdb
        arrays: dict[str, np.ndarray] = {}
        for i, series in enumerate(tsdb.store.all_series()):
            ts, val, ival, isint = series.arrays()
            manifest["series"].append({
                "metric": series.key.metric,
                "tags": list(series.key.tags),
            })
            arrays["s%d_ts" % i] = ts
            arrays["s%d_val" % i] = val
            arrays["s%d_ival" % i] = ival
            arrays["s%d_isint" % i] = isint
        np.savez_compressed(
            os.path.join(self.directory, SERIES_NPZ), **arrays)

        rollup_arrays: dict[str, np.ndarray] = {}
        if tsdb.rollup_store is not None:
            idx = 0
            for (interval, agg, pre) in tsdb.rollup_store.lanes():
                lane = tsdb.rollup_store.peek_lane(interval, agg, pre)
                for series in lane.all_series():
                    ts, val, ival, isint = series.arrays()
                    manifest["rollup"].append({
                        "interval": interval, "agg": agg, "pre": pre,
                        "metric": series.key.metric,
                        "tags": list(series.key.tags),
                    })
                    rollup_arrays["s%d_ts" % idx] = ts
                    rollup_arrays["s%d_val" % idx] = val
                    rollup_arrays["s%d_ival" % idx] = ival
                    rollup_arrays["s%d_isint" % idx] = isint
                    idx += 1
        np.savez_compressed(
            os.path.join(self.directory, ROLLUP_NPZ), **rollup_arrays)

    def _restore_native(self) -> None:
        from opentsdb_tpu.storage.memstore import SeriesKey
        from opentsdb_tpu.storage.native_engine import NativeEngine
        tsdb = self.tsdb
        with NativeEngine.load(self._series_bin_path()) as eng:
            for sid in range(eng.num_series()):
                ident = json.loads(eng.series_key(sid))
                # raw read: unresolved duplicates must survive the
                # round-trip so the series restores dirty (fsck repairs)
                ts, fval, ival, isint = eng.window_raw(sid)
                key = SeriesKey(ident["m"],
                                tuple(tuple(t) for t in ident["t"]))
                lane_key = ident.get("l")
                if lane_key is None:
                    target = tsdb.store
                elif tsdb.rollup_store is not None:
                    target = tsdb.rollup_store.lane(*lane_key)
                else:
                    continue  # rollups disabled since the snapshot
                target.get_or_create_series(key).restore_arrays(
                    ts, fval, ival, isint)

    # ------------------------------------------------------------------ #
    # Restore                                                            #
    # ------------------------------------------------------------------ #

    def restore(self) -> bool:
        """Load the snapshot (if any) then replay the WAL tail."""
        path = os.path.join(self.directory, SNAPSHOT_JSON)
        loaded = False
        if os.path.exists(path):
            with open(path) as fh:
                manifest = json.load(fh)
            self._restore_manifest(manifest)
            loaded = True
        self.replay_wal()
        return loaded

    def _restore_manifest(self, manifest: dict) -> None:
        from opentsdb_tpu.histogram import SimpleHistogram
        from opentsdb_tpu.meta.objects import TSMeta, UIDMeta
        from opentsdb_tpu.storage.memstore import Annotation, SeriesKey
        from opentsdb_tpu.tree.objects import Tree, TreeRule
        tsdb = self.tsdb
        tsdb.metrics.restore(manifest["uids"]["metric"])
        tsdb.tag_names.restore(manifest["uids"]["tagk"])
        tsdb.tag_values.restore(manifest["uids"]["tagv"])

        if manifest.get("series_codec") == "native":
            from opentsdb_tpu.storage import native_engine
            if not native_engine.available():
                raise RuntimeError(
                    "snapshot was written by the native engine but "
                    "libtsdb_engine.so is unavailable (build native/ or "
                    "set TSDB_NATIVE_LIB)")
            self._restore_native()

        series_path = os.path.join(self.directory, SERIES_NPZ)
        if manifest["series"] and os.path.exists(series_path):
            with np.load(series_path) as arrays:
                for i, entry in enumerate(manifest["series"]):
                    key = SeriesKey(entry["metric"],
                                    tuple(tuple(t) for t in entry["tags"]))
                    tsdb.store.get_or_create_series(key).restore_arrays(
                        arrays["s%d_ts" % i], arrays["s%d_val" % i],
                        arrays["s%d_ival" % i], arrays["s%d_isint" % i])

        rollup_path = os.path.join(self.directory, ROLLUP_NPZ)
        if manifest["rollup"] and tsdb.rollup_store is not None \
                and os.path.exists(rollup_path):
            with np.load(rollup_path) as arrays:
                for i, entry in enumerate(manifest["rollup"]):
                    key = SeriesKey(entry["metric"],
                                    tuple(tuple(t) for t in entry["tags"]))
                    lane = tsdb.rollup_store.lane(
                        entry["interval"], entry["agg"], entry["pre"])
                    lane.get_or_create_series(key).restore_arrays(
                        arrays["s%d_ts" % i], arrays["s%d_val" % i],
                        arrays["s%d_ival" % i], arrays["s%d_isint" % i])

        for note in manifest["annotations"]:
            tsdb.store.add_annotation(Annotation(**note))

        if manifest["histograms"] and tsdb.histogram_store is not None:
            for entry in manifest["histograms"]:
                key = SeriesKey(entry["metric"],
                                tuple(tuple(t) for t in entry["tags"]))
                for t, hist_json in entry["points"]:
                    tsdb.histogram_store.add_point(
                        key, t, SimpleHistogram.from_pojo(hist_json))

        for m in manifest["uidmeta"]:
            meta = tsdb.meta_store.ensure_uidmeta(
                m["type"].lower(), m["uid"], m["name"])
            meta.display_name = m.get("displayName", "")
            meta.description = m.get("description", "")
            meta.notes = m.get("notes", "")
            meta.created = m.get("created", 0)
            meta.custom = m.get("custom")
        for m in manifest["tsmeta"]:
            meta = tsdb.meta_store.ensure_tsmeta(m["tsuid"])
            meta.display_name = m.get("displayName", "")
            meta.description = m.get("description", "")
            meta.notes = m.get("notes", "")
            meta.created = m.get("created", 0)
            meta.custom = m.get("custom")
            meta.units = m.get("units", "")
            meta.data_type = m.get("dataType", "")
            meta.retention = m.get("retention", 0)
            meta.last_received = m.get("lastReceived", 0)
            meta.total_dps = m.get("totalDatapoints", 0)

        for t in manifest["trees"]:
            tree = Tree(tree_id=t["treeId"], name=t.get("name", ""),
                        description=t.get("description", ""),
                        notes=t.get("notes", ""),
                        strict_match=bool(t.get("strictMatch")),
                        enabled=bool(t.get("enabled")),
                        store_failures=bool(t.get("storeFailures")),
                        created=t.get("created", 0))
            with tsdb.tree_store._lock:
                tsdb.tree_store._trees[tree.tree_id] = tree
                from opentsdb_tpu.tree.objects import Branch
                tsdb.tree_store._branches.setdefault(
                    (tree.tree_id, ()), Branch(tree.tree_id, ()))
            for r in t.get("rules", []):
                tree.add_rule(TreeRule.from_json(r))

    def close(self) -> None:
        with self._wal_lock:
            if self._wal is not None:
                self._wal.close()
                self._wal = None
