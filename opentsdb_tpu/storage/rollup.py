"""Rollup lanes: multi-resolution pre-aggregation as the standing fast path.

ROADMAP item 2 (the rollup-lanes tentpole; the reference's src/rollup/
layer re-thought for the columnar rebuild).  The write-side rollup
store (rollup/store.py) accepts pre-aggregated points an EXTERNAL
pipeline computed; this module is the missing internal half: a
maintenance-thread subsystem that materializes coarse-interval
aggregate lanes (1m/1h/1d, ``tsd.rollup.intervals``) FROM the memstore
itself, so long-range dashboard queries stop re-reducing months of raw
points on every load.

The cached unit
---------------

One **lane block** = ``tsd.rollup.block_windows`` consecutive lane
cells of one (metric, lane interval), aligned to the ABSOLUTE lane
grid (block k covers cells [k*B, (k+1)*B) of the epoch-anchored grid),
holding MERGEABLE PARTIALS per (series, cell): sum, count, min, max.
Those four moments are closed under window coarsening, so any
fixed-interval downsample whose interval is an integer multiple of a
lane and whose function is lane-derivable answers EXACTLY from the
lane — sum/zimsum re-reduce with sum, count with sum, min/max with
min/max, and avg derives as (sum of sums) / (sum of counts), the same
float64 division the raw kernel performs on identical operands.
Non-derivable functions (percentiles, dev, first/last, moving
averages) and non-multiple intervals provably fall back to the exact
agg-cache/tiled/streamed paths; tests/test_rollup_lanes.py pins
lane-served == exact-fallback BITWISE on integer data for every
derivable function.

Storyboard placement (arXiv:2002.03063) under ``tsd.rollup.mb``
---------------------------------------------------------------

Which (metric, lane) pairs to materialize is not static config: every
eligible consult records a demand observation priced by the FITTED
costmodel (the monolithic stage breakdown vs the lane-served
prediction, ``ops.costmodel.predict_lane``), and the maintenance pass
greedily selects candidates by saving-per-byte until the byte budget
is spent — precompute-under-budget, with the budget enforced again at
insert time by LRU eviction.

Invalidation (incremental, on ingest)
-------------------------------------

Identical contract to the PR 9 agg cache: the memstore write path
calls ``note_mutation`` AFTER each write lands (write-then-mark), the
mark ring records (generation, range) per metric, and a block is valid
only when no mark newer than its build generation overlaps its range —
an acked write is never served stale (the planner falls back to the
exact path until the maintenance thread rebuilds the dirty block).
The ring is bounded; overflow raises the floor generation
(conservatively invalidates older blocks, never serves stale).
tsdblint's cache-coherence analyzer owns the contract: the blocks
table is declared a ``rollup-lanes`` cache whose registered
invalidator is ``invalidate`` (see the annotation above ``_blocks``)
and gutting the invalidator fails the tree (pinned by
tests/test_rollup_lanes.py).

Past the HBM wall
-----------------

A block build is itself a grouped reduction and can exceed the
``tsd.query.streaming.state_mb`` device budget (wide metrics x coarse
lanes); builds then apply PR 10's bounded-working-set stance — the
series axis splits into budget-sized tiles whose partial lanes land
straight into the block's host arrays.  SERVING past the wall is
where the PR 10 spill machinery is genuinely reused: over-budget
lane grids either fold [G, W] partial moments tile-by-tile (the
mesh's combine_* decomposition applied to tiles) or, for
non-mergeable aggregators, replay lane-derived tile grids through
the spill pool's window-striped tail (ops/tiling.py run_tiled
``tile_grid_fn``) — see the planner's ``_run_lane_serve``.

This module stays importable numpy-only (device work lives in
ops/pipeline.py run_lane_partials; obs.jaxprof imports lazily).
"""

from __future__ import annotations

import logging
import threading
from collections import deque
from dataclasses import dataclass, field

import numpy as np

from opentsdb_tpu.obs.registry import REGISTRY
from opentsdb_tpu.utils import datetime_util as DT

_LOG = logging.getLogger("rollup_lanes")

# bytes per lane cell: sum f64 + count i32 + min f64 + max f64
LANE_CELL_BYTES = 28

# bound on retained (generation, range) dirty marks per metric —
# overflow raises the floor generation (same stance as agg_cache)
_MARK_RING = 512

# bound on tracked demand candidates (stalest-first eviction)
_DEMAND_MAX = 1024

# hard cap on lane blocks one plan/coverage/refresh walk may touch —
# a request-shaped range must never drive an unbounded loop (the
# query's own windows are bounded by the budget guards downstream;
# these walks run BEFORE them)
_MAX_BLOCK_WALK = 65536

# Downsample functions a lane answers exactly.  Aliases share their
# canonical reduction: zimsum downsamples as sum, mimmin/mimmax as
# min/max (ops/downsample.py PREFIX_AGGS / EXTREME_AGGS).
DERIVABLE_DS = frozenset(
    {"sum", "zimsum", "count", "avg", "min", "mimmin", "max", "mimmax"})

# host batch-build cost per raw point (same figure the agg cache
# charges) — what a lane hit SAVES includes never copying the points
_HOST_BUILD_S_PER_POINT = 5e-9


@dataclass
class _LaneBlock:
    """One materialized block: [S, B] mergeable partials per cell."""
    metric: int
    lane_ms: int
    rows: dict               # Series object -> row index (identity keyed)
    sums: np.ndarray         # [S, B] float64 (0.0 in empty cells)
    counts: np.ndarray       # [S, B] int32 (0 in empty cells)
    mins: np.ndarray         # [S, B] float64 (+inf in empty cells)
    maxs: np.ndarray         # [S, B] float64 (-inf in empty cells)
    gen: int                 # build generation (mark-ring validation)
    lo_ms: int               # covered range [lo_ms, hi_ms] inclusive
    hi_ms: int
    nbytes: int = 0
    hits: int = 0


@dataclass
class LanePlan:
    """An executable lane-served decomposition handed to the planner."""
    metric: int
    lane: str                # configured lane label ("1h")
    lane_ms: int
    k: int                   # lane cells per query window
    wf_lo: int               # first/last FULL window index in the grid
    wf_hi: int
    n_cells: int             # interior cells assembled
    # (entry, rows[S] int64, c0, c1, dst_off): each block's own
    # series->row index vector + the column slice it contributes
    # (blocks built at different times may order rows differently)
    segments: list = field(default_factory=list)
    gen0: int = 0
    decision: dict = field(default_factory=dict)
    striped: bool = False    # over-budget: window-striped tail replay
    tile_plan: object = None  # ops.tiling.TilePlan when striped


class RollupLanes:
    """Maintenance-built multi-resolution lane store + plan API."""

    def __init__(self, config):
        self.config = config
        labels = [t.strip() for t in config.get_string(
            "tsd.rollup.intervals").split(",") if t.strip()]
        # (label, lane_ms), coarsest first — the widest lane that
        # divides a query interval serves it with the fewest cells
        self.lanes: list[tuple[str, int]] = sorted(
            ((lb, DT.parse_duration(lb)) for lb in labels),
            key=lambda p: -p[1])
        if not self.lanes:
            raise ValueError("tsd.rollup.intervals must name at least "
                             "one lane interval")
        bw = max(config.get_int("tsd.rollup.block_windows"), 8)
        p = 8
        while p < bw:
            p <<= 1
        self.block_windows = p
        self.max_bytes = config.get_int("tsd.rollup.mb") * 2 ** 20
        self.refresh_blocks = max(
            config.get_int("tsd.rollup.refresh_blocks"), 1)
        self.delay_ms = max(config.get_int("tsd.rollup.delay_ms"), 0)
        self.fix_duplicates = config.fix_duplicates
        # flight recorder (obs/flightrec.py), attached by the TSDB
        # after construction: maintenance build passes are retained
        # diagnostics (lane staleness post-mortems start there)
        self.recorder = None
        self._lock = threading.Lock()
        # the materialized lane blocks — THE backing store of this
        # subsystem; (metric, lane_ms, block_idx) -> _LaneBlock, dict
        # order = LRU recency (move-to-end on consult)
        # cache: rollup-lanes invalidated-by: invalidate
        self._blocks = {}  # guarded-by: _lock
        # (metric) -> deque[(gen, lo_ms, hi_ms)] dirty marks
        self._marks: dict[int, deque] = {}  # guarded-by: _lock
        # metric -> floor generation (mark-ring overflow safety)
        self._floor: dict[int, int] = {}  # guarded-by: _lock
        self._gen = 0  # guarded-by: _lock
        # newest generation any plan/build snapshotted (mark coalescing
        # stops at it — see agg_cache's identical field)
        self._planned_gen = 0  # guarded-by: _lock
        # ingest fast path: until the FIRST build reads store data,
        # note_mutation returns without the lock (sticky; written only
        # under _lock, read without it — same reasoning as
        # agg_cache._maybe_cached)
        self._armed = False  # guarded-by: _lock (writes; reads race)
        # (metric, lane_ms) -> demand record {n, saving_s, lo, hi,
        # series, tick}: the Storyboard selection corpus
        self._demand: dict[tuple, dict] = {}  # guarded-by: _lock
        self._tick = 0  # guarded-by: _lock
        self._bytes = 0  # guarded-by: _lock
        # stats (walked by TSDB.collect_stats)  # guarded-by: _lock
        self.hits = 0
        self.misses = 0
        self.builds = 0
        self.build_errors = 0
        self.evictions = 0
        self.invalidations = 0
        self.served_windows = 0

    # -- metrics helpers -------------------------------------------------

    def _set_gauges_locked(self) -> None:
        REGISTRY.gauge(
            "tsd.rollup.lane.bytes",
            "Rollup-lane store resident bytes (tsd.rollup.mb budget)"
        ).set(float(self._bytes))
        REGISTRY.gauge(
            "tsd.rollup.lane.blocks",
            "Rollup-lane blocks resident").set(float(len(self._blocks)))

    @staticmethod
    def _count_hit(lane: str) -> None:
        REGISTRY.counter(
            "tsd.rollup.lane.hits",
            "Plans answered from a rollup lane, by lane interval"
        ).labels(lane=lane).inc()

    @staticmethod
    def _count_miss(reason: str) -> None:
        REGISTRY.counter(
            "tsd.rollup.lane.misses",
            "Lane-eligible plans that fell back to the exact paths, "
            "by reason").labels(reason=reason).inc()

    # -- invalidation ----------------------------------------------------

    def note_mutation(self, metric: int, lo_ms: int | None,
                      hi_ms: int | None, store=None) -> None:
        """Ingest-side hook (memstore mutation listener), called AFTER
        the write lands (write-then-mark).  Routes to ``invalidate`` —
        the registered invalidator the cache-coherence lint holds this
        store to."""
        del store
        if not self._armed:
            # no build has ever read store data: nothing materialized
            # can be stale, and the hot ingest path skips the lock.
            # Sound because this read happens after the caller's write
            # landed and refresh() arms the flag under the lock BEFORE
            # its first store read.
            return
        self.invalidate(metric=metric, lo_ms=lo_ms, hi_ms=hi_ms)

    def invalidate(self, metric: int | None = None,
                   lo_ms: int | None = None,
                   hi_ms: int | None = None) -> None:
        """THE invalidation entry point (registered in the `# cache:`
        declaration above ``_blocks``).

        With a metric: record a dirty mark over [lo_ms, hi_ms] (None
        bounds = open) — blocks overlapping the range fail their
        generation check from now on and the maintenance pass rebuilds
        them.  Without a metric: drop everything (/api/dropcaches)."""
        with self._lock:
            if metric is None:
                self.invalidations += 1
                self._blocks = {}
                self._marks.clear()
                self._floor.clear()
                self._bytes = 0
                self._gen += 1
                self._set_gauges_locked()
            else:
                lo = -2 ** 62 if lo_ms is None else int(lo_ms)
                hi = 2 ** 62 if hi_ms is None else int(hi_ms)
                ring = self._marks.get(metric)
                if ring is None:
                    ring = self._marks[metric] = deque(maxlen=_MARK_RING)
                if ring and ring[-1][0] > self._planned_gen:
                    # per-point ingest coalesces to one widened mark
                    # while no plan/build snapshotted in between (same
                    # argument as agg_cache.invalidate)
                    g, plo, phi = ring[-1]
                    ring[-1] = (g, min(plo, lo), max(phi, hi))
                    return
                self.invalidations += 1
                self._gen += 1
                if len(ring) == _MARK_RING:
                    self._floor[metric] = max(
                        self._floor.get(metric, 0), ring[0][0])
                ring.append((self._gen, lo, hi))
        REGISTRY.counter(
            "tsd.rollup.lane.invalidations",
            "Rollup-lane invalidation marks (ingest dirty ranges, "
            "dropcaches)").inc()

    def _valid_locked(self, entry: _LaneBlock) -> bool:
        if entry.gen < self._floor.get(entry.metric, 0):
            return False
        ring = self._marks.get(entry.metric)
        if not ring:
            return True
        for gen, lo, hi in reversed(ring):
            if gen <= entry.gen:
                break
            if lo <= entry.hi_ms and hi >= entry.lo_ms:
                return False
        return True

    # -- lane selection helpers ------------------------------------------

    # effects: pure
    def lane_for(self, interval_ms: int,
                 first_window_ms: int) -> tuple[str, int] | None:
        """The coarsest configured lane able to serve a fixed grid:
        its span must divide both the interval and the grid origin
        (epoch-aligned origins always do when the interval divides)."""
        if interval_ms <= 0:
            return None
        for label, lane_ms in self.lanes:
            if interval_ms % lane_ms == 0 \
                    and first_window_ms % lane_ms == 0:
                return label, lane_ms
        return None

    # effects: pure
    @staticmethod
    def derivable(ds_fn: str | None) -> bool:
        return ds_fn in DERIVABLE_DS

    # -- planning --------------------------------------------------------

    # effects: observe-gated(observe)
    def plan(self, metric: int, series_list, windows, start_ms: int,
             end_ms: int, ds_fn: str, platform: str, s: int,
             n_max: int, g_pad: int, has_rate: bool,
             total_points: int = 0, observe: bool = True):
        """Lane-serve decision for one fixed-grid downsample segment.

        Returns (LanePlan | None, decision dict).  None = fall back to
        the exact paths; the decision dict always comes back for the
        trace span (PR 6 contract).  Every eligible consult — hit or
        miss — records a costmodel-priced demand observation, the
        Storyboard selection corpus ``refresh()`` shops from.

        ``observe=False`` is the EXPLAIN engine's dry-run arm
        (query/explain.py): the verdict computation is identical, but
        nothing is recorded — no demand observation, no LRU recency
        bump, no hit/miss counters, no ``_planned_gen`` advance, and
        stale/incomplete blocks are left in place for the real pass to
        reap — so explaining a query cannot perturb what the
        maintenance selector builds or what the executor then
        decides."""
        from opentsdb_tpu.obs import jaxprof
        from opentsdb_tpu.ops import costmodel as cm
        from opentsdb_tpu.ops.downsample import pad_pow2
        interval = windows.interval_ms
        first = windows.first_window_ms
        w = windows.count
        decision = {"decision": "fallback", "reason": "", "lane": "",
                    "coverage": 0.0}
        if not self.derivable(ds_fn):
            decision["reason"] = "not_derivable"
            return None, decision
        picked = self.lane_for(interval, first)
        if picked is None:
            decision["reason"] = "no_lane_divides"
            return None, decision
        label, lane_ms = picked
        k = interval // lane_ms
        decision["lane"] = label
        # interior FULL windows only (edge windows see a partial point
        # population and always recompute from raw — same rule as the
        # agg cache)
        wf_lo = 0 if start_ms <= first else 1
        last_start = first + (w - 1) * interval
        wf_hi = w - 1 if last_start + interval - 1 <= end_ms else w - 2
        if wf_hi < wf_lo:
            decision["reason"] = "no_full_windows"
            return None, decision
        c_lo = (first + wf_lo * interval) // lane_ms
        c_hi = (first + (wf_hi + 1) * interval) // lane_ms - 1
        n_cells = c_hi - c_lo + 1
        bw = self.block_windows
        b_lo, b_hi = c_lo // bw, c_hi // bw
        if b_hi - b_lo + 1 > _MAX_BLOCK_WALK:
            decision["reason"] = "too_many_blocks"
            return None, decision

        # costmodel economics: what the lane saves vs the monolithic
        # exact plan (prices the demand record AND the span annotation)
        wp = pad_pow2(w)
        np_pad = pad_pow2(max(int(n_max), 1))
        full_bd = jaxprof.stage_breakdown(platform, s, np_pad, wp, g_pad,
                                          ds_fn, has_rate)
        ds_s = full_bd.get("downsample", 0.0)
        pred_full = sum(full_bd.values()) \
            + total_points * _HOST_BUILD_S_PER_POINT
        pred_lane = (sum(full_bd.values()) - ds_s) \
            + cm.predict_lane(s, wf_hi - wf_lo + 1, k, platform)
        saving = max(pred_full - pred_lane, 0.0)
        decision["predictedLaneMs"] = round(pred_lane * 1e3, 3)
        decision["predictedFullMs"] = round(pred_full * 1e3, 3)

        # pass 1, under the lock: generation snapshot + mark-validity +
        # LRU bump; refs only (block arrays/row maps are immutable once
        # stored, so completeness + row-vector work happens outside)
        candidates: list = []
        missing = 0
        with self._lock:
            gen0 = self._gen
            if observe:
                self._planned_gen = max(self._planned_gen, gen0)
                self._note_demand_locked(metric, lane_ms, s, start_ms,
                                         end_ms, saving)
            for b in range(b_lo, b_hi + 1):
                key = (metric, lane_ms, b)
                entry = self._blocks.get(key)
                if entry is None or not self._valid_locked(entry):
                    if entry is not None and observe:
                        self._drop_locked(key)
                    missing += 1
                    continue
                if observe:
                    # LRU recency = dict order (move-to-end)
                    self._blocks.pop(key)
                    self._blocks[key] = entry
                candidates.append((key, entry, b))
        # pass 2, outside the lock: row completeness + per-block row
        # vectors (blocks built at different times may order rows
        # differently — each segment carries its own index vector)
        segments: list = []
        incomplete: list = []
        for key, entry, b in candidates:
            if not all(srs in entry.rows for srs in series_list):
                incomplete.append(key)
                missing += 1
                continue
            rows = np.fromiter((entry.rows[srs] for srs in series_list),
                               np.int64, count=len(series_list))
            lo_cell = max(c_lo, b * bw)
            hi_cell = min(c_hi, (b + 1) * bw - 1)
            segments.append((entry, rows, lo_cell - b * bw,
                             hi_cell - b * bw + 1, lo_cell - c_lo))
        if incomplete and observe:
            with self._lock:
                for key in incomplete:
                    # row-incomplete (a series appeared since the
                    # build): drop so the next pass rebuilds
                    self._drop_locked(key)
        if missing:
            decision["reason"] = "cold"
            decision["coverage"] = round(
                1.0 - missing / (b_hi - b_lo + 1), 4)
            if observe:
                self._count_miss("cold")
                with self._lock:
                    self.misses += 1
            return None, decision
        decision.update(decision="lane", reason="served", coverage=1.0,
                        cells=n_cells, blocks=len(segments))
        # hit accounting happens in note_served() once the planner
        # COMMITS to the plan — an over-budget plan the striping sizer
        # voids must not count as a lane hit
        return LanePlan(metric=metric, lane=label, lane_ms=lane_ms,
                        k=k, wf_lo=wf_lo, wf_hi=wf_hi, n_cells=n_cells,
                        segments=segments, gen0=gen0,
                        decision=decision), decision

    def note_served(self, plan: LanePlan) -> None:
        """The planner committed to this plan (residency/striping
        checks passed): count the hit."""
        with self._lock:
            self.hits += 1
            self.served_windows += plan.wf_hi - plan.wf_lo + 1
        self._count_hit(plan.lane)

    def note_striping_fallback(self) -> None:
        """An over-budget plan the striping sizer could not serve fell
        back to the exact paths: count the miss, not a hit."""
        with self._lock:
            self.misses += 1
        self._count_miss("striping")

    def _note_demand_locked(self, metric: int, lane_ms: int, s: int,
                            lo_ms: int, hi_ms: int,
                            saving_s: float) -> None:
        key = (metric, lane_ms)
        self._tick += 1
        rec = self._demand.pop(key, None)
        if rec is None:
            rec = {"n": 0, "saving_s": 0.0, "lo": lo_ms, "hi": hi_ms,
                   "series": s}
        rec["n"] += 1
        rec["saving_s"] += saving_s
        rec["lo"] = min(rec["lo"], lo_ms)
        rec["hi"] = max(rec["hi"], hi_ms)
        rec["series"] = max(rec["series"], s)
        rec["tick"] = self._tick
        self._demand[key] = rec    # move-to-end: stalest-first eviction
        while len(self._demand) > _DEMAND_MAX:
            self._demand.pop(next(iter(self._demand)))

    # -- serving: grid derivation ----------------------------------------

    def derive_grid(self, plan: LanePlan, ds_fn: str, fill_policy: str,
                    fill_value: float, row_lo: int = 0,
                    row_hi: int | None = None
                    ) -> tuple[np.ndarray, np.ndarray]:
        """Assemble the interior [rows, windows] downsample grid from
        the plan's lane cells — numpy, host-side, outside any lock
        (blocks are immutable once stored).

        Exactness: window w re-reduces its k cells with the function's
        mergeable form; on integer data every value is bit-identical
        to what ``ops.downsample.downsample`` computes from the raw
        points (sums of exactly-representable integers are exact in
        any association, min/max are selections, and avg divides the
        same two exact operands).  ``row_lo``/``row_hi`` slice the
        series axis for the window-striped tiled replay."""
        first = plan.segments[0][1]
        s = len(first[row_lo:row_hi])
        k = plan.k
        nc = plan.n_cells
        sums = np.empty((s, nc), np.float64)
        counts = np.empty((s, nc), np.int64)
        need_min = ds_fn in ("min", "mimmin")
        need_max = ds_fn in ("max", "mimmax")
        mins = np.empty((s, nc), np.float64) if need_min else None
        maxs = np.empty((s, nc), np.float64) if need_max else None
        for entry, seg_rows, c0, c1, off in plan.segments:
            rows = seg_rows[row_lo:row_hi]
            sums[:, off:off + c1 - c0] = entry.sums[rows, c0:c1]
            counts[:, off:off + c1 - c0] = entry.counts[rows, c0:c1]
            if need_min:
                mins[:, off:off + c1 - c0] = entry.mins[rows, c0:c1]
            if need_max:
                maxs[:, off:off + c1 - c0] = entry.maxs[rows, c0:c1]
        nw = nc // k
        cnt_w = counts.reshape(s, nw, k).sum(axis=2)
        mask = cnt_w > 0
        if ds_fn in ("sum", "zimsum"):
            vals = sums.reshape(s, nw, k).sum(axis=2)
        elif ds_fn == "count":
            vals = cnt_w.astype(np.float64)
        elif ds_fn == "avg":
            vals = sums.reshape(s, nw, k).sum(axis=2) \
                / np.maximum(cnt_w, 1)
        elif need_min:
            vals = mins.reshape(s, nw, k).min(axis=2)
        elif need_max:
            vals = maxs.reshape(s, nw, k).max(axis=2)
        else:  # pragma: no cover — plan() rejected it already
            raise ValueError("not lane-derivable: %s" % ds_fn)
        # fill semantics mirror ops.downsample.apply_fill over interior
        # windows (all interior windows are live by construction)
        from opentsdb_tpu.ops.downsample import (FILL_NAN, FILL_NONE,
                                                 FILL_NULL, FILL_SCALAR,
                                                 FILL_ZERO)
        if fill_policy == FILL_NONE:
            vals = np.where(mask, vals, np.nan)
        else:
            if fill_policy == FILL_ZERO:
                fill = 0.0
            elif fill_policy in (FILL_NAN, FILL_NULL):
                fill = np.nan
            elif fill_policy == FILL_SCALAR:
                fill = float(fill_value)
            else:
                raise ValueError("Unrecognized fill policy: "
                                 + fill_policy)
            vals = np.where(mask, vals, fill)
            mask = np.ones_like(mask)
        return vals, mask

    # -- admission-estimate support --------------------------------------

    def coverage(self, metric: int, interval_ms: int, ds_fn: str,
                 start_ms: int, end_ms: int) -> float:
        """Fraction of the plan's interior windows servable from valid
        lane blocks — tsd/admission.py prices the lane-served plan
        with it so warm dashboards admit where cold ones shed.
        Approximate: ignores the series-set completeness check."""
        if not self.derivable(ds_fn) or interval_ms <= 0:
            return 0.0
        first = start_ms - start_ms % interval_ms
        picked = self.lane_for(interval_ms, first)
        if picked is None:
            return 0.0
        _label, lane_ms = picked
        w = (end_ms - end_ms % interval_ms - first) // interval_ms + 1
        wf_lo = 0 if start_ms <= first else 1
        last_start = first + (w - 1) * interval_ms
        wf_hi = w - 1 if last_start + interval_ms - 1 <= end_ms else w - 2
        if wf_hi < wf_lo:
            return 0.0
        c_lo = (first + wf_lo * interval_ms) // lane_ms
        c_hi = (first + (wf_hi + 1) * interval_ms) // lane_ms - 1
        bw = self.block_windows
        good = 0
        total = c_hi // bw - c_lo // bw + 1
        # the walk bound is a request-range clamp: this runs on the
        # pre-admission path, before any budget guard
        total = min(total, _MAX_BLOCK_WALK)
        with self._lock:
            for i in range(total):
                entry = self._blocks.get(
                    (metric, lane_ms, c_lo // bw + i))
                if entry is not None and self._valid_locked(entry):
                    good += 1
        return good / max(total, 1)

    # -- maintenance: Storyboard selection + block builds ----------------

    def refresh(self, store, max_blocks: int | None = None,
                now_ms: int | None = None) -> int:
        """One maintenance pass: select (metric, lane) targets by
        saving-per-byte under ``tsd.rollup.mb``, then (re)build up to
        ``max_blocks`` missing/invalid blocks over the demanded
        ranges.  Returns blocks built."""
        if max_blocks is None:
            max_blocks = self.refresh_blocks
        if now_ms is None:
            now_ms = DT.current_time_millis()
        built = self._refresh(store, max_blocks, now_ms)
        if built and self.recorder is not None:
            with self._lock:
                resident = len(self._blocks)
            self.recorder.record("rollup_build", blocks=built,
                                 resident=resident)
        return built

    def _refresh(self, store, max_blocks: int, now_ms: int) -> int:
        with self._lock:
            demand = sorted(self._demand.items(),
                            key=lambda kv: -kv[1]["saving_s"])
        # greedy saving-per-byte selection under the byte budget
        remaining = self.max_bytes
        selected: list[tuple] = []
        scored = []
        for key, rec in demand:
            _metric, lane_ms = key
            cells = max((rec["hi"] - rec["lo"]) // lane_ms + 1, 1)
            bytes_est = rec["series"] * cells * LANE_CELL_BYTES
            if bytes_est <= 0:
                continue
            # saving_s is already frequency-weighted (one increment
            # per consult) — dividing by bytes gives saving-per-byte
            scored.append((rec["saving_s"] / bytes_est,
                           bytes_est, key, rec))
        scored.sort(key=lambda t: -t[0])
        for _score, bytes_est, key, rec in scored:
            if bytes_est <= remaining:
                selected.append((key, rec))
                remaining -= bytes_est
        built = 0
        bw = self.block_windows
        for (metric, lane_ms), rec in selected:
            label = next((lb for lb, ms in self.lanes
                          if ms == lane_ms), str(lane_ms))
            series_list = sorted(
                store.series_for_metric(metric),
                key=lambda srs: (srs.key.metric, srs.key.tags))
            if not series_list:
                continue
            span = bw * lane_ms
            b0 = rec["lo"] // span
            n_scan = min(rec["hi"] // span - b0 + 1, _MAX_BLOCK_WALK)
            for b in range(b0, b0 + n_scan):
                if built >= max_blocks:
                    return built
                hi_ms = (b + 1) * span - 1
                if self.delay_ms and hi_ms > now_ms - self.delay_ms:
                    # the actively-written head: skip it this pass so
                    # continuous ingest doesn't rebuild it every tick
                    continue
                key = (metric, lane_ms, b)
                with self._lock:
                    entry = self._blocks.get(key)
                    if entry is not None and self._valid_locked(entry) \
                            and all(srs in entry.rows
                                    for srs in series_list):
                        continue
                try:
                    if self._build_block(metric, label, lane_ms, b,
                                         series_list):
                        built += 1
                except Exception:
                    with self._lock:
                        self.build_errors += 1
                    REGISTRY.counter(
                        "tsd.rollup.lane.build_errors",
                        "Lane block builds that raised (caught + "
                        "counted; retried next pass)").inc()
                    _LOG.exception("lane block build failed: %r", key)
        return built

    def _build_block(self, metric: int, label: str, lane_ms: int,
                     b: int, series_list) -> bool:
        """Materialize one [S, B] partials block from the raw store.

        Over-wall builds apply PR 10's bounded-working-set stance to
        construction: the series axis tiles to the device-state
        budget, and each tile's partial lanes land straight into the
        preallocated destination arrays (the block IS the host
        buffer, so nothing needs to stage anywhere else)."""
        from opentsdb_tpu.ops.downsample import FixedWindows, pad_pow2
        from opentsdb_tpu.ops.pipeline import (build_batch_direct,
                                               run_lane_partials)
        bw = self.block_windows
        span = bw * lane_ms
        lo, hi = b * span, (b + 1) * span - 1
        s = len(series_list)
        with self._lock:
            gen0 = self._gen
            self._planned_gen = max(self._planned_gen, gen0)
            # arm the ingest-side mark path BEFORE reading store data
            self._armed = True
        fix = self.fix_duplicates
        counts = [srs.window_count(lo, hi, fix) for srs in series_list]
        n_max = max(counts, default=0)
        budget = self.config.get_int(
            "tsd.query.streaming.state_mb") * 2 ** 20
        # per-series working bytes: the padded point batch (ts 8 +
        # val 8 + mask 1) plus the four [*, B] partial lanes
        per_row = pad_pow2(max(n_max, 1)) * 17 + bw * LANE_CELL_BYTES
        tile_rows = s if budget <= 0 else max(budget // per_row, 1)
        tile_rows = min(tile_rows, s)
        sums = np.zeros((s, bw), np.float64)
        cnts = np.zeros((s, bw), np.int32)
        mins = np.full((s, bw), np.inf, np.float64)
        maxs = np.full((s, bw), -np.inf, np.float64)
        wspec, wargs = FixedWindows(lane_ms, lo, bw).split()
        for t_lo in range(0, s, tile_rows):
            t_hi = min(t_lo + tile_rows, s)
            ts, val, mask, _ = build_batch_direct(
                series_list[t_lo:t_hi], lo, hi, fix)
            tsu, tcn, tmn, tmx = run_lane_partials(
                wspec, ts, val, mask, wargs)
            sums[t_lo:t_hi] = np.asarray(tsu)[:, :bw]
            cnts[t_lo:t_hi] = np.asarray(tcn)[:, :bw]
            mins[t_lo:t_hi] = np.asarray(tmn)[:, :bw]
            maxs[t_lo:t_hi] = np.asarray(tmx)[:, :bw]
        entry = _LaneBlock(
            metric=metric, lane_ms=lane_ms,
            rows={srs: i for i, srs in enumerate(series_list)},
            sums=sums, counts=cnts, mins=mins, maxs=maxs, gen=gen0,
            lo_ms=lo, hi_ms=hi, nbytes=s * bw * LANE_CELL_BYTES)
        with self._lock:
            if not self._valid_locked(entry):
                # a write landed in range while building: discard; the
                # next pass rebuilds from post-write data
                return False
            if entry.nbytes > self.max_bytes:
                return False
            self._evict_for_locked(entry.nbytes)
            key = (metric, lane_ms, b)
            if key in self._blocks:
                self._drop_locked(key)
            self._blocks[key] = entry
            self._bytes += entry.nbytes
            self.builds += 1
            self._set_gauges_locked()
        REGISTRY.counter(
            "tsd.rollup.lane.builds",
            "Lane blocks materialized from the memstore, by lane "
            "interval").labels(lane=label).inc()
        return True

    # -- eviction --------------------------------------------------------

    def _drop_locked(self, key: tuple) -> None:
        entry = self._blocks.pop(key, None)
        if entry is not None:
            self._bytes -= entry.nbytes

    def _evict_for_locked(self, incoming: int) -> None:
        while self._blocks and \
                self._bytes + incoming > self.max_bytes:
            self._drop_locked(next(iter(self._blocks)))
            self.evictions += 1
            REGISTRY.counter(
                "tsd.rollup.lane.evictions",
                "Lane blocks evicted by the tsd.rollup.mb LRU").inc()

    # -- stats -----------------------------------------------------------

    def collect_stats(self) -> dict:
        with self._lock:
            return {
                "tsd.query.rollup.hits": float(self.hits),
                "tsd.query.rollup.misses": float(self.misses),
                "tsd.query.rollup.builds": float(self.builds),
                "tsd.query.rollup.build_errors": float(
                    self.build_errors),
                "tsd.query.rollup.blocks": float(len(self._blocks)),
                "tsd.query.rollup.bytes": float(self._bytes),
                "tsd.query.rollup.evictions": float(self.evictions),
                "tsd.query.rollup.invalidations": float(
                    self.invalidations),
                "tsd.query.rollup.served_windows": float(
                    self.served_windows),
                "tsd.query.rollup.demand_entries": float(
                    len(self._demand)),
            }

    def __len__(self) -> int:
        with self._lock:
            return len(self._blocks)
