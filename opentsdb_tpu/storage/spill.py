"""Bounded partial-aggregate spill pool: host-RAM ring + disk overflow.

The out-of-core tiled executor (ops/tiling.py, ROADMAP item 4) finishes
one series tile at a time and must park each tile's [S_tile, W] partial
grids somewhere until the window-striped assembly pass replays them —
"somewhere" is this pool, the spilled-window-aggregation stance of
arXiv:2007.10385 reduced to two byte-budgeted tiers:

  host tier   numpy arrays in an insertion-ordered ring, budgeted by
              ``tsd.query.spill.host_mb``.  New entries always land
              here (the producer just materialized them on the host
              anyway); when the ring overflows, the NEWEST entries
              demote to disk.  Newest-first matches the executor's
              access pattern: entries are written tile-major but
              replayed STRIPE-major, so the oldest surviving entry
              (lowest tile, lowest stripe) is among the next to be
              read while the newest (highest tile, highest stripe) is
              read last — the assembly pass starts from RAM and takes
              its disk reads at the tail.
  disk tier   one ``.npy`` file per array under
              ``tsd.query.spill.dir`` (a private tempdir when unset),
              budgeted by ``tsd.query.spill.disk_mb``.  Reads go
              through ``numpy`` memory-mapping so a window-striped
              column slice fetches ~its own bytes, not the whole
              tile grid, bounding the assembly pass's read
              amplification.

Capacity is a REFUSAL, not an OOM: ``put`` raises ``SpillCapacityError``
when an entry cannot fit even after demoting everything demotable, and
``SpillWriteError`` when the disk tier itself fails (disk full — the
``spill.write`` fault site injects exactly this for
``tools/chaos_soak.py --spill``).  The executor translates either into
the query-level 413/503 contract and releases whatever the query had
already pooled; a failed spill never wedges the pool for later queries.

Ownership contract (tsdblint resource_leak): disk files are opened via
``open_spill_file`` / ``SpillPool.open_spill`` — a registered
acquisition kind — and every handle either closes in a ``finally`` or
transfers ownership to the pool's ``_files`` table, whose entries
``free``/``close`` unlink.  The pool itself is process-long-lived
(``TSDB.shutdown`` closes it).

Like the rest of storage/, this module stays importable numpy-only.
"""

from __future__ import annotations

import logging
import os
import tempfile
import threading

import numpy as np

from opentsdb_tpu.utils import faults

LOG = logging.getLogger(__name__)


class SpillError(Exception):
    """Base: the spill pool could not hold or produce an entry."""


class SpillCapacityError(SpillError):
    """Entry exceeds the pool's combined host+disk byte budget."""


class SpillWriteError(SpillError):
    """The disk tier failed mid-write (disk full / injected fault)."""


def open_spill_file(path: str, mode: str = "wb"):
    """Open one spill tier file.  A dedicated acquisition kind under
    tsdblint's resource_leak analyzer: every handle this returns must
    reach close/with/finally or transfer ownership to the pool."""
    return open(path, mode)


class SpillPool:
    """Byte-budgeted two-tier store for numpy array tuples.

    Thread-safe: queries spill concurrently under the admission gate's
    permit count.  Accounting and the ring/files tables live under one
    lock; the (potentially slow) disk writes happen OUTSIDE it on the
    demoting thread, with the entry kept HOST-VISIBLE (and marked
    non-re-demotable) until its file write completes — a concurrent
    ``get`` of a mid-demotion key serves the RAM copy and never falls
    between tiers or reads a half-written file.
    """

    def __init__(self, host_budget_bytes: int, disk_budget_bytes: int,
                 directory: str | None = None):
        self._lock = threading.Lock()
        self.host_budget = max(int(host_budget_bytes), 0)
        self.disk_budget = max(int(disk_budget_bytes), 0)
        # flight recorder (obs/flightrec.py), attached by the TSDB
        # after construction: host->disk demotions are retained
        # diagnostics (spill pressure is how the HBM wall shows up)
        self.recorder = None
        self._configured_dir = directory or None
        self._dir: str | None = None       # guarded-by: _lock (lazy tempdir)
        self._own_dir = False              # guarded-by: _lock
        self._next_key = 0                 # guarded-by: _lock
        # host ring: key -> tuple of arrays (insertion-ordered; oldest
        # first — dict preserves insertion order)
        self._host: dict[int, tuple] = {}  # guarded-by: _lock
        # disk tier: key -> list of file paths (one per array)
        self._files: dict[int, list] = {}  # guarded-by: _lock
        self._bytes: dict[int, int] = {}   # guarded-by: _lock (per entry)
        # keys mid-demotion (host copy still servable; not re-demotable)
        self._demoting: set[int] = set()   # guarded-by: _lock
        self.host_bytes = 0                # guarded-by: _lock
        self.disk_bytes = 0                # guarded-by: _lock
        self._closed = False               # guarded-by: _lock

    # -- metrics ------------------------------------------------------- #

    def _gauges_locked(self) -> None:
        from opentsdb_tpu.obs.registry import REGISTRY
        g = REGISTRY.gauge("tsd.query.spill.bytes",
                           "Spill-pool resident bytes, by tier")
        g.labels(tier="host").set(float(self.host_bytes))
        g.labels(tier="disk").set(float(self.disk_bytes))
        e = REGISTRY.gauge("tsd.query.spill.entries",
                           "Spill-pool resident entries, by tier")
        e.labels(tier="host").set(float(len(self._host)))
        e.labels(tier="disk").set(float(len(self._files)))

    # -- tier plumbing -------------------------------------------------- #

    def _ensure_dir_locked(self) -> str:
        if self._dir is None:
            if self._configured_dir:
                os.makedirs(self._configured_dir, exist_ok=True)
                self._dir = self._configured_dir
            else:
                self._dir = tempfile.mkdtemp(prefix="tsdb_spill_")
                self._own_dir = True
        return self._dir

    def _write_entry(self, directory: str, key: int, arrays: tuple) -> list:
        """Write one entry's arrays to the disk tier; returns the paths.
        Raises SpillWriteError (cleaning up its own partial files) on
        any OS-level failure, including the injected spill.write fault."""
        paths = []
        try:
            for i, a in enumerate(arrays):
                path = os.path.join(directory, "spill_%d_%d.npy" % (key, i))
                faults.check("spill.write")
                fh = open_spill_file(path)
                try:
                    np.save(fh, a)
                finally:
                    fh.close()
                paths.append(path)
        except OSError as e:
            for p in paths:
                try:
                    os.unlink(p)
                except OSError:
                    pass
            from opentsdb_tpu.obs.registry import REGISTRY
            REGISTRY.counter(
                "tsd.query.spill.write_errors",
                "Spill-pool disk writes that failed (disk full / "
                "injected fault)").inc()
            raise SpillWriteError("spill write failed: %s" % e) from e
        return paths

    def _demote_one(self) -> bool:
        """Move the NEWEST demotable host entry to disk (see the module
        docstring for why newest-first fits the stripe-major replay).
        Returns False when nothing is demotable.  Disk I/O runs outside
        the lock; the entry STAYS host-visible until its file write
        completes, so a concurrent ``get`` of the same key never falls
        between tiers."""
        with self._lock:
            key = next((k for k in reversed(self._host)
                        if k not in self._demoting), None)
            if key is None:
                return False
            arrays = self._host[key]
            nbytes = self._bytes[key]
            if self.disk_budget <= 0 \
                    or self.disk_bytes + nbytes > self.disk_budget:
                return False
            directory = self._ensure_dir_locked()
            self.disk_bytes += nbytes          # reserve before the write
            self._demoting.add(key)
        try:
            paths = self._write_entry(directory, key, arrays)
        except SpillWriteError:
            with self._lock:
                self.disk_bytes -= nbytes
                self._demoting.discard(key)
            raise
        with self._lock:
            self._demoting.discard(key)
            if self._host.pop(key, None) is None:
                # freed concurrently: the disk copy is garbage now
                self.disk_bytes -= nbytes
                stale = paths
            else:
                self.host_bytes -= nbytes
                self._files[key] = paths
                stale = ()
                from opentsdb_tpu.obs.registry import REGISTRY
                REGISTRY.counter(
                    "tsd.query.spill.evictions",
                    "Spill-pool host-ring entries demoted to the disk "
                    "tier").inc()
                # the demoted entry has now LANDED on disk — the other
                # arm of the tier-labeled landing counter (puts always
                # land host first)
                REGISTRY.counter(
                    "tsd.query.spill.spills",
                    "Partial grids written to the spill pool, by "
                    "landing tier").labels(tier="disk").inc()
            self._gauges_locked()
        if not stale and self.recorder is not None:
            self.recorder.record("spill_demote", bytes=int(nbytes))
        for p in stale:
            try:
                os.unlink(p)
            except OSError:
                pass
        return True

    # -- public API ----------------------------------------------------- #

    def put(self, arrays: tuple) -> int:
        """Pool one entry (a tuple of numpy arrays); returns its key.

        The entry lands in the host ring; older entries demote to disk
        until the ring fits its budget again.  Raises
        SpillCapacityError when the combined budgets cannot hold it and
        SpillWriteError when the disk tier fails."""
        arrays = tuple(np.ascontiguousarray(a) for a in arrays)
        nbytes = int(sum(a.nbytes for a in arrays))
        with self._lock:
            if self._closed:
                raise SpillError("spill pool is closed")
            if nbytes > max(self.host_budget, self.disk_budget):
                raise SpillCapacityError(
                    "spill entry of %d bytes exceeds every tier budget "
                    "(host %d, disk %d)" % (nbytes, self.host_budget,
                                            self.disk_budget))
            key = self._next_key
            self._next_key += 1
            self._host[key] = arrays
            self._bytes[key] = nbytes
            self.host_bytes += nbytes
            from opentsdb_tpu.obs.registry import REGISTRY
            REGISTRY.counter(
                "tsd.query.spill.spills",
                "Partial grids written to the spill pool, by landing "
                "tier").labels(tier="host").inc()
            self._gauges_locked()
        while True:
            with self._lock:
                over = self.host_bytes > self.host_budget
            if not over:
                break
            try:
                demoted = self._demote_one()
            except SpillWriteError:
                # the caller never receives a key for this entry, so it
                # must not stay pooled (its owner could not free it)
                self.free(key)
                raise
            if not demoted:
                # nothing (more) demotable: over-budget is now a refusal
                self.free(key)
                raise SpillCapacityError(
                    "spill pool over budget: host %d/%d disk %d/%d bytes"
                    % (self.host_bytes, self.host_budget,
                       self.disk_bytes, self.disk_budget))
        return key

    def get(self, key: int, col_lo: int | None = None,
            col_hi: int | None = None) -> tuple:
        """Fetch an entry (optionally a [:, col_lo:col_hi] column slice
        of every 2-D array — the window-striped read).  Disk-tier reads
        memory-map, so a stripe slice costs ~its own bytes."""
        with self._lock:
            arrays = self._host.get(key)
            paths = self._files.get(key)
        if arrays is None and paths is None:
            raise KeyError("no spill entry %d" % key)
        out = []
        if arrays is not None:
            for a in arrays:
                if col_lo is not None and a.ndim == 2:
                    a = a[:, col_lo:col_hi]
                out.append(a)
            return tuple(out)
        from opentsdb_tpu.obs.registry import REGISTRY
        REGISTRY.counter(
            "tsd.query.spill.reads",
            "Spill entries read back from the disk tier").inc()
        for p in paths:
            a = np.load(p, mmap_mode="r")
            if col_lo is not None and a.ndim == 2:
                a = a[:, col_lo:col_hi]
            out.append(np.ascontiguousarray(a))
        return tuple(out)

    def free(self, key: int) -> None:
        """Release one entry (both tiers); idempotent."""
        with self._lock:
            arrays = self._host.pop(key, None)
            paths = self._files.pop(key, None)
            nbytes = self._bytes.pop(key, 0)
            if arrays is not None:
                self.host_bytes -= nbytes
            elif paths is not None:
                self.disk_bytes -= nbytes
            if arrays is not None or paths is not None:
                from opentsdb_tpu.obs.registry import REGISTRY
                REGISTRY.counter(
                    "tsd.query.spill.invalidations",
                    "Spill entries released back to the pool").inc()
                self._gauges_locked()
        for p in paths or ():
            try:
                os.unlink(p)
            except OSError:
                pass

    def release(self, keys) -> None:
        """Free a batch of keys (the per-query cleanup path)."""
        for key in keys:
            self.free(key)

    def stats(self) -> dict:
        with self._lock:
            return {"host_bytes": self.host_bytes,
                    "disk_bytes": self.disk_bytes,
                    "host_entries": len(self._host),
                    "disk_entries": len(self._files)}

    def close(self) -> None:
        """Drop every entry and the private tempdir (TSDB.shutdown)."""
        with self._lock:
            self._closed = True
            keys = list(self._host) + list(self._files)
        self.release(keys)
        with self._lock:
            own_dir = self._own_dir and self._dir
            directory, self._dir = self._dir, None
            self._own_dir = False
        if own_dir:
            try:
                os.rmdir(directory)
            except OSError:
                pass
