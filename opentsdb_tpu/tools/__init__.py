"""CLI tools (the src/tools layer: TSDMain, importers, fsck, uid admin)."""
