"""The `tsdb` CLI: fsck, import, mkmetric, query, tsd, scan, search, uid,
version.

Reference behavior: /root/reference/tsdb.in (:63-101 command dispatch) and
the src/tools classes — Fsck.java (table scan + repair), TextImporter.java
(bulk import of `metric ts value tag=v...` lines, gzip-aware),
CliQuery.java, DumpSeries.java (scan/export), Search.java (lookup),
UidManager.java (:63-88 grep/assign/rename/delete/fsck/metasync/metapurge/
treesync), TSDMain.java.

All commands that touch data operate on a persistent store directory
(`--config` pointing tsd.storage.directory, the HBase-cluster analog).
"""

from __future__ import annotations

import argparse
import gzip
import json
import re
import sys
import time


def make_tsdb(args):
    from opentsdb_tpu.core import TSDB
    from opentsdb_tpu.utils.config import Config
    config = Config()
    if getattr(args, "config", None):
        config.load_file(args.config)
    if getattr(args, "auto_metric", False):
        config.override_config("tsd.core.auto_create_metrics", "true")
    return TSDB(config)


def _add_common(p: argparse.ArgumentParser) -> None:
    p.add_argument("--config", help="Path to a configuration file")
    p.add_argument("--auto-metric", action="store_true",
                   help="Automatically add metrics")


# ------------------------------------------------------------------ #
# import (TextImporter.java)                                         #
# ------------------------------------------------------------------ #

def cmd_import(args) -> int:
    """Bulk text import (TextImporter.java role).

    Lines parse into put dicts and flush through the vectorized
    add_points_bulk in batches — one columnar append per series per
    batch, one WAL record per batch — with per-line error reporting."""
    BATCH = 50_000
    tsdb = make_tsdb(args)
    points = 0
    errors = 0
    start = time.time()
    pending: list[dict] = []
    origins: list[tuple[str, int]] = []   # (path, lineno) per pending dp

    def flush() -> None:
        nonlocal points, errors
        if not pending:
            return
        success, errs = tsdb.add_points_bulk(pending)
        points += success
        errors += len(errs)
        for i, e in errs:
            path, lineno = origins[i]
            print("Error at %s:%d: %s" % (path, lineno, e),
                  file=sys.stderr)
        pending.clear()
        origins.clear()

    for path in args.files:
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "rt") as fh:
            for lineno, line in enumerate(fh, 1):
                line = line.strip()
                if not line:
                    continue
                words = line.split()
                if len(words) < 4:
                    print("Invalid line %d in %s: %s"
                          % (lineno, path, line), file=sys.stderr)
                    errors += 1
                    continue
                try:
                    tags = {}
                    for w in words[3:]:
                        k, _, v = w.partition("=")
                        if not k or not v:
                            raise ValueError("invalid tag: " + w)
                        tags[k] = v
                    ts = float(words[1]) if "." in words[1] \
                        else int(words[1])
                    pending.append({"metric": words[0], "timestamp": ts,
                                    "value": words[2], "tags": tags})
                    origins.append((path, lineno))
                except Exception as e:
                    errors += 1
                    print("Error at %s:%d: %s" % (path, lineno, e),
                          file=sys.stderr)
                if len(pending) >= BATCH:
                    flush()
    flush()
    tsdb.shutdown()
    elapsed = time.time() - start
    rate = points / elapsed if elapsed > 0 else 0
    print("Total: imported %d data points in %.3fs (%.1f points/s), "
          "%d errors" % (points, elapsed, rate, errors))
    return 0 if errors == 0 else 1


# ------------------------------------------------------------------ #
# query (CliQuery.java)                                              #
# ------------------------------------------------------------------ #

def cmd_query(args) -> int:
    from opentsdb_tpu.models import TSQuery, parse_m_subquery
    tsdb = make_tsdb(args)
    q = TSQuery(start=args.start, end=args.end,
                queries=[parse_m_subquery(m) for m in args.queries])
    q.validate()
    from opentsdb_tpu.utils import format_ascii_point
    # fans out when the CLI's config names cluster peers (same front
    # door as the daemon's /api/query)
    from opentsdb_tpu.tsd.cluster import serve_query
    for result in serve_query(tsdb, q):
        for ts, value in result.dps:
            print(format_ascii_point(result.metric, ts, value, result.tags))
    return 0


# ------------------------------------------------------------------ #
# scan / dump (DumpSeries.java)                                      #
# ------------------------------------------------------------------ #

def cmd_scan(args) -> int:
    tsdb = make_tsdb(args)
    metric_re = re.compile(args.pattern) if args.pattern else None
    for series in sorted(tsdb.store.all_series(),
                         key=lambda s: tsdb.tsuid(s.key)):
        metric = tsdb.metrics.get_name(series.key.metric)
        if metric_re is not None and not metric_re.search(metric):
            continue
        tags = tsdb.resolve_key_tags(series.key)
        tag_str = " ".join("%s=%s" % kv for kv in sorted(tags.items()))
        ts, fv, iv, isint = series.arrays()
        if args.delete:
            series.delete_range(int(ts[0]) if len(ts) else 0,
                                int(ts[-1]) if len(ts) else 0)
            tsdb.store.notify_mutation(series.key.metric, None, None)
        from opentsdb_tpu.utils import format_ascii_point
        for i in range(len(ts)):
            value = int(iv[i]) if isint[i] else float(fv[i])
            if args.importfmt:
                print(format_ascii_point(metric, int(ts[i]), value, tags))
            else:
                print("%s %d %s {%s}" % (tsdb.tsuid(series.key), ts[i],
                                         value, tag_str))
    if args.delete:
        tsdb.shutdown()
    return 0


# ------------------------------------------------------------------ #
# search (Search.java -> TimeSeriesLookup)                           #
# ------------------------------------------------------------------ #

def cmd_search(args) -> int:
    from opentsdb_tpu.search.lookup import LookupQuery, TimeSeriesLookup
    tsdb = make_tsdb(args)
    lq = LookupQuery.parse(args.query)
    lq.limit = 0    # CLI dumps everything
    result = TimeSeriesLookup(tsdb, lq).lookup()
    for hit in result["results"]:
        tags = " ".join("%s=%s" % kv for kv in sorted(hit["tags"].items()))
        print("%s %s %s" % (hit["tsuid"], hit["metric"], tags))
    print("%d results" % result["totalResults"])
    return 0


# ------------------------------------------------------------------ #
# uid (UidManager.java)                                              #
# ------------------------------------------------------------------ #

def cmd_uid(args) -> int:
    tsdb = make_tsdb(args)
    sub = args.subcommand
    rest = args.args
    kinds = ("metrics", "tagk", "tagv")

    def table_for(kind: str):
        return tsdb.uid_table("metric" if kind == "metrics" else kind)

    if sub == "grep":
        if rest and rest[0] in kinds:
            search_kinds, pattern = [rest[0]], rest[1] if len(rest) > 1 \
                else ""
        else:
            search_kinds, pattern = list(kinds), rest[0] if rest else ""
        regex = re.compile(pattern)
        found = 0
        for kind in search_kinds:
            table = table_for(kind)
            for name in sorted(table.names()):
                if regex.search(name):
                    print("%s %s: %s" % (
                        kind, name,
                        table.uid_to_hex(table.get_id(name))))
                    found += 1
        return 0 if found else 1
    if sub == "assign":
        if len(rest) < 2 or rest[0] not in kinds:
            print("usage: uid assign <metrics|tagk|tagv> <name> [names]",
                  file=sys.stderr)
            return 2
        table = table_for(rest[0])
        for name in rest[1:]:
            uid = table.get_or_create_id(name)
            print("%s %s: %s" % (rest[0], name, table.uid_to_hex(uid)))
        tsdb.shutdown()
        return 0
    if sub == "rename":
        if len(rest) != 3 or rest[0] not in kinds:
            print("usage: uid rename <metrics|tagk|tagv> <name> <newname>",
                  file=sys.stderr)
            return 2
        table_for(rest[0]).rename(rest[1], rest[2])
        tsdb.shutdown()
        return 0
    if sub == "delete":
        if len(rest) != 2 or rest[0] not in kinds:
            print("usage: uid delete <metrics|tagk|tagv> <name>",
                  file=sys.stderr)
            return 2
        table_for(rest[0]).delete(rest[1])
        tsdb.shutdown()
        return 0
    if sub == "fsck":
        return _uid_fsck(tsdb)
    if sub == "metasync":
        count = 0
        from opentsdb_tpu.meta.rpc import resolve_tsmeta
        for series in tsdb.store.all_series():
            tsuid = tsdb.tsuid(series.key)
            created = tsdb.meta_store.record_datapoint(tsuid, 0,
                                                       count=False)
            if tsdb.search_plugin is not None:
                tsdb.search_plugin.index_tsmeta(
                    resolve_tsmeta(tsdb, tsuid))
            count += 1
        print("Synced %d TSMeta entries" % count)
        tsdb.shutdown()
        return 0
    if sub == "metapurge":
        for meta in tsdb.meta_store.all_tsmeta():
            tsdb.meta_store.delete_tsmeta(meta.tsuid)
        for meta in tsdb.meta_store.all_uidmeta():
            tsdb.meta_store.delete_uidmeta(meta.type, meta.uid)
        print("Purged all meta entries")
        tsdb.shutdown()
        return 0
    if sub == "treesync":
        total = 0
        for tree in tsdb.tree_store.all_trees():
            if tree.enabled:
                total += tsdb.tree_store.rebuild(tsdb, tree)
        print("Synced %d tree leaves" % total)
        tsdb.shutdown()
        return 0
    print("Unknown uid subcommand: %s" % sub, file=sys.stderr)
    return 2


def _uid_fsck(tsdb) -> int:
    """UID dictionary consistency check (UidManager fsck)."""
    errors = 0
    for kind, table in (("metrics", tsdb.metrics), ("tagk", tsdb.tag_names),
                        ("tagv", tsdb.tag_values)):
        forward = table.snapshot()
        reverse: dict[int, str] = {}
        for name, uid in forward.items():
            if uid in reverse:
                print("%s: UID collision: %r and %r share %s"
                      % (kind, reverse[uid], name, table.uid_to_hex(uid)))
                errors += 1
            reverse[uid] = name
        for name, uid in forward.items():
            if table.get_name(uid) != name:
                print("%s: forward/reverse mismatch for %r" % (kind, name))
                errors += 1
    print("%d errors found" % errors)
    return 0 if errors == 0 else 1


# ------------------------------------------------------------------ #
# fsck (Fsck.java)                                                   #
# ------------------------------------------------------------------ #

def cmd_fsck(args) -> int:
    tsdb = make_tsdb(args)
    import numpy as np
    series_checked = 0
    points = 0
    dupes = 0
    ooo = 0
    unknown_uids = 0
    for series in tsdb.store.all_series():
        series_checked += 1
        try:
            tsdb.metrics.get_name(series.key.metric)
            for k, v in series.key.tags:
                tsdb.tag_names.get_name(k)
                tsdb.tag_values.get_name(v)
        except Exception:
            unknown_uids += 1
            print("Series %s references unknown UIDs"
                  % tsdb.tsuid(series.key))
        ts, _, _, _ = series.arrays()
        points += len(ts)
        if len(ts) > 1:
            diffs = np.diff(ts)
            ooo += int((diffs < 0).sum())
            dupes += int((diffs == 0).sum())
    if args.fix and (dupes or ooo):
        for series in tsdb.store.all_series():
            series.normalize(fix_duplicates=True)
        print("Resolved %d duplicates and %d out-of-order runs"
              % (dupes, ooo))
        tsdb.shutdown()
    print("Scanned %d series, %d datapoints: %d duplicates, %d "
          "out-of-order, %d unknown-UID series"
          % (series_checked, points, dupes, ooo, unknown_uids))
    # --fix repairs dupes/out-of-order but NOT dangling UIDs, which must
    # keep failing the health check.
    clean = (dupes == 0 and ooo == 0) or args.fix
    return 0 if clean and unknown_uids == 0 else 1


# ------------------------------------------------------------------ #
# version / mkmetric / tsd                                           #
# ------------------------------------------------------------------ #

def cmd_version(args) -> int:
    from opentsdb_tpu import build_data
    print(build_data.revision_string())
    print(build_data.build_string())
    return 0


def main(argv: list[str] | None = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    parser = argparse.ArgumentParser(
        prog="tsdb",
        description="Valid commands: fsck, import, mkmetric, query, tsd, "
                    "scan, search, uid, version")
    subs = parser.add_subparsers(dest="command", required=True)

    p = subs.add_parser("fsck", help="Check/repair the data store")
    _add_common(p)
    p.add_argument("--fix", action="store_true",
                   help="Repair errors (dedup + reorder)")
    p.set_defaults(fn=cmd_fsck)

    p = subs.add_parser("import", help="Bulk import datapoint files")
    _add_common(p)
    p.add_argument("files", nargs="+")
    p.set_defaults(fn=cmd_import)

    p = subs.add_parser("mkmetric", help="Create metric UIDs")
    _add_common(p)
    p.add_argument("names", nargs="+")
    p.set_defaults(fn=lambda a: cmd_uid(argparse.Namespace(
        config=a.config, auto_metric=a.auto_metric, subcommand="assign",
        args=["metrics"] + a.names)))

    p = subs.add_parser("query", help="Run a query")
    _add_common(p)
    p.add_argument("start")
    p.add_argument("--end", default=None)
    p.add_argument("queries", nargs="+",
                   help="m-subquery strings like sum:1h-avg:sys.cpu{...}")
    p.set_defaults(fn=cmd_query)

    p = subs.add_parser("tsd", help="Start the time series daemon")
    _add_common(p)
    p.add_argument("--port", type=int)
    p.add_argument("--bind")
    p.add_argument("--staticroot")
    p.add_argument("--cachedir")
    p.add_argument("--mode", choices=["rw", "ro", "wo"])
    p.add_argument("--worker-threads", type=int, default=8)
    p.add_argument("--verbose", action="store_true")
    def run_tsd(a):
        from opentsdb_tpu.tools import tsd_main
        flags = []
        for name in ("port", "bind", "config", "mode", "staticroot",
                     "cachedir"):
            value = getattr(a, name, None)
            if value is not None:
                flags += ["--" + name, str(value)]
        flags += ["--worker-threads", str(a.worker_threads)]
        if a.auto_metric:
            flags.append("--auto-metric")
        if a.verbose:
            flags.append("--verbose")
        return tsd_main.main(flags)
    p.set_defaults(fn=run_tsd)

    p = subs.add_parser("scan", help="Dump raw series data")
    _add_common(p)
    p.add_argument("--importfmt", action="store_true",
                   help="Output in import-compatible format")
    p.add_argument("--delete", action="store_true",
                   help="Delete the scanned rows")
    p.add_argument("pattern", nargs="?", default="",
                   help="Metric regex filter")
    p.set_defaults(fn=cmd_scan)

    p = subs.add_parser("search", help="Look up time series")
    _add_common(p)
    p.add_argument("query", help='lookup spec "metric{tagk=tagv}"')
    p.set_defaults(fn=cmd_search)

    p = subs.add_parser("uid", help="UID administration")
    _add_common(p)
    p.add_argument("subcommand",
                   choices=["grep", "assign", "rename", "delete", "fsck",
                            "metasync", "metapurge", "treesync"])
    p.add_argument("args", nargs="*")
    p.set_defaults(fn=cmd_uid)

    p = subs.add_parser("version", help="Print the version")
    _add_common(p)
    p.set_defaults(fn=cmd_version)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
