"""Daemon entry point: `python -m opentsdb_tpu.tools.tsd_main`.

Reference behavior: /root/reference/src/tools/TSDMain.java (:71) — parse
flags + config, build the TSDB, load plugins, bind the server, serve until
shutdown.
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import sys


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tsdb tsd", description="Start the TSD (time series daemon)")
    p.add_argument("--port", type=int, default=None,
                   help="TCP port to listen on (tsd.network.port)")
    p.add_argument("--bind", default=None,
                   help="Address to bind to (tsd.network.bind)")
    p.add_argument("--config", default=None,
                   help="Path to a configuration file")
    p.add_argument("--mode", default=None, choices=["rw", "ro", "wo"],
                   help="Operation mode (tsd.mode)")
    p.add_argument("--auto-metric", action="store_true", default=None,
                   help="Automatically add metrics (tsd.core.auto_create_metrics)")
    p.add_argument("--staticroot", default=None,
                   help="Web root for static files (tsd.http.staticroot)")
    p.add_argument("--cachedir", default=None,
                   help="Directory for temporary files (tsd.http.cachedir)")
    p.add_argument("--worker-threads", type=int, default=8,
                   help="Responder thread pool size")
    p.add_argument("--verbose", action="store_true",
                   help="Print more logging messages")
    return p


def make_config_from_args(args) -> "Config":
    from opentsdb_tpu.utils.config import Config
    config = Config()
    if args.config:
        config.load_file(args.config)
    if args.mode:
        config.override_config("tsd.mode", args.mode)
    if args.auto_metric:
        config.override_config("tsd.core.auto_create_metrics", "true")
    if args.staticroot:
        config.override_config("tsd.http.staticroot", args.staticroot)
    if args.cachedir:
        config.override_config("tsd.http.cachedir", args.cachedir)
    if args.port is not None:
        config.override_config("tsd.network.port", str(args.port))
    if args.bind:
        config.override_config("tsd.network.bind", args.bind)
    return config


def make_tsdb_from_args(args) -> "TSDB":
    from opentsdb_tpu.core import TSDB
    config = make_config_from_args(args)
    # the sanitizer must arm BEFORE the TSDB exists: locks and classes
    # constructed from here on get the instrumented wrappers
    maybe_arm_sanitizer(config)
    return TSDB(config)


def maybe_arm_sanitizer(config) -> bool:
    """tsd.sanitizer.enable=true arms tsdbsan (tools/sanitize) for this
    daemon: instrumented locks, write interception on lock-holding
    classes, and the deadlock watchdog.  A chaos/testing surface (the
    --san mode of tools/chaos_soak.py rides it); deployments without
    the tools/ tree degrade LOUDLY to disarmed."""
    if not config.get_bool("tsd.sanitizer.enable"):
        return False
    try:
        from tools import sanitize
    except ImportError:
        logging.getLogger("tsd.sanitizer").warning(
            "tsd.sanitizer.enable is set but tools.sanitize is not "
            "importable (repo root not on sys.path?) — sanitizer "
            "DISARMED")
        return False
    sanitize.install(
        lockset=config.get_bool("tsd.sanitizer.lockset.enable"),
        deadlock_watch=config.get_bool("tsd.sanitizer.deadlock.enable"),
        jax=config.get_bool("tsd.sanitizer.jax.enable"),
        watchdog_ms=config.get_int("tsd.sanitizer.deadlock.watchdog_ms"))
    logging.getLogger("tsd.sanitizer").info("tsdbsan armed")
    return True


def write_sanitizer_report(config) -> None:
    """At shutdown: finalize inversion detection and write the findings
    artifact when tsd.sanitizer.report.path is set."""
    path = config.get_string("tsd.sanitizer.report.path")
    if not path:
        return
    try:
        from tools import sanitize
        from tools.sanitize import deadlock
    except ImportError:
        return
    if not sanitize.installed():
        return
    deadlock.detect_inversions()
    try:
        sanitize.REPORTER.write_report(path)
    except OSError as e:
        logging.getLogger("tsd.sanitizer").warning(
            "could not write sanitizer report to %s: %s", path, e)


def main(argv: list[str] | None = None) -> int:
    args = build_arg_parser().parse_args(argv)
    logging.basicConfig(
        level=logging.DEBUG if args.verbose else logging.INFO,
        format="%(asctime)s %(levelname)s [%(threadName)s] "
               "%(name)s: %(message)s")
    tsdb = make_tsdb_from_args(args)
    if tsdb.config.enable_compactions:
        # The compaction-thread analog (CompactionQueue.java:95-107): dirty
        # series normalize off the read path, WAL fsync + snapshots follow
        # their configured cadences.
        tsdb.start_maintenance()
    port_cfg = tsdb.config.get_string("tsd.network.port")
    if not port_cfg:
        print("Missing network port (--port or tsd.network.port)",
              file=sys.stderr)
        return 1
    from opentsdb_tpu.tsd.server import TSDServer
    server = TSDServer(
        tsdb, port=int(port_cfg),
        bind=tsdb.config.get_string("tsd.network.bind") or "0.0.0.0",
        worker_threads=args.worker_threads)

    async def run():
        await server.start()
        # SIGTERM/SIGINT take the GRACEFUL path (drain in-flight
        # responder work, then tsdb.shutdown -> final snapshot) instead
        # of the default instant kill — a supervisor's stop must not be
        # a crash.  request_shutdown is idempotent and thread-safe.
        import signal
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                loop.add_signal_handler(sig, server.request_shutdown)
            except (NotImplementedError, RuntimeError):
                pass         # non-main thread / platform without support
        await server.serve_forever()

    try:
        asyncio.run(run())
    except KeyboardInterrupt:
        pass
    write_sanitizer_report(tsdb.config)
    return 0


if __name__ == "__main__":
    sys.exit(main())
