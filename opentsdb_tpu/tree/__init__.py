"""Tree subsystem: hierarchical namespace materialization.

Reference behavior: /root/reference/src/tree/ — Tree.java (definition + CAS
persistence, strict_match/enabled/store_failures flags), TreeRule.java
(:60-65 rule types METRIC/METRIC_CUSTOM/TAGK/TAGK_CUSTOM/TAGV_CUSTOM with
regex/separator/display_format), TreeBuilder.java (ordered rule levels
applied to a TSMeta producing Branch/Leaf rows), Branch.java/Leaf.java.
"""

from opentsdb_tpu.tree.objects import Tree, TreeRule, Branch, Leaf
from opentsdb_tpu.tree.builder import TreeBuilder
from opentsdb_tpu.tree.store import TreeStore

__all__ = ["Tree", "TreeRule", "Branch", "Leaf", "TreeBuilder", "TreeStore"]
