"""TreeBuilder: apply a tree's ordered rule levels to one TSMeta.

Reference behavior: /root/reference/src/tree/TreeBuilder.java —
processRuleset (:596: rules on a level are OR'd, first match wins; split
rules consume one depth level per split element before the rule index
advances), parseMetricRule/parseTagkRule/parse*CustomRule (:740-925),
processParsedValue/processSplit/processRegexRule (:926-1050), and
setCurrentName's display_format tokens {ovalue} {value} {tsuid} {tag_name}.

The recursion is flattened: the walk produces the branch path top-down; the
deepest element becomes the leaf under its parent branch
(processRuleset's roll-back-and-attach tail).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from opentsdb_tpu.tree.objects import Branch, Leaf, Tree, TreeRule


@dataclass
class BuildResult:
    path: list[str] = field(default_factory=list)   # branch path + leaf name
    not_matched: list[str] = field(default_factory=list)
    messages: list[str] = field(default_factory=list)


class TreeBuilder:
    def __init__(self, tree: Tree, test_mode: bool = False):
        self.tree = tree
        self.test_mode = test_mode

    def build_path(self, meta) -> BuildResult:
        """Walk the rule levels over a resolved TSMeta (meta.rpc
        .resolve_tsmeta shape: .tsuid, .metric UIDMeta, .tags [k,v,...])."""
        result = BuildResult()
        levels = self.tree.rule_levels()
        level_idx = 0
        splits: list[str] | None = None
        split_idx = 0
        split_rule: TreeRule | None = None
        split_original = ""
        while level_idx < len(levels):
            name = None
            if splits is not None:
                # still consuming split elements of the previous rule
                if split_idx < len(splits):
                    name = self._format(split_rule, split_original,
                                        splits[split_idx], meta)
                    split_idx += 1
                    if split_idx >= len(splits):
                        splits = None
                        level_idx += 1
                    if name:
                        result.path.append(name)
                    continue
                splits = None
            matched_rule = None
            for rule in levels[level_idx]:
                value = self._parse_source(rule, meta, result)
                if value is None:
                    continue
                if rule.compiled_regex() is not None:
                    name = self._apply_regex(rule, value, result)
                elif rule.separator:
                    # Java String.split takes a regex, so "\\." means a
                    # literal dot (processSplit :962).
                    import re as _re
                    splits = [s for s in _re.split(rule.separator, value)]
                    split_original = value
                    split_rule = rule
                    if not splits:
                        splits = None
                        continue
                    name = self._format(rule, value, splits[0], meta)
                    split_idx = 1
                    if split_idx >= len(splits):
                        splits = None
                else:
                    name = self._format(rule, value, value, meta)
                if name:
                    matched_rule = rule
                    break
                splits = None
            if name:
                result.path.append(name)
                result.messages.append(
                    "Depth [%d] matched rule %s" % (len(result.path),
                                                    _rid(matched_rule)))
            else:
                last = levels[level_idx][-1]
                result.not_matched.append(_rid(last))
                result.messages.append(
                    "No match on level %d (%s)" % (last.level, _rid(last)))
            if splits is None or split_idx >= len(splits):
                splits = None
                level_idx += 1
        return result

    # -- value sources per rule type (parse*Rule :740-925) --

    def _parse_source(self, rule: TreeRule, meta, result: BuildResult
                      ) -> str | None:
        t = rule.type.upper()
        if t == "METRIC":
            return meta.metric.name if meta.metric else None
        if t == "METRIC_CUSTOM":
            custom = (meta.metric.custom or {}) if meta.metric else {}
            return custom.get(rule.custom_field) or None
        if t == "TAGK":
            return self._tag_value(meta, rule.field)
        if t == "TAGK_CUSTOM":
            for uidmeta in meta.tags:
                if uidmeta.type.lower() == "tagk" \
                        and uidmeta.name == rule.field:
                    return (uidmeta.custom or {}).get(rule.custom_field) \
                        or None
            return None
        if t == "TAGV_CUSTOM":
            for uidmeta in meta.tags:
                if uidmeta.type.lower() == "tagv" \
                        and uidmeta.name == rule.field:
                    return (uidmeta.custom or {}).get(rule.custom_field) \
                        or None
            return None
        raise ValueError("Unknown rule type: " + rule.type)

    @staticmethod
    def _tag_value(meta, tagk: str) -> str | None:
        """The [tagk, tagv, ...] pair walk of parseTagkRule (:760)."""
        found = False
        for uidmeta in meta.tags:
            if uidmeta.type.lower() == "tagk" and uidmeta.name == tagk:
                found = True
            elif uidmeta.type.lower() == "tagv" and found:
                return uidmeta.name or None
        return None

    def _apply_regex(self, rule: TreeRule, value: str,
                     result: BuildResult) -> str | None:
        m = rule.compiled_regex().search(value)
        if not m:
            return None
        if m.lastindex is None or m.lastindex < rule.regex_group_idx + 1:
            result.messages.append(
                "Regex group index [%d] out of bounds for rule %s"
                % (rule.regex_group_idx, _rid(rule)))
            return None
        extracted = m.group(rule.regex_group_idx + 1)
        if not extracted:
            return None
        return self._format(rule, value, extracted, None) or None

    @staticmethod
    def _format(rule: TreeRule, original: str, extracted: str,
                meta) -> str:
        """setCurrentName display_format tokens (:1060-1090)."""
        fmt = rule.display_format
        if not fmt:
            return extracted
        fmt = fmt.replace("{ovalue}", original)
        fmt = fmt.replace("{value}", extracted)
        if meta is not None and "{tsuid}" in fmt:
            fmt = fmt.replace("{tsuid}", meta.tsuid)
        fmt = fmt.replace("{tag_name}", rule.field or "")
        return fmt


def _rid(rule: TreeRule | None) -> str:
    if rule is None:
        return "?"
    return "[%d:%d:%s]" % (rule.level, rule.order, rule.type)
