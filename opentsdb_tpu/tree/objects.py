"""Tree / TreeRule / Branch / Leaf objects.

Reference behavior: Tree.java (fields + flags), TreeRule.java (:76-115
fields, validateRule :542 — regex XOR-ish constraints, custom rules need
field+custom_field), Branch.java (display name + path + leaves + child
branches; branch ids are hex path hashes — here crc32-based, deterministic
but not byte-identical to the reference's hash).
"""

from __future__ import annotations

import re
import time
import zlib
from dataclasses import dataclass, field

RULE_TYPES = ("METRIC", "METRIC_CUSTOM", "TAGK", "TAGK_CUSTOM",
              "TAGV_CUSTOM")


@dataclass
class TreeRule:
    type: str = ""
    tree_id: int = 0
    level: int = 0
    order: int = 0
    field: str = ""
    custom_field: str = ""
    regex: str = ""
    separator: str = ""
    regex_group_idx: int = 0
    display_format: str = ""
    description: str = ""
    notes: str = ""

    def __post_init__(self):
        if self.regex:
            try:
                self._compiled = re.compile(self.regex)
            except re.error as e:
                raise ValueError("Invalid regex '%s': %s" % (self.regex, e))
        else:
            self._compiled = None

    def compiled_regex(self):
        return self._compiled

    def validate(self) -> None:
        """TreeRule.validateRule (:542)."""
        if self.type.upper() not in RULE_TYPES:
            raise ValueError("Invalid rule type: %s" % self.type)
        t = self.type.upper()
        if t in ("TAGK", "TAGK_CUSTOM", "TAGV_CUSTOM") and not self.field:
            raise ValueError(
                "Missing field name required for " + t + " rule")
        if t in ("METRIC_CUSTOM", "TAGK_CUSTOM", "TAGV_CUSTOM") \
                and not self.custom_field:
            raise ValueError(
                "Missing custom field name required for " + t + " rule")
        if self.regex and self.regex_group_idx < 0:
            raise ValueError(
                "Invalid regex group index. Cannot be less than 0")

    @staticmethod
    def from_json(body: dict) -> "TreeRule":
        rule = TreeRule(
            type=str(body.get("type", "")).upper(),
            tree_id=int(body.get("treeId", body.get("tree_id", 0))),
            level=int(body.get("level", 0)),
            order=int(body.get("order", 0)),
            field=body.get("field", "") or "",
            custom_field=body.get("customField",
                                  body.get("custom_field", "")) or "",
            regex=body.get("regex", "") or "",
            separator=body.get("separator", "") or "",
            regex_group_idx=int(body.get("regexGroupIdx",
                                         body.get("regex_group_idx", 0))),
            display_format=body.get("displayFormat",
                                    body.get("display_format", "")) or "",
            description=body.get("description", "") or "",
            notes=body.get("notes", "") or "")
        return rule

    def to_json(self) -> dict:
        return {
            "type": self.type.upper(),
            "treeId": self.tree_id,
            "level": self.level,
            "order": self.order,
            "field": self.field,
            "customField": self.custom_field,
            "regex": self.regex,
            "separator": self.separator,
            "regexGroupIdx": self.regex_group_idx,
            "displayFormat": self.display_format,
            "description": self.description,
            "notes": self.notes,
        }


@dataclass
class Tree:
    tree_id: int = 0
    name: str = ""
    description: str = ""
    notes: str = ""
    strict_match: bool = False
    enabled: bool = False
    store_failures: bool = False
    created: int = field(default_factory=lambda: int(time.time()))
    # level -> order -> rule
    rules: dict[int, dict[int, TreeRule]] = field(default_factory=dict)
    collisions: dict[str, str] = field(default_factory=dict)
    not_matched: dict[str, str] = field(default_factory=dict)

    def add_rule(self, rule: TreeRule) -> None:
        rule.validate()
        rule.tree_id = self.tree_id
        self.rules.setdefault(rule.level, {})[rule.order] = rule

    def delete_rule(self, level: int, order: int) -> bool:
        lvl = self.rules.get(level)
        if lvl is None or order not in lvl:
            return False
        del lvl[order]
        if not lvl:
            del self.rules[level]
        return True

    def rule_levels(self) -> list[list[TreeRule]]:
        return [[self.rules[lvl][o] for o in sorted(self.rules[lvl])]
                for lvl in sorted(self.rules)]

    def update_from(self, body: dict) -> None:
        for json_key, attr in (("name", "name"),
                               ("description", "description"),
                               ("notes", "notes")):
            if json_key in body:
                setattr(self, attr, body[json_key])
        for json_key, attr in (("strictMatch", "strict_match"),
                               ("enabled", "enabled"),
                               ("storeFailures", "store_failures")):
            if json_key in body:
                value = body[json_key]
                if isinstance(value, str):
                    # query-string form sends "true"/"false"
                    value = value.strip().lower() == "true"
                setattr(self, attr, bool(value))

    def to_json(self, include_rules: bool = True) -> dict:
        out = {
            "treeId": self.tree_id,
            "name": self.name,
            "description": self.description,
            "notes": self.notes,
            "strictMatch": self.strict_match,
            "enabled": self.enabled,
            "storeFailures": self.store_failures,
            "created": self.created,
        }
        if include_rules:
            out["rules"] = [r.to_json()
                            for level in self.rule_levels() for r in level]
        return out


def branch_id(tree_id: int, path: tuple[str, ...]) -> str:
    """Deterministic hex branch id: 4 hex digits of tree id + 8 per path
    element (Branch.compileBranchId analog; crc32, not the reference hash)."""
    out = ["%04x" % tree_id]
    for name in path:
        out.append("%08x" % zlib.crc32(name.encode()))
    return "".join(out)


@dataclass
class Leaf:
    display_name: str
    tsuid: str
    metric: str = ""
    tags: dict[str, str] = field(default_factory=dict)

    def to_json(self) -> dict:
        return {
            "displayName": self.display_name,
            "tsuid": self.tsuid,
            "metric": self.metric,
            "tags": self.tags,
        }


@dataclass
class Branch:
    tree_id: int
    path: tuple[str, ...] = ()          # path INCLUDING this branch's name
    leaves: dict[str, Leaf] = field(default_factory=dict)
    children: set[tuple[str, ...]] = field(default_factory=set)

    @property
    def display_name(self) -> str:
        return self.path[-1] if self.path else ""

    @property
    def depth(self) -> int:
        return len(self.path)

    @property
    def branch_id(self) -> str:
        return branch_id(self.tree_id, self.path)

    def to_json(self, child_branches: list["Branch"] | None = None) -> dict:
        out = {
            "treeId": self.tree_id,
            "branchId": self.branch_id,
            "displayName": self.display_name or "ROOT",
            "depth": self.depth,
            "path": {str(i + 1): name for i, name in enumerate(self.path)},
            "leaves": ([leaf.to_json()
                        for _, leaf in sorted(self.leaves.items())]
                       or None),
        }
        if child_branches is not None:
            out["branches"] = ([b.to_json() for b in child_branches]
                               or None)
        return out
