"""/api/tree endpoints (TreeRpc.java:~60-300).

Routes: /api/tree (CRUD + list), /api/tree/branch (?branch=<id> or
?treeid=<id> for the root), /api/tree/rule (single rule CRUD by
treeid/level/order), /api/tree/rules (bulk replace), /api/tree/test
(?treeid&tsuids= dry-run with messages), /api/tree/collisions,
/api/tree/not_matched.  A non-standard POST /api/tree/rebuild runs the
TreeSync pass inline (the reference does this via the `tsdb uid treesync`
CLI).
"""

from __future__ import annotations

from opentsdb_tpu.tree.builder import TreeBuilder
from opentsdb_tpu.tree.objects import Tree, TreeRule
from opentsdb_tpu.tsd.http import BadRequestError, HttpQuery
from opentsdb_tpu.uid import NoSuchUniqueId


def _require_tree(tsdb, tree_id) -> Tree:
    try:
        tree_id = int(tree_id)
    except (TypeError, ValueError):
        raise BadRequestError("Unable to parse the tree id")
    tree = tsdb.tree_store.get_tree(tree_id)
    if tree is None:
        raise BadRequestError("Unable to locate tree: %s" % tree_id,
                              status=404)
    return tree


def handle_tree(tsdb, query: HttpQuery) -> None:
    sub = query.api_subpath()
    endpoint = sub[0] if sub else ""
    if endpoint == "":
        return _tree_crud(tsdb, query)
    if endpoint == "branch":
        return _branch(tsdb, query)
    if endpoint == "rule":
        return _rule(tsdb, query)
    if endpoint == "rules":
        return _rules(tsdb, query)
    if endpoint == "test":
        return _test(tsdb, query)
    if endpoint == "collisions":
        return _collisions(tsdb, query, "collisions")
    if endpoint == "not_matched":
        return _collisions(tsdb, query, "not_matched")
    if endpoint == "rebuild":
        return _rebuild(tsdb, query)
    raise BadRequestError("Unknown tree endpoint: %s" % endpoint,
                          status=404)


def _body_or_params(query: HttpQuery, *names: str) -> dict:
    if query.request.body:
        return query.json_body()
    out = {}
    for name in names:
        v = query.get_query_string_param(name)
        if v is not None:
            out[name] = v
    return out


def _tree_crud(tsdb, query: HttpQuery) -> None:
    method = query.effective_method()
    if method == "GET":
        tree_id = query.get_query_string_param("treeid") or \
            query.get_query_string_param("treeId")
        if tree_id:
            query.send_reply(_require_tree(tsdb, tree_id).to_json())
        else:
            query.send_reply([t.to_json()
                              for t in tsdb.tree_store.all_trees()])
        return
    if method in ("POST", "PUT"):
        body = _body_or_params(query, "treeid", "name", "description",
                               "notes", "strictMatch", "enabled",
                               "storeFailures")
        tree_id = body.get("treeId", body.get("treeid"))
        if tree_id:   # edit
            tree = _require_tree(tsdb, tree_id)
            if method == "PUT":
                tree.name = tree.description = tree.notes = ""
                tree.strict_match = tree.enabled = False
                tree.store_failures = False
            tree.update_from(body)
            query.send_reply(tree.to_json())
            return
        if not body.get("name"):
            raise BadRequestError("Missing tree name")
        tree = Tree()
        tree.update_from(body)
        tsdb.tree_store.create_tree(tree)
        query.send_reply(tree.to_json())
        return
    if method == "DELETE":
        body = _body_or_params(query, "treeid", "definition")
        tree_id = body.get("treeId", body.get("treeid"))
        definition = str(body.get("definition", "false")).lower() == "true"
        tree = _require_tree(tsdb, tree_id)
        tsdb.tree_store.delete_tree(tree.tree_id, definition)
        query.send_status_only(204)
        return
    raise BadRequestError("Method not allowed", status=405)


def _branch(tsdb, query: HttpQuery) -> None:
    if query.method != "GET":
        raise BadRequestError("Method not allowed", status=405)
    branch_id = query.get_query_string_param("branch")
    if branch_id:
        branch = tsdb.tree_store.get_branch_by_id(branch_id)
    else:
        tree = _require_tree(
            tsdb, query.required_query_string_param("treeid"))
        branch = tsdb.tree_store.get_branch(tree.tree_id, ())
    if branch is None:
        raise BadRequestError("Unable to locate branch", status=404)
    children = tsdb.tree_store.children_of(branch)
    query.send_reply(branch.to_json(child_branches=children))


def _rule(tsdb, query: HttpQuery) -> None:
    method = query.effective_method()
    body = _body_or_params(query, "treeid", "level", "order", "type",
                           "field", "custom_field", "regex", "separator",
                           "regex_group_idx", "display_format",
                           "description", "notes")
    tree = _require_tree(tsdb, body.get("treeId", body.get("treeid")))
    level = int(body.get("level", 0))
    order = int(body.get("order", 0))
    if method == "GET":
        rule = tree.rules.get(level, {}).get(order)
        if rule is None:
            raise BadRequestError("Unable to locate rule", status=404)
        query.send_reply(rule.to_json())
        return
    if method in ("POST", "PUT"):
        rule = TreeRule.from_json(body)
        rule.level, rule.order = level, order
        tree.add_rule(rule)
        query.send_reply(rule.to_json())
        return
    if method == "DELETE":
        if not tree.delete_rule(level, order):
            raise BadRequestError("Unable to locate rule", status=404)
        query.send_status_only(204)
        return
    raise BadRequestError("Method not allowed", status=405)


def _rules(tsdb, query: HttpQuery) -> None:
    method = query.effective_method()
    if method not in ("POST", "PUT", "DELETE"):
        raise BadRequestError("Method not allowed", status=405)
    if method == "DELETE":
        tree = _require_tree(
            tsdb, query.required_query_string_param("treeid"))
        tree.rules.clear()
        query.send_status_only(204)
        return
    rules = query.json_body()
    if not isinstance(rules, list) or not rules:
        raise BadRequestError("Missing tree rules")
    tree_ids = {int(r.get("treeId", r.get("tree_id", 0))) for r in rules}
    if len(tree_ids) != 1:
        raise BadRequestError(
            "All rules must belong to the same tree")
    tree = _require_tree(tsdb, tree_ids.pop())
    # Validate the whole replacement set BEFORE mutating the tree, so a bad
    # rule cannot destroy a working ruleset mid-apply.
    parsed = [TreeRule.from_json(r) for r in rules]
    for rule in parsed:
        rule.validate()
    if method == "PUT":
        tree.rules.clear()
    for rule in parsed:
        tree.add_rule(rule)
    query.send_status_only(204)


def _test(tsdb, query: HttpQuery) -> None:
    from opentsdb_tpu.meta.rpc import resolve_tsmeta
    body = _body_or_params(query, "treeid", "tsuids")
    tree = _require_tree(tsdb, body.get("treeId", body.get("treeid")))
    tsuids = body.get("tsuids")
    if isinstance(tsuids, str):
        tsuids = tsuids.split(",")
    if not tsuids:
        raise BadRequestError.missing_parameter("tsuids")
    results = {}
    for tsuid in tsuids:
        entry: dict = {"tsuid": tsuid}
        try:
            meta = resolve_tsmeta(tsdb, tsuid)
        except (NoSuchUniqueId, ValueError) as e:
            entry["messages"] = ["Unable to locate TSUID meta data: %s" % e]
            entry["branch"] = None
            results[tsuid] = entry
            continue
        result = TreeBuilder(tree, test_mode=True).build_path(meta)
        entry["messages"] = result.messages
        entry["meta"] = meta.to_json()
        entry["branch"] = {
            "path": result.path,
            "notMatched": result.not_matched,
        }
        results[tsuid] = entry
    query.send_reply(results)


def _collisions(tsdb, query: HttpQuery, kind: str) -> None:
    if query.method != "GET":
        raise BadRequestError("Method not allowed", status=405)
    tree = _require_tree(
        tsdb, query.required_query_string_param("treeid"))
    data = tree.collisions if kind == "collisions" else tree.not_matched
    tsuids = query.get_query_string_param("tsuids")
    if tsuids:
        wanted = {t.strip().upper() for t in tsuids.split(",")}
        data = {k: v for k, v in data.items() if k.upper() in wanted}
    query.send_reply(data)


def _rebuild(tsdb, query: HttpQuery) -> None:
    if query.method != "POST":
        raise BadRequestError("Method not allowed", status=405)
    tree = _require_tree(
        tsdb, query.required_query_string_param("treeid"))
    count = tsdb.tree_store.rebuild(tsdb, tree)
    query.send_reply({"treeId": tree.tree_id, "leaves": count})
