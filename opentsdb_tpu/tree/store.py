"""TreeStore: trees, materialized branches, collision/not-matched records.

Reference behavior: Tree.java persistence into the tsdb-tree table (trees by
id with CAS, collision/not-matched rows under store_failures) and
TreeBuilder's branch/leaf writes; TreeSync (src/tools/TreeSync.java) rebuilds
a tree from every TSMeta.
"""

from __future__ import annotations

import threading

from opentsdb_tpu.tree.builder import TreeBuilder
from opentsdb_tpu.tree.objects import Branch, Leaf, Tree

MAX_TREES = 65535


class TreeStore:
    def __init__(self):
        # guarded-by: _lock
        self._trees: dict[int, Tree] = {}
        # (tree_id, path tuple) -> Branch  # guarded-by: _lock
        self._branches: dict[tuple[int, tuple[str, ...]], Branch] = {}
        self._lock = threading.Lock()

    # -- tree CRUD (Tree.createNewTree / storeTree / deleteTree) --

    def create_tree(self, tree: Tree) -> int:
        with self._lock:
            tree_id = max(self._trees, default=0) + 1
            if tree_id > MAX_TREES:
                raise ValueError("Exhausted all possible tree IDs")
            tree.tree_id = tree_id
            # construct the root branch BEFORE touching either map: a
            # raise between the two writes would register the tree with
            # no root, wedging every later branch walk for this id
            root = Branch(tree_id, ())
            self._trees[tree_id] = tree
            self._branches[(tree_id, ())] = root
            return tree_id

    def get_tree(self, tree_id: int) -> Tree | None:
        with self._lock:
            return self._trees.get(tree_id)

    def all_trees(self) -> list[Tree]:
        with self._lock:
            return [self._trees[i] for i in sorted(self._trees)]

    def delete_tree(self, tree_id: int, definition: bool = True) -> bool:
        """Drop branches (+ the definition unless definition=False, the
        ?definition=false 'data only' flavor of TreeRpc delete)."""
        with self._lock:
            if tree_id not in self._trees:
                return False
            for key in [k for k in self._branches if k[0] == tree_id]:
                del self._branches[key]
            tree = self._trees[tree_id]
            tree.collisions.clear()
            tree.not_matched.clear()
            if definition:
                del self._trees[tree_id]
            else:
                self._branches[(tree_id, ())] = Branch(tree_id, ())
            return True

    # -- branches --

    def get_branch(self, tree_id: int, path: tuple[str, ...]
                   ) -> Branch | None:
        with self._lock:
            return self._branches.get((tree_id, path))

    def get_branch_by_id(self, hex_id: str) -> Branch | None:
        with self._lock:
            for branch in self._branches.values():
                if branch.branch_id == hex_id.lower():
                    return branch
        return None

    def children_of(self, branch: Branch) -> list[Branch]:
        with self._lock:
            return [self._branches[(branch.tree_id, p)]
                    for p in sorted(branch.children)
                    if (branch.tree_id, p) in self._branches]

    # -- processing (TreeBuilder.processTimeseriesMeta) --

    def process_tsmeta(self, tree: Tree, meta,
                       metric: str = "", tags: dict | None = None) -> bool:
        """Apply the tree's rules to one resolved TSMeta; returns True when
        a leaf was stored."""
        result = TreeBuilder(tree).build_path(meta)
        if result.not_matched and tree.strict_match:
            if tree.store_failures:
                tree.not_matched[meta.tsuid] = "; ".join(result.not_matched)
            return False
        if not result.path:
            if tree.store_failures:
                tree.not_matched[meta.tsuid] = "no rules matched"
            return False
        leaf_name = result.path[-1]
        parent_path = tuple(result.path[:-1])
        with self._lock:
            # materialize the branch chain from the root down
            for depth in range(len(parent_path) + 1):
                path = tuple(parent_path[:depth])
                key = (tree.tree_id, path)
                if key not in self._branches:
                    self._branches[key] = Branch(tree.tree_id, path)
                if depth < len(parent_path):
                    self._branches[key].children.add(
                        tuple(parent_path[:depth + 1]))
            parent = self._branches[(tree.tree_id, parent_path)]
            existing = parent.leaves.get(leaf_name)
            if existing is not None and existing.tsuid != meta.tsuid:
                # Leaf collision (Branch.addLeaf + Tree.addCollision)
                if tree.store_failures:
                    tree.collisions[meta.tsuid] = existing.tsuid
                return False
            parent.leaves[leaf_name] = Leaf(leaf_name, meta.tsuid,
                                            metric=metric,
                                            tags=dict(tags or {}))
        return True

    def rebuild(self, tsdb, tree: Tree) -> int:
        """TreeSync: run every known series through the tree."""
        from opentsdb_tpu.meta.rpc import resolve_tsmeta
        self.delete_tree(tree.tree_id, definition=False)
        count = 0
        for series in tsdb.store.all_series():
            tsuid = tsdb.tsuid(series.key)
            meta = resolve_tsmeta(tsdb, tsuid)
            if self.process_tsmeta(
                    tree, meta,
                    metric=tsdb.metrics.get_name(series.key.metric),
                    tags=tsdb.resolve_key_tags(series.key)):
                count += 1
        return count
