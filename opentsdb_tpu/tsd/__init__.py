"""Network/API server layer.

Reference behavior: /root/reference/src/tsd/ — the Netty 3 pipeline
(PipelineFactory.java:44 first-byte HTTP/telnet sniff), RpcManager route
table (RpcManager.java:251-364) and per-endpoint Rpc handlers.  Rebuilt on
asyncio: one port serves both the line-oriented telnet protocol and
HTTP/1.1, handlers run on a worker thread pool so device compute never
blocks the event loop.
"""

from opentsdb_tpu.tsd.http import (
    HttpRequest, HttpResponse, HttpQuery, BadRequestError)
from opentsdb_tpu.tsd.rpc_manager import RpcManager
from opentsdb_tpu.tsd.server import TSDServer

__all__ = ["HttpRequest", "HttpResponse", "HttpQuery", "BadRequestError",
           "RpcManager", "TSDServer"]
