"""Admin/observability RPC handlers: stats, version, config, aggregators,
serializers, dropcaches, logs, home page, static files.

Reference behavior: /root/reference/src/tsd/RpcManager.java (:585-740
builtin handlers: Version, ListAggregators, HomePage, Serializers, Help,
Exit, DieDieDie), StatsRpc.java (:86-97 threads/jvm/query/region_clients
sub-endpoints), DropCachesRpc.java, LogsRpc.java (:85 in-memory ring
buffer), StaticFileRpc.java.
"""

from __future__ import annotations

import collections
import logging
import os
import sys
import threading
import time

from opentsdb_tpu import build_data
from opentsdb_tpu.ops.aggregators import agg_names
from opentsdb_tpu.stats import StatsCollector
from opentsdb_tpu.tsd.http import BadRequestError, HttpQuery
from opentsdb_tpu.tsd.rpcs import HttpRpc, TelnetRpc, allowed_methods
from opentsdb_tpu.tsd.serializers import SERIALIZERS
from opentsdb_tpu.tsd.ui import UI_PAGE as _HOME_PAGE


class VersionRpc(TelnetRpc, HttpRpc):
    def execute_telnet(self, tsdb, conn, words) -> str:
        return build_data.revision_string() + "\n" + \
            build_data.build_string() + "\n"

    def execute_http(self, tsdb, query: HttpQuery) -> None:
        allowed_methods(query, "GET", "POST")
        version = build_data.version_map()
        if query.api_version > 0:
            query.send_reply(query.serializer.format_version_v1(version))
        elif query.request.uri.endswith("json"):
            query.send_reply(version)
        else:
            query.send_reply(build_data.revision_string() + "\n"
                             + build_data.build_string() + "\n",
                             content_type="text/plain")


class ListAggregators(HttpRpc):
    def execute_http(self, tsdb, query: HttpQuery) -> None:
        allowed_methods(query, "GET", "POST")
        names = agg_names()
        if query.api_version > 0:
            query.send_reply(query.serializer.format_aggregators_v1(names))
        else:
            query.send_reply(names)


class SerializersRpc(HttpRpc):
    def execute_http(self, tsdb, query: HttpQuery) -> None:
        allowed_methods(query, "GET", "POST")
        descriptors = [cls.descriptor() for cls in SERIALIZERS.values()]
        query.send_reply(
            query.serializer.format_serializers_v1(descriptors))


class ShowConfig(HttpRpc):
    """/api/config + /api/config/filters."""

    def execute_http(self, tsdb, query: HttpQuery) -> None:
        allowed_methods(query, "GET", "POST")
        sub = query.api_subpath()
        if sub and sub[0] == "filters":
            from opentsdb_tpu.query.filters import FILTER_TYPES
            out = {}
            for name, cls in sorted(FILTER_TYPES.items()):
                out[name] = {
                    "examples": getattr(cls, "examples", ""),
                    "description": (cls.__doc__ or "").strip(),
                }
            query.send_reply(out)
            return
        query.send_reply(query.serializer.format_config_v1(
            tsdb.config.as_map(obfuscate=True)))


class DropCachesRpc(TelnetRpc, HttpRpc):
    def _drop(self, tsdb) -> None:
        tsdb.store.drop_caches()
        if tsdb.device_cache is not None:
            tsdb.device_cache.invalidate()
        if tsdb.agg_cache is not None:
            tsdb.agg_cache.invalidate()
        if tsdb.rollup_lanes is not None:
            tsdb.rollup_lanes.invalidate()
        # UID cachs are authoritative dictionaries here (no backing store),
        # so unlike UniqueId.dropCaches they must NOT be emptied.

    def execute_telnet(self, tsdb, conn, words) -> str:
        self._drop(tsdb)
        return "Caches dropped.\n"

    def execute_http(self, tsdb, query: HttpQuery) -> None:
        allowed_methods(query, "GET", "POST")
        self._drop(tsdb)
        if query.api_version > 0:
            query.send_reply(query.serializer.format_dropcaches_v1(
                {"status": "200", "message": "Caches dropped"}))
        else:
            query.send_reply("Caches dropped.\n", content_type="text/plain")


class StatsRpc(TelnetRpc, HttpRpc):
    """/api/stats (+/query, /jvm, /threads, /region_clients) + telnet stats."""

    def __init__(self, stats_registry=None):
        self.stats_registry = stats_registry

    def _collect(self, tsdb) -> StatsCollector:
        """One stats walk: TSDB counters, cluster breakers, rollup
        lanes, plus every registered stats hook (the RpcManager's hook
        covers ingest RPCs, error envelopes, and the server).  Shared
        with the self-report loop — obs/selfreport.py — so /api/stats
        and the dogfooded tsd.* series can never diverge."""
        from opentsdb_tpu.obs.selfreport import collect_all
        return collect_all(tsdb)

    def execute_telnet(self, tsdb, conn, words) -> str:
        return self._collect(tsdb).emit_ascii()

    def execute_http(self, tsdb, query: HttpQuery) -> None:
        sub = query.api_subpath()
        endpoint = sub[0] if sub else ""
        if endpoint == "prometheus":
            # text exposition (version 0.0.4) beside the JSON surface:
            # registry counters/gauges/latency histograms first, then
            # every StatsCollector record (device cache, breakers,
            # compaction, ingest counters) as gauges — the records
            # already carry the host tag, so nothing re-registers them.
            # tsd.diag.exemplars additionally links histogram tail
            # buckets to flight-recorder trace ids via comment lines
            # (the format stays 0.0.4-parseable).
            from opentsdb_tpu.obs.registry import REGISTRY
            text = REGISTRY.prometheus_text(
                extra_records=self._collect(tsdb).records,
                exemplars=tsdb.config.get_bool("tsd.diag.exemplars"))
            query.send_reply(
                text,
                content_type="text/plain; version=0.0.4; charset=utf-8")
            return
        if endpoint == "query":
            if self.stats_registry is None:
                raise BadRequestError("Query stats are not enabled",
                                      status=404)
            payload = self.stats_registry.snapshot()
            # the costmodel predicted-vs-actual segment ring rides the
            # query-stats payload: a saved /api/stats/query response is
            # a fittable calibration corpus (tools/fit_costmodel.py)
            from opentsdb_tpu.obs import jaxprof
            payload["costmodelSegments"] = jaxprof.segments()
            query.send_reply(query.serializer.format_query_stats_v1(
                payload))
            return
        if endpoint == "threads":
            query.send_reply(self._threads())
            return
        if endpoint == "jvm":
            query.send_reply(self._runtime())
            return
        if endpoint == "region_clients":
            # No region servers: the storage engine is in-process.
            query.send_reply([])
            return
        collector = self._collect(tsdb)
        if query.api_version > 0:
            query.send_reply(
                query.serializer.format_stats_v1(collector.records))
        else:
            query.send_reply(collector.emit_ascii(),
                             content_type="text/plain")

    @staticmethod
    def _threads() -> list[dict]:
        out = []
        frames = sys._current_frames()
        for t in threading.enumerate():
            frame = frames.get(t.ident)
            out.append({
                "threadID": t.ident,
                "name": t.name,
                "state": "RUNNABLE" if t.is_alive() else "TERMINATED",
                "daemon": t.daemon,
                "stack": ([ "%s:%d" % (frame.f_code.co_filename,
                                       frame.f_lineno)] if frame else []),
            })
        return out

    @staticmethod
    def _runtime() -> dict:
        """Process runtime stats (the JVM-stats analog for CPython)."""
        import resource
        usage = resource.getrusage(resource.RUSAGE_SELF)
        return {
            "runtime": {
                "implementation": sys.implementation.name,
                "version": sys.version,
                "pid": os.getpid(),
            },
            "memory": {
                "maxRSSKb": usage.ru_maxrss,
            },
            "os": {
                "systemLoadAverage": os.getloadavg()[0],
            },
            "gc": {
                "collections": sum(
                    g["collections"]
                    for g in __import__("gc").get_stats()),
            },
        }


class DiagRpc(HttpRpc):
    """/api/diag (+ /slow, /health): the flight-recorder ring, the
    slow-query store, and the health-engine verdicts
    (obs/flightrec.py, obs/health.py; docs/observability.md).

      * ``/api/diag``              the event ring, oldest first.
        ``?since=<seq>`` returns only events newer than that sequence
        number — poll with the last ``seq`` you saw for an incremental
        feed.  ``?trace_id=<id>`` narrows to one request's ring slice
        (an explain fingerprint's plan event, a latency exemplar, or
        an X-TSDB-Trace-Id resolve in ONE request instead of paging
        the whole ring client-side); combinable with ``since``.
      * ``/api/diag/slow``         retained slow/anomalous queries
        (span tree + costmodel decisions + ring slice), newest first.
        ``?trace_id=<id>`` looks one capture up by its trace id.
      * ``/api/diag/health``       per-subsystem ok/degraded/failing
        verdicts (the chaos_soak post-heal gate).
      * ``/api/diag/latency``      always-on per-phase latency
        attribution (obs/latattr.py): streaming histograms keyed by
        (route, plan fingerprint, tenant) — populated with tracing
        OFF.  ``?since=<seq>`` returns only profiles touched after
        that sequence number; ``?fingerprint=`` / ``?tenant=`` narrow
        to one key.
    """

    def execute_http(self, tsdb, query: HttpQuery) -> None:
        allowed_methods(query, "GET")
        sub = query.api_subpath()
        endpoint = sub[0] if sub else ""
        if endpoint == "latency":
            engine = getattr(tsdb, "latattr", None)
            if engine is None:
                raise BadRequestError(
                    "Latency attribution is disabled", status=404,
                    details="Set tsd.latattr.enable=true")
            raw = query.get_query_string_param("since")
            try:
                since = int(raw) if raw else 0
            except ValueError:
                raise BadRequestError("'since' must be an integer "
                                      "sequence number")
            query.send_reply(engine.report(
                since=since,
                fingerprint=query.get_query_string_param("fingerprint"),
                tenant=query.get_query_string_param("tenant")))
            return
        if endpoint == "health":
            engine = getattr(tsdb, "health", None)
            if engine is None:
                raise BadRequestError(
                    "The health engine is disabled", status=404,
                    details="Set tsd.health.enable=true")
            query.send_reply(engine.report())
            return
        recorder = getattr(tsdb, "flightrec", None)
        if recorder is None:
            raise BadRequestError(
                "The flight recorder is disabled", status=404,
                details="Set tsd.diag.enable=true")
        trace_id = query.get_query_string_param("trace_id")
        if endpoint == "slow":
            query.send_reply(
                {"queries": recorder.slow_queries(trace_id=trace_id)})
            return
        if endpoint:
            raise BadRequestError(
                "No such diag endpoint: %s" % endpoint, status=404)
        raw = query.get_query_string_param("since")
        try:
            since = int(raw) if raw else 0
        except ValueError:
            raise BadRequestError("'since' must be an integer sequence "
                                  "number")
        if trace_id:
            events = [e for e in recorder.events_for_trace(trace_id)
                      if e["seq"] > since]
        else:
            events = recorder.events(since=since)
        dropped, dropped_total = recorder.dropped()
        reply = {
            "seq": recorder.latest_seq(),
            "ringSize": recorder.ring_size,
            "events": events,
            # overflow accounting: events evicted from the ring before
            # anyone read them, tallied by the evicted event's kind —
            # a sustained climb means the ring is too small for the
            # event rate and diagnoses are losing history
            "dropped": dropped,
            "droppedTotal": dropped_total,
        }
        if trace_id:
            reply["traceId"] = trace_id
        else:
            # the fair-share audit view: per-tenant inflight/queued/
            # deficit plus the drained/refused split of the demand
            # counter (tsd/admission.py weighted DRR).  Only on the
            # full-ring view — a trace-scoped fetch is one request's
            # evidence, not the gate's
            gate = getattr(tsdb, "_admission_gate", None)
            if gate is not None:
                reply["tenants"] = gate.tenant_snapshot()
        query.send_reply(reply)


class LogBuffer(logging.Handler):
    """In-memory ring of recent log lines (LogsRpc.LogIterator :85)."""

    def __init__(self, capacity: int = 1024):
        super().__init__()
        self.ring = collections.deque(maxlen=capacity)
        self.setFormatter(logging.Formatter(
            "%(asctime)s %(levelname)s [%(threadName)s] "
            "%(name)s: %(message)s"))

    def emit(self, record: logging.LogRecord) -> None:
        try:
            self.ring.append(self.format(record))
        except Exception:
            # logging from inside the log handler would recurse; a
            # record the ring can't format is dropped by design
            pass  # tsdblint: disable=except-swallow


_LOG_BUFFER = LogBuffer()
# refcount, not a boolean: several servers in one process (tests, the
# chaos harness) each install on start and uninstall on stop — the
# handler leaves the root logger only when the LAST one stops, and a
# stopped server no longer pins log capture for the host program.
# Servers run on their own threads, so the count is lock-protected.
_LOG_BUFFER_LOCK = threading.Lock()
_LOG_BUFFER_INSTALLS = 0  # guarded-by: _LOG_BUFFER_LOCK


def install_log_buffer() -> None:
    """Attach the /logs ring buffer to the root logger (refcounted).

    Called by server startup, NOT at import time — importing the package
    must not mutate the host program's logging configuration.  Pair with
    `uninstall_log_buffer()` on shutdown.
    """
    global _LOG_BUFFER_INSTALLS
    with _LOG_BUFFER_LOCK:
        if _LOG_BUFFER_INSTALLS == 0:
            # global-install: removeHandler paired-with: uninstall_log_buffer
            logging.getLogger().addHandler(_LOG_BUFFER)
        _LOG_BUFFER_INSTALLS += 1


def uninstall_log_buffer() -> None:
    """Detach the /logs handler once the last installer stops."""
    global _LOG_BUFFER_INSTALLS
    with _LOG_BUFFER_LOCK:
        if _LOG_BUFFER_INSTALLS == 0:
            return
        _LOG_BUFFER_INSTALLS -= 1
        if _LOG_BUFFER_INSTALLS == 0:
            logging.getLogger().removeHandler(_LOG_BUFFER)


class LogsRpc(HttpRpc):
    def execute_http(self, tsdb, query: HttpQuery) -> None:
        lines = list(_LOG_BUFFER.ring)[::-1]  # newest first, like LogsRpc
        if query.has_query_string_param("json"):
            query.send_reply(lines)
        else:
            query.send_reply("\n".join(lines) + "\n",
                             content_type="text/plain")




class HomePage(HttpRpc):
    """The query UI (the GWT QueryUi.java replacement: a self-contained
    page driving /api/suggest and the /q SVG endpoint)."""

    def execute_http(self, tsdb, query: HttpQuery) -> None:
        query.send_reply(_HOME_PAGE,
                         content_type="text/html; charset=UTF-8")


class StaticFileRpc(HttpRpc):
    """/s/<file> from tsd.http.staticroot (StaticFileRpc.java)."""

    CONTENT_TYPES = {
        ".html": "text/html; charset=UTF-8",
        ".js": "text/javascript",
        ".css": "text/css",
        ".png": "image/png",
        ".gif": "image/gif",
        ".ico": "image/x-icon",
        ".svg": "image/svg+xml",
        ".json": "application/json",
    }

    def execute_http(self, tsdb, query: HttpQuery) -> None:
        root = tsdb.config.get_string("tsd.http.staticroot")
        if not root:
            raise BadRequestError("tsd.http.staticroot is not configured",
                                  status=404)
        parts = query.path.split("/")
        rel = "/".join(parts[1:]) if parts[0] == "s" else query.path
        path = os.path.realpath(os.path.join(root, rel))
        if not path.startswith(os.path.realpath(root) + os.sep):
            raise BadRequestError("Malformed path", status=403)
        if not os.path.isfile(path):
            raise BadRequestError("File not found", status=404)
        with open(path, "rb") as fh:
            body = fh.read()
        ext = os.path.splitext(path)[1].lower()
        ctype = self.CONTENT_TYPES.get(ext, "application/octet-stream")
        query.send_reply(body, content_type=ctype)


class SearchRpc(HttpRpc):
    def execute_http(self, tsdb, query: HttpQuery) -> None:
        try:
            from opentsdb_tpu.search.rpc import handle_search
        except ImportError:
            raise BadRequestError("Search is not available", status=501)
        handle_search(tsdb, query)


class TreeRpc(HttpRpc):
    def execute_http(self, tsdb, query: HttpQuery) -> None:
        try:
            from opentsdb_tpu.tree.rpc import handle_tree
        except ImportError:
            raise BadRequestError("Tree support is not available",
                                  status=501)
        handle_tree(tsdb, query)


class HelpRpc(TelnetRpc):
    def __init__(self, commands):
        self.commands = commands

    def execute_telnet(self, tsdb, conn, words) -> str:
        return ("available commands: "
                + " ".join(sorted(self.commands())) + "\n")


class ExitRpc(TelnetRpc):
    def execute_telnet(self, tsdb, conn, words) -> str | None:
        conn.close_after_write = True
        return "exiting\n"


class DieDieDie(TelnetRpc, HttpRpc):
    """Graceful shutdown trigger."""

    def __init__(self, shutdown_cb):
        self.shutdown_cb = shutdown_cb

    def execute_telnet(self, tsdb, conn, words) -> str:
        conn.close_after_write = True
        self.shutdown_cb()
        return "Cleaning up and exiting now.\n"

    def execute_http(self, tsdb, query: HttpQuery) -> None:
        query.send_reply("Cleaning up and exiting now.\n",
                         content_type="text/plain")
        self.shutdown_cb()
