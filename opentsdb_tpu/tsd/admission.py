"""Admission control for the query path: bounded concurrency, priority
queues, costmodel-informed shedding, and a degradation ladder.

The 8-thread responder pool (tsd/server.py) queues work unboundedly;
under saturation the daemon doesn't degrade — it stalls.  This gate
sits in front of every device-dispatching query (QueryRpc /
GraphHandler execution) and bounds what the daemon ADMITS, in the
Enthuse shared-aggregation stance (arXiv:2405.18168): bound what you
admit, shed what you can't, and make every admitted query finish
inside its deadline.

Three mechanisms, one `admit()` front door:

  * **Permits** — at most ``tsd.query.admission.permits`` queries
    dispatch device work concurrently; excess requests wait in a
    bounded FIFO queue per priority class (``X-TSDB-Priority:
    interactive|batch``, interactive drains first).  A full queue
    sheds with 503 + ``Retry-After``.
  * **Costmodel shedding** — with a bounded request deadline
    (tsd.query.timeout or the client's ``X-TSDB-Deadline-Ms``), the
    parsed plan's predicted device cost (PR 6's fitted ``predict_*``
    via obs.jaxprof.stage_breakdown) plus the expected queue wait is
    compared against the remaining deadline; a query that cannot
    finish in time is refused NOW (503 + Retry-After) instead of
    burning device time and timing out anyway.  When
    ``tsd.query.degrade=allow``, a degradation ladder runs first:
    coarsen the downsample interval (x2..x16), then truncate the range
    toward the present — a degraded 200 carries the ``partialResults``
    annotation (tsd/cluster.py partial_annotation).
  * **Cooperative cancellation** — the queue wait observes the request
    deadline's cancellation token (query/limits.py Deadline): a
    cancelled or expired query leaves the queue WITHOUT taking a
    permit; the server responder loop flips the token on client
    disconnect, and TSDServer.stop flips every in-flight one at drain
    timeout.

Every decision is traced (an ``admission`` child span with wait ms +
decision) and counted (queue depth gauge, wait histogram,
shed/degrade/cancel counters by reason — see METRICS_SCHEMA).
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque

from opentsdb_tpu.obs import latattr
from opentsdb_tpu.obs import trace as obs_trace
from opentsdb_tpu.obs.registry import REGISTRY
from opentsdb_tpu.query.limits import (
    Deadline, QueryException, active_deadline)
from opentsdb_tpu.uid import NoSuchUniqueName
from opentsdb_tpu.utils import faults

# Remaining request budget, in integer milliseconds, forwarded to
# fan-out peers (tsd/cluster.py) and accepted from clients
# (rpc_manager.handle_http mints the request Deadline from
# min(tsd.query.timeout, this header)).
DEADLINE_HEADER = "x-tsdb-deadline-ms"
PRIORITY_HEADER = "x-tsdb-priority"
# Clamped to the registered/hashed tenant table (obs/flightrec.py
# clamp_tenant) before it mints any metric label.
TENANT_HEADER = "x-tsdb-tenant"

# Priority classes, drain order first to last.  An unknown/absent
# header value lands in the first class.
CLASSES = ("interactive", "batch")

# Queue-wait poll granularity: cancellation (client disconnect, drain)
# flips a token without notifying the gate's condition, so waiters
# re-check on this cadence even without a release.
_WAIT_TICK_S = 0.05


class ShedError(QueryException):
    """Admission refused the query: 503 + Retry-After.  The server is
    overloaded (or the query cannot meet its deadline) — the client
    should back off and retry, unchanged requests may succeed later."""

    def __init__(self, message: str, retry_after_s: int = 1):
        super().__init__(message, status=503)
        self.retry_after_s = max(int(retry_after_s), 1)


def count_cancelled(reason: str) -> None:
    """The cancel counter, one emission site for every flipper (gate
    queue wait, server disconnect watcher, drain force-cancel)."""
    REGISTRY.counter(
        "tsd.query.admission.cancelled",
        "Queries cancelled cooperatively, by reason").labels(
            reason=reason).inc()


class CancellationHandle:
    """Server-side cancellation lever for one in-flight request.

    The responder loop creates it BEFORE dispatching (it owns
    disconnect detection), attaches it to the request, and
    rpc_manager.handle_http binds the freshly minted Deadline to it —
    ``cancel()`` works in either order: a flip that lands before the
    bind is replayed onto the deadline when it arrives.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._deadline: Deadline | None = None  # guarded-by: _lock
        self._pending_reason: str | None = None  # guarded-by: _lock

    def bind(self, deadline: Deadline) -> None:
        with self._lock:
            self._deadline = deadline
            reason = self._pending_reason
        if reason is not None:
            deadline.cancel(reason)

    def cancel(self, reason: str) -> bool:
        """Flip the bound deadline's token (or stash the reason for the
        bind).  Returns True when this call did the flip."""
        with self._lock:
            deadline = self._deadline
            if deadline is None:
                if self._pending_reason is not None:
                    return False
                self._pending_reason = reason
                return True
        return deadline.cancel(reason)

    def is_cancelled(self) -> bool:
        with self._lock:
            if self._deadline is None:
                return self._pending_reason is not None
            deadline = self._deadline
        return deadline.is_cancelled()


# --------------------------------------------------------------------- #
# Plan-shape cost estimation                                            #
# --------------------------------------------------------------------- #

# Series sampled per sub query when estimating point counts: the
# estimate must stay O(sample * log points), never O(all series), on
# the pre-admission path.
_COST_SAMPLE_SERIES = 64


def estimate_plan_cost_ms(tsdb, ts_query) -> float:
    """Predicted device milliseconds for the parsed plan, from the
    fitted costmodel (obs.jaxprof.stage_breakdown over the per-axis
    ``predict_*``).  An ESTIMATE by design: series counts are
    un-filtered (upper bound), point counts extrapolate from a bounded
    sample (the per-series window_count is the log-points part; the
    store hands back a count + bounded sample, never the full
    per-metric series list), and the group count is approximated —
    good enough to refuse a query that is orders off its deadline,
    never a timer.  Returns 0.0 when nothing is predictable (unknown
    metrics, tsuid subqueries, empty stores)."""
    from opentsdb_tpu.obs import jaxprof
    from opentsdb_tpu.ops.downsample import pad_pow2
    from opentsdb_tpu.ops.hostlane import execution_platform

    platform = execution_platform()
    fix = tsdb.config.fix_duplicates
    total_s = 0.0
    for sub in ts_query.queries:
        if not sub.metric:
            continue                    # tsuids: host-local, unpredicted
        try:
            metric_uid = tsdb.metrics.get_id(sub.metric)
        except NoSuchUniqueName:
            continue
        s, sample = tsdb.store.series_count_and_sample(
            metric_uid, _COST_SAMPLE_SERIES)
        if not s:
            continue
        pts = sum(sr.window_count(ts_query.start_time, ts_query.end_time,
                                  fix) for sr in sample)
        points = pts * s / len(sample)
        if points <= 0:
            continue
        ds = sub.downsample_spec
        ds_fn = None
        w = 1
        if ds is not None and ds.interval_ms > 0 and not ds.run_all:
            ds_fn = ds.function
            w = max(int((ts_query.end_time - ts_query.start_time)
                        // ds.interval_ms) + 1, 1)
            # Rollup lanes first (storage/rollup.py): a fully
            # lane-covered plan never fetches, streams, or tiles the
            # raw points — price the lane assembly + the tail stages
            # instead, so warm long-range dashboards ADMIT where a
            # cold raw-priced estimate would shed them.
            lanes = getattr(tsdb, "rollup_lanes", None)
            if lanes is not None and not ds.use_calendar:
                cov = lanes.coverage(metric_uid, ds.interval_ms, ds_fn,
                                     ts_query.start_time,
                                     ts_query.end_time)
                if cov >= 1.0:
                    from opentsdb_tpu.ops import costmodel as cm
                    first = ts_query.start_time \
                        - ts_query.start_time % ds.interval_ms
                    picked = lanes.lane_for(ds.interval_ms, first)
                    k = (ds.interval_ms // picked[1]) if picked else 1
                    g = pad_pow2(s if sub.aggregator == "none" else 1)
                    total_s += cm.predict_lane(s, w, k, platform)
                    total_s += sum(jaxprof.stage_breakdown(
                        platform, s, 8, w, g, ds_fn,
                        bool(sub.rate)).values())
                    continue
            # Price the REWRITTEN plan, not the original: windows
            # covered by valid partial-aggregate blocks never
            # dispatch, so only the uncovered fraction of the scan
            # costs anything.  The discount mirrors the planner's
            # rewrite eligibility — a plan the planner can never
            # rewrite (streaming-sized, mesh-sharded) must keep its
            # FULL predicted cost, or the shed gate under-prices
            # exactly the heaviest queries it exists to refuse.
            rewritable = (
                getattr(tsdb, "agg_cache", None) is not None
                and not ds.use_calendar
                and points <= tsdb.config.get_int(
                    "tsd.query.streaming.point_threshold")
                and not (tsdb.query_mesh() is not None
                         and s >= tsdb.config.get_int(
                             "tsd.query.mesh.min_series")))
            if rewritable:
                coverage = tsdb.agg_cache.coverage(
                    tsdb.store, metric_uid, ds.interval_ms,
                    ds.function, ts_query.start_time,
                    ts_query.end_time)
                points *= max(1.0 - coverage, 0.0)
                if points < 1:
                    continue
        n = pad_pow2(max(int(math.ceil(points / s)), 1))
        # group count: "none" keeps every series; aggregations reduce —
        # approximated as one group (conservatively LOW, so estimation
        # errs toward admitting)
        g = pad_pow2(s if sub.aggregator == "none" else 1)
        breakdown = jaxprof.stage_breakdown(platform, s, n, w, g, ds_fn,
                                            bool(sub.rate))
        total_s += sum(breakdown.values())
        # Out-of-core plans: a [s, w] state past the streaming budget
        # no longer refuses — the tiled executor serves it (ROADMAP
        # item 4) — so the gate must PRICE the tiled plan (compute +
        # the spill/dispatch overhead of costmodel.predict_tiled)
        # instead of shedding a query the planner would answer.  The
        # sizing mirrors ops/tiling.size_tiles against the same
        # budgets; an unservable plan adds nothing (the planner's
        # structured 413 is cheaper than any queue wait).
        state_mb = tsdb.config.get_int("tsd.query.streaming.state_mb")
        pool = getattr(tsdb, "spill_pool", None)
        if pool is not None and state_mb > 0 and ds_fn is not None:
            # the PLANNER's per-cell estimate, not a constant: 16B for
            # single-lane sums, 264B for sketch percentiles — a flat
            # 24B would miss spill-heavy sketch plans (under-pricing)
            # and tax resident single-lane plans (over-shedding)
            from opentsdb_tpu.ops.streaming import (SKETCH_K,
                                                    is_sketch_ds,
                                                    lanes_for)
            sketch = (is_sketch_ds(ds_fn) and tsdb.config.get_bool(
                "tsd.query.streaming.sketch_percentiles"))
            per_cell = 8 + 8 * len(lanes_for([ds_fn])) \
                + (4 * SKETCH_K if sketch else 0)
        else:
            per_cell = 0
        if (pool is not None and state_mb > 0 and per_cell
                and s * w * per_cell > state_mb * 2**20):
            from opentsdb_tpu.ops import costmodel as cm
            from opentsdb_tpu.ops.tiling import size_tiles
            chunk_points = max(tsdb.config.get_int(
                "tsd.query.streaming.chunk_points"), 1)
            plan = size_tiles(
                s, w, state_mb * 2**20, per_cell, g,
                tsdb.config.get_int("tsd.query.spill.max_tiles"),
                chunks_per_tile=max(int(math.ceil(
                    points / chunk_points)), 1))
            if plan is not None and plan.spill_bytes \
                    <= pool.host_budget + pool.disk_budget:
                total_s += cm.predict_tiled(
                    s, w, g, plan.n_tiles, plan.n_stripes,
                    plan.spill_bytes, plan.dispatches, platform)
    return total_s * 1e3


# --------------------------------------------------------------------- #
# Degradation ladder                                                    #
# --------------------------------------------------------------------- #

# Rung 1: coarsen eligible fixed downsample intervals by these factors.
_COARSEN_FACTORS = (2, 4, 8, 16)
# Rung 2: truncate the range toward the present, keeping this fraction.
_TRUNCATE_KEEP = (0.5, 0.25, 0.125)


def _coarsenable(sub) -> bool:
    ds = sub.downsample_spec
    return (ds is not None and ds.interval_ms > 0
            and not ds.use_calendar and not ds.run_all)


def try_degrade(tsdb, ts_query, budget_ms: float,
                queue_wait_ms: float) -> dict | None:
    """Mutate ``ts_query`` down the ladder until its predicted cost
    fits ``budget_ms - queue_wait_ms``; returns the degradation note
    for the partialResults annotation, or None when even the last rung
    doesn't fit.  Deterministic and cheap: each rung re-runs the same
    plan-shape estimate.  Rungs coarsen from the ORIGINAL interval
    (not compounding), so the note reports the factor actually
    applied."""
    fits_ms = budget_ms - queue_wait_ms
    coarsen = [sub for sub in ts_query.queries if _coarsenable(sub)]
    originals = {id(sub): sub.downsample_spec.interval_ms
                 for sub in coarsen}
    for factor in _COARSEN_FACTORS:
        if not coarsen:
            break
        for sub in coarsen:
            sub.downsample_spec.interval_ms = \
                originals[id(sub)] * factor
            # the STRING form is what travels to stats/duplicate
            # detection/peers (TSQuery hash + ts_query_json) and what a
            # re-validate would re-parse — keep it in lockstep with the
            # parsed spec (a coarsenable spec always has a "-fn" tail)
            sub.downsample = "%dms-%s" % (
                sub.downsample_spec.interval_ms,
                sub.downsample.split("-", 1)[1])
        if estimate_plan_cost_ms(tsdb, ts_query) <= fits_ms:
            return {"coarsenedIntervalFactor": factor,
                    "coarsenedIntervalMs": max(
                        sub.downsample_spec.interval_ms
                        for sub in coarsen)}
    span_ms = ts_query.end_time - ts_query.start_time
    for keep in _TRUNCATE_KEEP:
        new_start = int(ts_query.end_time - span_ms * keep)
        ts_query.start_time = new_start
        # the string form travels to fan-out peers (_raw_query) — keep
        # it in lockstep with the parsed time
        ts_query.start = str(new_start)
        if estimate_plan_cost_ms(tsdb, ts_query) <= fits_ms:
            note = {"truncatedStartMs": new_start,
                    "truncatedKeepFraction": keep}
            if coarsen:
                note["coarsenedIntervalFactor"] = _COARSEN_FACTORS[-1]
            return note
    return None


# --------------------------------------------------------------------- #
# The gate                                                              #
# --------------------------------------------------------------------- #

class Permit:
    """One admitted query's permit: releases on exit, exactly once."""

    def __init__(self, gate: "AdmissionGate | None",
                 tenant: str = "default", gate_tenant: str | None = None):
        self._gate = gate
        self._t0 = time.monotonic()
        self.degrade_note: dict | None = None
        # the clamped tenant of the admitted request — set at acquire
        # so downstream accounting (per-tenant latency histograms,
        # slow-query captures) reuses ONE clamping decision.  The
        # gate's OWN inflight bookkeeping releases under the identity
        # it admitted with (gate_tenant — "default" when fair share
        # is off), which admit() must never overwrite.
        self.tenant = tenant
        self._gate_tenant = gate_tenant or tenant

    def __enter__(self) -> "Permit":
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def release(self) -> None:
        gate, self._gate = self._gate, None
        if gate is not None:
            gate._release((time.monotonic() - self._t0) * 1e3,
                          self._gate_tenant)


class _Waiter:
    """One queued query's token: identity + the DRR bookkeeping the
    fair-share drain needs (clamped tenant, predicted cost).  `public`
    is the un-collapsed clamped tenant the permit reports for latency
    labels (== tenant unless fair share is off)."""

    __slots__ = ("tenant", "priority", "cost_ms", "public")

    def __init__(self, tenant: str, priority: str, cost_ms: float,
                 public: str | None = None):
        self.tenant = tenant
        self.priority = priority
        self.cost_ms = max(float(cost_ms), 1.0)
        self.public = public or tenant


class AdmissionGate:
    """Concurrency permits + bounded per-priority wait queues with
    weighted deficit-round-robin tenant fair share.

    One instance per TSDB (``gate_for``), shared by every responder
    thread.  All mutable state is guarded by ``_lock``; waiters park on
    a Condition sharing that lock and re-check on a short tick so
    cancellation flips (which don't notify) are observed promptly.

    Draining order: priority class first (interactive before batch —
    the PR 8 contract), then WEIGHTED DEFICIT ROUND ROBIN across the
    clamped tenants inside a class, each queued entry costing its
    costmodel-predicted milliseconds (1 ms floor when unpredicted).
    Every virtual DRR round credits each backlogged tenant
    ``tsd.query.tenant.quantum_ms`` x its weight of deficit; the
    tenant able to afford its head entry in the fewest rounds drains
    next — so one tenant's dashboard storm queues behind its own
    deficit while other tenants' entries keep draining at their
    weighted share.  ``tsd.query.tenant.max_inflight`` additionally
    caps any one tenant's concurrently held permits.  With a single
    tenant (the default) the drain reduces exactly to the PR 8
    per-priority FIFO.
    """

    def __init__(self, config):
        self.enabled = config.get_bool("tsd.query.admission.enable")
        self.permits = config.get_int("tsd.query.admission.permits")
        self.queue_limit = config.get_int("tsd.query.admission.queue_limit")
        self.max_wait_ms = config.get_int("tsd.query.admission.max_wait_ms")
        self.fair_share = config.get_bool("tsd.query.tenant.fair_share")
        self.quantum_ms = max(
            config.get_int("tsd.query.tenant.quantum_ms"), 1)
        self.tenant_max_inflight = config.get_int(
            "tsd.query.tenant.max_inflight")
        self._weights = self._parse_weights(
            config.get_string("tsd.query.tenant.weights"))
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self.in_flight = 0  # guarded-by: _lock
        # per priority class: tenant -> FIFO of _Waiter entries
        # guarded-by: _lock
        self._queues: dict[str, dict[str, deque]] = {
            c: {} for c in CLASSES}
        # DRR rotation (tenants with queued work, arrival order) and
        # deficit counters per class  # guarded-by: _lock
        self._rr: dict[str, deque] = {c: deque() for c in CLASSES}
        self._deficit: dict[str, dict[str, float]] = {
            c: {} for c in CLASSES}
        # permits currently held per tenant  # guarded-by: _lock
        self._tenant_inflight: dict[str, int] = {}
        # EWMA of permit-hold time, the Retry-After basis
        self._ewma_service_ms = 200.0  # guarded-by: _lock
        self.admitted = 0  # guarded-by: _lock
        self.shed = 0  # guarded-by: _lock
        # per-tenant drained/refused split (the fair-share audit trail;
        # mirrored into the registry counters)  # guarded-by: _lock
        self.tenant_admitted: dict[str, int] = {}
        self.tenant_refused: dict[str, int] = {}

    @staticmethod
    def _parse_weights(spec: str) -> dict[str, float]:
        """'tenant:weight,...' -> {tenant: weight}; malformed entries
        are skipped (an operator typo must not take the gate down)."""
        out: dict[str, float] = {}
        for part in (spec or "").split(","):
            part = part.strip()
            if not part or ":" not in part:
                continue
            name, _, w = part.rpartition(":")
            try:
                weight = float(w)
            except ValueError:
                continue
            if name.strip() and weight > 0:
                out[name.strip()] = weight
        return out

    def _weight(self, tenant: str) -> float:
        return self._weights.get(tenant, 1.0)

    # -- accounting -----------------------------------------------------

    def _gauge_depths_locked(self) -> None:
        for cls, tenants in self._queues.items():
            REGISTRY.gauge(
                "tsd.query.admission.queue_depth",
                "Admission wait-queue depth, by priority class").labels(
                    priority=cls).set(
                        sum(len(q) for q in tenants.values()))

    def _depth_locked(self) -> int:
        return sum(len(q) for tenants in self._queues.values()
                   for q in tenants.values())

    def retry_after_s(self) -> int:
        """Seconds until capacity plausibly frees: the backlog (queued
        + in flight) worked off at the observed service rate."""
        with self._lock:
            backlog = self._depth_locked() + self.in_flight
            ewma = self._ewma_service_ms
        lanes = max(self.permits, 1)
        return max(int(math.ceil(backlog * ewma / lanes / 1e3)), 1)

    def queue_wait_estimate_ms(self) -> float:
        """Expected wait before a permit frees for a NEW arrival."""
        with self._lock:
            if self.in_flight < self.permits and self._depth_locked() == 0:
                return 0.0
            backlog = self._depth_locked() + 1
            ewma = self._ewma_service_ms
        return backlog * ewma / max(self.permits, 1)

    def _shed(self, reason: str, message: str,
              tenant: str = "default") -> ShedError:
        with self._lock:
            self.shed += 1
            self.tenant_refused[tenant] = \
                self.tenant_refused.get(tenant, 0) + 1
        REGISTRY.counter(
            "tsd.query.admission.shed",
            "Queries refused by the admission gate, by reason").labels(
                reason=reason).inc()
        REGISTRY.counter(
            "tsd.query.tenant.refused",
            "Queries refused by the admission gate, by clamped tenant "
            "(the refused half of the demand split)").labels(
                tenant=tenant).inc()
        return ShedError(message, retry_after_s=self.retry_after_s())

    # -- acquire/release ------------------------------------------------

    def _tenant_capped_locked(self, tenant: str) -> bool:
        cap = self.tenant_max_inflight
        return cap > 0 and self._tenant_inflight.get(tenant, 0) >= cap

    def _queue_full_locked(self, tenant: str) -> bool:
        """With fair share on, the queue bound applies PER TENANT: a
        storming tenant saturates its own backlog and sheds at the
        door while other tenants still enqueue (total backlog stays
        bounded — tenant cardinality is clamped by tsd.diag.tenants/
        tenant_buckets).  Fair share off keeps the PR 8 global bound."""
        if not self.fair_share:
            return self._depth_locked() >= self.queue_limit
        return sum(len(self._queues[cls].get(tenant, ()))
                   for cls in CLASSES) >= self.queue_limit

    def _admit_locked(self, tenant: str, priority: str, wait_ms: float,
                      public_tenant: str | None = None) -> Permit:
        """`tenant` is the gate's DRR identity (collapsed to "default"
        when fair share is off) and owns the inflight bookkeeping;
        ACCOUNTING (the drained/refused split, the registry counters)
        always uses the real clamped tenant, or the demand counter's
        per-tenant series and the admitted series would disagree and
        the health engine's starvation invariant would misfire on a
        fair-share-off daemon."""
        public = public_tenant or tenant
        self.in_flight += 1
        self.admitted += 1
        self._tenant_inflight[tenant] = \
            self._tenant_inflight.get(tenant, 0) + 1
        self.tenant_admitted[public] = \
            self.tenant_admitted.get(public, 0) + 1
        self._set_inflight_gauge_locked()
        self._observe_wait(priority, wait_ms)
        return Permit(self, tenant=public, gate_tenant=tenant)

    def acquire(self, deadline: Deadline | None, priority: str,
                route: str = "api/query", tenant: str = "default",
                cost_ms: float = 1.0) -> Permit:
        """Block until a permit is held, or raise: ShedError (queue
        full / waited past max_wait), QueryException (deadline expired
        or cancelled while queued — WITHOUT taking a permit).
        ``cost_ms`` is the costmodel-predicted device cost the DRR
        drain charges against the tenant's deficit."""
        faults.check("admission.acquire", route=route)
        if not self.enabled:
            return Permit(None, tenant=tenant)
        if priority not in self._queues:
            priority = CLASSES[0]
        public_tenant = tenant
        if not self.fair_share:
            # fair share off: every query shares one DRR identity, so
            # the drain below IS the PR 8 per-priority FIFO (the
            # permit keeps the real clamped tenant for latency labels)
            tenant = "default"
        waiter = _Waiter(tenant, priority, cost_ms,
                         public=public_tenant)
        t0 = time.monotonic()
        admitted = None
        with self._lock:
            if (self.in_flight < self.permits
                    and self._depth_locked() == 0
                    and not self._tenant_capped_locked(tenant)):
                admitted = self._admit_locked(tenant, priority, 0.0,
                                              public_tenant)
            elif self._queue_full_locked(tenant):
                # raise outside the lock (the counter path re-locks)
                full = True
            else:
                full = False
                self._enqueue_locked(waiter)
                self._gauge_depths_locked()
        if admitted is not None:
            self._count_admitted(public_tenant)
            return admitted
        if full:
            raise self._shed(
                "queue_full",
                "Sorry, the query admission queue is full (%d waiting, "
                "%d in flight). Please retry later." % (
                    self.queue_limit, self.permits),
                tenant=public_tenant)
        return self._wait_in_queue(deadline, waiter, t0)

    @staticmethod
    def _count_admitted(tenant: str) -> None:
        REGISTRY.counter(
            "tsd.query.tenant.admitted",
            "Queries admitted through the gate, by clamped tenant "
            "(the drained half of the demand split)").labels(
                tenant=tenant).inc()

    def _enqueue_locked(self, waiter: _Waiter) -> None:
        tenants = self._queues[waiter.priority]
        q = tenants.get(waiter.tenant)
        if q is None:
            q = tenants[waiter.tenant] = deque()
            self._rr[waiter.priority].append(waiter.tenant)
            self._deficit[waiter.priority].setdefault(waiter.tenant,
                                                      0.0)
        q.append(waiter)

    def _remove_locked(self, waiter: _Waiter) -> None:
        tenants = self._queues[waiter.priority]
        q = tenants.get(waiter.tenant)
        if q is None:
            return
        try:
            q.remove(waiter)
        except ValueError:
            return
        if not q:
            del tenants[waiter.tenant]
            try:
                self._rr[waiter.priority].remove(waiter.tenant)
            except ValueError:
                pass
            self._deficit[waiter.priority].pop(waiter.tenant, None)

    def _pick_locked(self):
        """The weighted-DRR drain choice: first priority class with
        eligible work; within it, the tenant whose head entry needs
        the fewest virtual quantum rounds to afford.  Returns
        (waiter, rounds) or (None, 0) when nothing is eligible (all
        queued tenants at their inflight cap)."""
        for cls in CLASSES:
            tenants = self._queues[cls]
            if not tenants:
                continue
            deficit = self._deficit[cls]
            best = None
            for pos, t in enumerate(self._rr[cls]):
                q = tenants.get(t)
                if not q or self._tenant_capped_locked(t):
                    continue
                qw = self.quantum_ms * self._weight(t)
                need = q[0].cost_ms - deficit.get(t, 0.0)
                rounds = 0 if need <= 0 else int(math.ceil(need / qw))
                if best is None or (rounds, pos) < (best[0], best[1]):
                    best = (rounds, pos, t)
            if best is not None:
                rounds, _pos, t = best
                return tenants[t][0], rounds
            # every queued tenant in this class is capped: lower
            # classes may still drain (capacity isolation, not a leak
            # — the capped tenants' permits free into this class
            # first on release)
        return None, 0

    def _claim_locked(self, waiter: _Waiter, rounds: int,
                      t0: float) -> Permit:
        """Serve `waiter`: run the virtual DRR rounds (crediting every
        backlogged tenant in the class), charge its cost against its
        tenant's deficit, and hand over a permit."""
        cls = waiter.priority
        deficit = self._deficit[cls]
        if rounds:
            for t in self._rr[cls]:
                if self._queues[cls].get(t):
                    deficit[t] = deficit.get(t, 0.0) \
                        + rounds * self.quantum_ms * self._weight(t)
        deficit[waiter.tenant] = deficit.get(waiter.tenant, 0.0) \
            - waiter.cost_ms
        self._remove_locked(waiter)
        self._gauge_depths_locked()
        # a claim changes the drain choice: with multiple free permits
        # the NEXT eligible waiter must re-evaluate now, not on its
        # 50 ms cancellation tick
        self._cv.notify_all()
        wait_ms = (time.monotonic() - t0) * 1e3
        return self._admit_locked(waiter.tenant, cls, wait_ms,
                                  waiter.public)

    def _wait_in_queue(self, deadline: Deadline | None, waiter: _Waiter,
                       t0: float) -> Permit:
        tenant = waiter.public
        while True:
            expired = raise_shed = False
            permit = None
            with self._lock:
                if self.in_flight < self.permits:
                    picked, rounds = self._pick_locked()
                    if picked is waiter:
                        permit = self._claim_locked(waiter, rounds, t0)
                if permit is None:
                    if deadline is not None and (deadline.is_cancelled()
                                                 or deadline.expired()):
                        self._remove_locked(waiter)
                        self._gauge_depths_locked()
                        self._cv.notify_all()
                        expired = True
                    else:
                        waited_ms = (time.monotonic() - t0) * 1e3
                        if waited_ms >= self.max_wait_ms > 0:
                            self._remove_locked(waiter)
                            self._gauge_depths_locked()
                            self._cv.notify_all()
                            raise_shed = True
                        else:
                            self._cv.wait(_WAIT_TICK_S)
            if permit is not None:
                self._count_admitted(tenant)
                return permit
            if expired:
                if deadline.is_cancelled():
                    count_cancelled("queued")
                # raises QueryCancelledException (503) or the timeout
                # 413 — the query leaves WITHOUT having dispatched
                deadline.check()
                raise QueryException("Sorry, your query's deadline "
                                     "expired while queued.")
            if raise_shed:
                raise self._shed(
                    "max_wait",
                    "Sorry, no query capacity freed within %d ms. "
                    "Please retry later." % self.max_wait_ms,
                    tenant=tenant)

    def _release(self, held_ms: float, tenant: str = "default") -> None:
        with self._lock:
            self.in_flight -= 1
            left = self._tenant_inflight.get(tenant, 0) - 1
            if left > 0:
                self._tenant_inflight[tenant] = left
            else:
                self._tenant_inflight.pop(tenant, None)
            self._ewma_service_ms = (0.8 * self._ewma_service_ms
                                     + 0.2 * held_ms)
            self._set_inflight_gauge_locked()
            self._cv.notify_all()

    def contended(self) -> bool:
        """True when an arrival would queue (permits exhausted or a
        backlog exists) — the state in which DRR costs matter."""
        with self._lock:
            return (self.in_flight >= self.permits
                    or self._depth_locked() > 0)

    def tenant_inflight_of(self, tenant: str) -> int:
        """Permits this tenant currently holds (the admission span's
        fair-share annotation)."""
        with self._lock:
            return self._tenant_inflight.get(tenant, 0)

    def tenant_snapshot(self) -> dict:
        """The fair-share audit view served at /api/diag: per-tenant
        inflight permits, queued backlog, current deficit, weight, and
        the drained/refused split of the demand counter."""
        with self._lock:
            tenants: set[str] = set(self._tenant_inflight)
            tenants.update(self.tenant_admitted)
            tenants.update(self.tenant_refused)
            for cls in CLASSES:
                tenants.update(self._queues[cls])
            out = {}
            for t in sorted(tenants):
                out[t] = {
                    "inflight": self._tenant_inflight.get(t, 0),
                    "queued": sum(
                        len(self._queues[cls].get(t, ()))
                        for cls in CLASSES),
                    "deficitMs": {
                        cls: round(self._deficit[cls].get(t, 0.0), 3)
                        for cls in CLASSES
                        if t in self._deficit[cls]},
                    "weight": self._weight(t),
                    "admitted": self.tenant_admitted.get(t, 0),
                    "refused": self.tenant_refused.get(t, 0),
                }
            return {
                "fairShare": self.fair_share,
                "quantumMs": self.quantum_ms,
                "maxInflightPerTenant": self.tenant_max_inflight,
                "tenants": out,
            }

    def _set_inflight_gauge_locked(self) -> None:
        REGISTRY.gauge(
            "tsd.query.admission.inflight",
            "Queries currently holding an admission permit").set(
                self.in_flight)

    @staticmethod
    def _observe_wait(priority: str, wait_ms: float) -> None:
        REGISTRY.histogram(
            "tsd.query.admission.wait_ms",
            "Admission queue wait (ms), by priority class").labels(
                priority=priority).observe(wait_ms)


_GATE_LOCK = threading.Lock()


def gate_for(tsdb) -> AdmissionGate:
    gate = getattr(tsdb, "_admission_gate", None)
    if gate is None:
        with _GATE_LOCK:
            gate = getattr(tsdb, "_admission_gate", None)
            if gate is None:
                gate = AdmissionGate(tsdb.config)
                tsdb._admission_gate = gate
    return gate


# --------------------------------------------------------------------- #
# The front door                                                        #
# --------------------------------------------------------------------- #

def admit(tsdb, ts_query, http_query=None,
          route: str = "api/query") -> Permit:
    """Admission decision for one parsed, validated query: predict,
    (maybe) degrade, queue, admit — or raise ShedError (503 +
    Retry-After) / the deadline's own exception.  Returns the held
    Permit; ``permit.degrade_note`` is set when the ladder ran.

    The decision is traced as an ``admission`` child span (wait ms,
    decision, queue depth, predicted vs remaining ms).
    """
    from opentsdb_tpu.obs.flightrec import clamp_tenant
    if route.startswith("api/replication"):
        # replication traffic is EXEMPT from the query gate by
        # contract (tsd/replication.py): an overloaded query tier
        # shedding work must not sever durability.  It is bounded by
        # its own tsd.replication.max_inflight_mb byte gate instead.
        # Defensive: the replication RPC never calls admit(), but a
        # future route must not silently start queueing WAL ships
        # behind interactive queries.
        return Permit(None, tenant="replication")
    gate = gate_for(tsdb)
    deadline = active_deadline()
    priority = ""
    fanout = False
    tenant_raw = None
    if http_query is not None:
        priority = (http_query.request.header(PRIORITY_HEADER)
                    or "").strip().lower()
        tenant_raw = http_query.request.header(TENANT_HEADER)
        # a peer's raw-extraction sub-request must NEVER degrade: the
        # coordinator merges raw points verbatim and drops any
        # annotation entry (no "metric" key), so a peer-side
        # coarsen/truncate would arrive as an unmarked wrong answer.
        # Shed instead — a 503'd peer lands in the coordinator's own
        # partial_results machinery, which IS marked.
        fanout = bool(http_query.request.header("x-tsdb-cluster"))
    if priority not in CLASSES:
        priority = CLASSES[0]
    tenant = clamp_tenant(tsdb.config, tenant_raw)
    # key the request's latency-attribution profile by the same
    # clamped tenant the metrics use — set before the verdict so shed
    # requests profile under their tenant too
    latattr.set_tenant(tenant)
    # per-tenant demand: one tick per arriving query, BEFORE the
    # verdict — the fair-share scheduler (ROADMAP item 1) needs to see
    # demand it refused, not just demand it served
    REGISTRY.counter(
        "tsd.query.tenant.demand",
        "Queries arriving at admission, by clamped tenant").labels(
            tenant=tenant).inc()
    recorder = getattr(tsdb, "flightrec", None)
    with obs_trace.stage("admission", route=route, priority=priority,
                         tenant=tenant) as span:
        if deadline is not None:
            # an ALREADY-dead request (expired before admission, or
            # disconnect flipped the token mid-parse) raises its own
            # 413/503 here, not a misleading shed
            deadline.check()
        note = None
        cost_ms = 1.0
        if (gate.enabled and gate.fair_share
                and not (deadline is not None and deadline.bounded)
                and gate.contended()):
            # unbounded-deadline requests skip the shed estimate below,
            # but the DRR drain still needs a real per-query cost while
            # the gate is CONTENDED — without it, weighted fair share
            # degrades to query-count round robin and a tenant of huge
            # scans drains the same share as a tenant of tiny dashboard
            # panels.  Uncontended gates skip the walk (fast-path
            # admits never consult the deficit).
            cost_ms = estimate_plan_cost_ms(tsdb, ts_query)
        if gate.enabled and deadline is not None and deadline.bounded:
            predicted_ms = estimate_plan_cost_ms(tsdb, ts_query)
            cost_ms = predicted_ms
            queue_ms = gate.queue_wait_estimate_ms()
            remaining_ms = deadline.remaining_ms()
            obs_trace.annotate(span, predicted_ms=round(predicted_ms, 3),
                               queue_wait_estimate_ms=round(queue_ms, 3),
                               remaining_ms=round(remaining_ms, 3))
            if predicted_ms + queue_ms > remaining_ms:
                if _degrade_allowed(tsdb) and not fanout:
                    note = try_degrade(tsdb, ts_query,
                                       remaining_ms, queue_ms)
                if note is None:
                    obs_trace.annotate(span, decision="shed")
                    if recorder is not None:
                        recorder.record(
                            "admission", decision="shed",
                            reason="predicted_cost", route=route,
                            priority=priority, tenant=tenant,
                            predictedMs=round(predicted_ms, 3),
                            remainingMs=round(remaining_ms, 3))
                    raise gate._shed(
                        "predicted_cost",
                        "Sorry, this query's predicted cost (%d ms) "
                        "cannot fit in its remaining deadline (%d ms "
                        "after an estimated %d ms queue wait). Please "
                        "decrease your time range or coarsen the "
                        "downsample interval." % (
                            predicted_ms, remaining_ms, queue_ms),
                        tenant=tenant)
                REGISTRY.counter(
                    "tsd.query.admission.degraded",
                    "Queries served degraded by the admission ladder, "
                    "by reason").labels(reason="predicted_cost").inc()
                obs_trace.annotate(span, degraded=note)
        t0 = time.monotonic()
        try:
            permit = gate.acquire(deadline, priority, route=route,
                                  tenant=tenant, cost_ms=cost_ms)
        except QueryException as e:
            wait_ms = round((time.monotonic() - t0) * 1e3, 3)
            latattr.mark("admission_wait")
            decision = "shed" if isinstance(e, ShedError) else "cancelled"
            obs_trace.annotate(span, decision=decision, wait_ms=wait_ms)
            if recorder is not None:
                recorder.record("admission", decision=decision,
                                route=route, priority=priority,
                                tenant=tenant, waitMs=wait_ms)
            raise
        permit.degrade_note = note
        permit.tenant = tenant
        wait_ms = round((time.monotonic() - t0) * 1e3, 3)
        # everything since the parse mark — cost estimation, the
        # degradation ladder, and the gate wait itself — is admission
        latattr.mark("admission_wait")
        decision = "degraded" if note else "admitted"
        obs_trace.annotate(span, decision=decision, wait_ms=wait_ms,
                           tenant_inflight=gate.tenant_inflight_of(
                               permit._gate_tenant))
        if recorder is not None:
            fields = {"decision": decision, "route": route,
                      "priority": priority, "tenant": tenant,
                      "waitMs": wait_ms}
            if note:
                fields["degraded"] = note
            recorder.record("admission", **fields)
        return permit


def _degrade_allowed(tsdb) -> bool:
    return tsdb.config.get_string(
        "tsd.query.degrade").strip().lower() == "allow"
