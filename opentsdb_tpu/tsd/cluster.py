"""Cross-host request serving: one /api/query, the whole cluster's data.

Reference behavior being matched: a single TSD answers a query by
fanning scanners out across every storage node that holds a salt bucket
and aggregating the returned rows itself (SaltScanner — one scanner per
bucket across RegionServers, /root/reference/src/core/SaltScanner.java:269;
the TSD is the aggregation point).  The TPU-native equivalent keeps the
same shape: the TSD that receives a query asks every peer TSD for the
RAW matching series (aggregator "none", no downsample/rate — each peer
runs its own planner over its own store and chips), folds the returned
series together with its local ones into a scratch store, and runs the
ORIGINAL query against that — so downsampling, rate, interpolation,
group-by, and percentiles all execute once, locally, with exactly the
single-host semantics the test suite pins.  DCN traffic is the raw
matching points, as in the reference's scanner model.

This is the REQUEST-DRIVEN serving path for data partitioned across
independent TSD processes (each ingesting its own series).  It is
complementary to the SPMD path (`tsd.network.distributed.*` +
`jax.distributed.initialize`), where every process holds a shard of one
logical store and executes lock-step collectives — that path has the
higher throughput ceiling but needs all processes in one JAX runtime;
this one needs only HTTP reachability.

Fault tolerance (the asynchbase role — the reference TSD survives
RegionServer flaps because its storage client retries internally;
direct HTTP fan-out needs its own layer):

  * every peer fetch runs under capped-exponential-backoff retries
    (utils/retry.py) with the overall budget from
    `tsd.network.cluster.timeout_ms`;
  * each peer has a circuit breaker: after
    `tsd.network.cluster.breaker.threshold` consecutive fetch failures
    it opens and fetches fail fast (no network) until
    `tsd.network.cluster.breaker.cooldown_ms` elapses, then ONE
    half-open probe decides (success closes it, failure re-opens);
    state is surfaced through collect_stats -> /api/stats;
  * `tsd.network.cluster.partial_results` picks the stance when a peer
    still fails after all that: "error" (default — the reference's
    scanner-error stance, a partial answer is worse than an error)
    fails the query; "allow" folds whatever peers answered, marks
    `exec_stats["partialResults"]`/`["clusterPeersFailed"]`, and the
    query answers 200 with the surviving data (tsd/rpcs.py annotates
    the response body).

Config:
  tsd.network.cluster.peers       comma-separated "host:port" of the
                                  OTHER TSDs (empty = single-host serving)
  tsd.network.cluster.timeout_ms  overall per-fetch budget (all retries)
  tsd.network.cluster.partial_results           "error" | "allow"
  tsd.network.cluster.retry.max_attempts        attempts per peer fetch
  tsd.network.cluster.retry.attempt_timeout_ms  per-attempt deadline
                                  (0 = the full remaining budget)
  tsd.network.cluster.breaker.threshold         consecutive failures
                                  that open a peer's breaker (0 = off)
  tsd.network.cluster.breaker.cooldown_ms       open -> half-open delay

Loop prevention: fan-out requests carry the `X-TSDB-Cluster: fanout`
header; a TSD answering one serves purely from its local store.
"""

from __future__ import annotations

import copy
import json
import logging
import threading
import time
import urllib.error
import urllib.request
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from opentsdb_tpu.models.tsquery import TSQuery, TSSubQuery
from opentsdb_tpu.obs import trace as obs_trace
from opentsdb_tpu.query.limits import (Deadline, QueryException,
                                       active_deadline)
from opentsdb_tpu.uid import NoSuchUniqueName
from opentsdb_tpu.utils import faults
from opentsdb_tpu.utils.retry import RetryPolicy, call_with_retries

LOG = logging.getLogger(__name__)

CLUSTER_HEADER = "x-tsdb-cluster"


def cluster_peers(config) -> list[str]:
    raw = config.get_string("tsd.network.cluster.peers") or ""
    return [p.strip() for p in raw.split(",") if p.strip()]


def is_fanout_request(http_query) -> bool:
    """True for requests issued by a peer's fan-out (serve locally)."""
    return bool(http_query.request.headers.get(CLUSTER_HEADER))


# --------------------------------------------------------------------- #
# Circuit breakers                                                      #
# --------------------------------------------------------------------- #

class BreakerOpenError(ConnectionError):
    """A peer's circuit is open: failing fast without a network call."""


class CircuitBreaker:
    """closed -> (threshold consecutive failures) -> open ->
    (cooldown) -> half-open probe -> closed | open.

    ``threshold`` counts whole fetches (post-retry), not attempts:
    retries absorb transients, the breaker reacts to persistent ones.
    ``clock`` is injectable so tests drive the cooldown without sleeps.
    """

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

    def __init__(self, threshold: int, cooldown_s: float, clock=time.monotonic,
                 listener=None):
        self.threshold = threshold
        self.cooldown_s = cooldown_s
        self._clock = clock
        # state-transition callback `listener(old, new)`, invoked
        # OUTSIDE _lock (the flight recorder takes its own lock; a
        # callback under ours would order the two) — may observe a
        # state that already moved on, never a torn one
        self._listener = listener
        self._lock = threading.Lock()
        # guarded-by: _lock
        self.state = self.CLOSED
        self.consecutive_failures = 0  # guarded-by: _lock
        self.opened_at = 0.0  # guarded-by: _lock
        self._probing = False  # guarded-by: _lock
        # lifetime open transitions (stats)  # guarded-by: _lock
        self.opens = 0
        # calls refused while open (stats)  # guarded-by: _lock
        self.fast_fails = 0

    def _notify(self, old: str, new: str) -> None:
        if self._listener is not None and old != new:
            self._listener(old, new)

    def allow(self) -> bool:
        """True if a fetch may proceed now.  While open, the first call
        after the cooldown becomes the single half-open probe."""
        if self.threshold <= 0:
            return True
        with self._lock:
            if self.state == self.CLOSED:
                return True
            if self.state == self.OPEN:
                if self._clock() - self.opened_at >= self.cooldown_s:
                    self.state = self.HALF_OPEN
                    self._probing = True
                    transition = (self.OPEN, self.HALF_OPEN)
                else:
                    self.fast_fails += 1
                    return False
            # half-open: exactly one probe in flight.  Not counted as a
            # fast fail — callers may WAIT on the probe's verdict
            # (probe_pending) instead of failing.
            elif self._probing:
                return False
            else:
                self._probing = True
                return True
        self._notify(*transition)
        return True

    def probe_pending(self) -> bool:
        """True while a half-open probe is in flight — a sibling fetch
        (another subquery of the same clustered query) should await its
        verdict rather than fast-fail; the probe's success must not
        fail the very query that triggered it."""
        with self._lock:
            return self.state == self.HALF_OPEN and self._probing

    def record_success(self) -> None:
        with self._lock:
            old = self.state
            self.state = self.CLOSED
            self.consecutive_failures = 0
            self._probing = False
        self._notify(old, self.CLOSED)

    def record_failure(self) -> None:
        if self.threshold <= 0:
            return
        old = new = None
        with self._lock:
            if self.state == self.HALF_OPEN:
                # failed probe: back to a full cooldown
                old, new = self.state, self.OPEN
                self.state = self.OPEN
                self.opened_at = self._clock()
                self.opens += 1
                self._probing = False
            else:
                self.consecutive_failures += 1
                if (self.state == self.CLOSED
                        and self.consecutive_failures >= self.threshold):
                    old, new = self.state, self.OPEN
                    self.state = self.OPEN
                    self.opened_at = self._clock()
                    self.opens += 1
        if new is not None:
            self._notify(old, new)


class ClusterState:
    """Per-TSDB fault-tolerance state: one breaker per peer plus the
    counters /api/stats surfaces.  Lives across queries (attached to the
    TSDB instance by _state below)."""

    def __init__(self, config, recorder=None):
        self.threshold = config.get_int(
            "tsd.network.cluster.breaker.threshold")
        self.cooldown_s = config.get_int(
            "tsd.network.cluster.breaker.cooldown_ms") / 1e3
        # flight recorder (obs/flightrec.py): breaker transitions are
        # retained diagnostics — an operator reading /api/diag after a
        # partial-results burst sees WHICH peer flapped and when
        self.recorder = recorder
        self._lock = threading.Lock()
        # guarded-by: _lock
        self._breakers: dict[str, CircuitBreaker] = {}
        self.fetch_retries = 0  # guarded-by: _lock
        self.fetch_failures = 0  # guarded-by: _lock
        self.partial_queries = 0  # guarded-by: _lock
        self.failed_queries = 0  # guarded-by: _lock

    def _transition_listener(self, peer: str):
        recorder = self.recorder
        if recorder is None:
            return None

        def on_transition(old: str, new: str) -> None:
            recorder.record("breaker", peer=peer, before=old, state=new)
        return on_transition

    def breaker(self, peer: str) -> CircuitBreaker:
        with self._lock:
            b = self._breakers.get(peer)
            if b is None:
                b = self._breakers[peer] = CircuitBreaker(
                    self.threshold, self.cooldown_s,
                    listener=self._transition_listener(peer))
            return b

    def count(self, attr: str, n: int = 1) -> None:
        with self._lock:
            setattr(self, attr, getattr(self, attr) + n)

    def breakers(self) -> dict[str, CircuitBreaker]:
        with self._lock:
            return dict(self._breakers)


_STATE_LOCK = threading.Lock()

# Probe-verdict poll cadence (_guarded_fetch_inner): each tick parks on
# the request deadline's cancellation token, never a bare sleep.
_PROBE_TICK_S = 0.02


def _state(tsdb) -> ClusterState:
    state = getattr(tsdb, "_cluster_state", None)
    if state is None:
        with _STATE_LOCK:
            state = getattr(tsdb, "_cluster_state", None)
            if state is None:
                state = ClusterState(tsdb.config,
                                     recorder=getattr(tsdb, "flightrec",
                                                      None))
                tsdb._cluster_state = state
    return state


def partial_annotation(exec_stats: dict) -> dict | None:
    """The degraded-serving annotation every query-shaped endpoint
    attaches to a 200 that is missing peers — or that the admission
    ladder coarsened/truncated (tsd/admission.py) — None when the
    answer is the full one.  One definition so the contract can't
    diverge per endpoint."""
    if not exec_stats.get("partialResults"):
        return None
    out = {
        "partialResults": True,
        "clusterPeersFailed": exec_stats.get("clusterPeersFailed", 0),
        "clusterPeers": exec_stats.get("clusterPeers", 0),
    }
    if exec_stats.get("degraded"):
        out["degraded"] = exec_stats["degraded"]
    return out


def collect_stats(tsdb, collector) -> None:
    """Cluster fault-tolerance telemetry for /api/stats + telnet stats.
    Nothing is recorded on a TSD that never served clustered (the state
    attaches on first fan-out), keeping single-host stats unchanged."""
    state = getattr(tsdb, "_cluster_state", None)
    if state is None:
        return
    collector.record("cluster.fetch.retries", state.fetch_retries)
    collector.record("cluster.fetch.failures", state.fetch_failures)
    collector.record("cluster.queries", state.partial_queries,
                     "result=partial")
    collector.record("cluster.queries", state.failed_queries,
                     "result=failed")
    numeric = {CircuitBreaker.CLOSED: 0, CircuitBreaker.HALF_OPEN: 1,
               CircuitBreaker.OPEN: 2}
    for peer, b in sorted(state.breakers().items()):
        collector.record("cluster.breaker.state", numeric[b.state],
                         "peer=%s" % peer)
        collector.record("cluster.breaker.opens", b.opens,
                         "peer=%s" % peer)
        collector.record("cluster.breaker.fast_fails", b.fast_fails,
                         "peer=%s" % peer)


# --------------------------------------------------------------------- #
# Fan-out plumbing                                                      #
# --------------------------------------------------------------------- #

def _raw_query(ts_query: TSQuery) -> TSQuery:
    """The per-series extraction query: same range/filters, NO
    aggregation, downsampling, or rate — peers ship raw matching points
    and every cross-series semantic runs once at the receiver."""
    raw = TSQuery(start=ts_query.start, end=ts_query.end)
    raw.ms_resolution = True
    for i, sub in enumerate(ts_query.queries):
        if not sub.metric:
            # TSUIDs are per-process surrogate keys here (the reference's
            # are cluster-global via the shared HBase uid table) — a
            # tsuid doesn't name the same series on a peer
            raise ValueError("cluster serving requires metric-named "
                             "subqueries (tsuids are host-local)")
        r = TSSubQuery(aggregator="none", metric=sub.metric, index=i)
        r.filters = copy.deepcopy(sub.filters)
        r.explicit_tags = sub.explicit_tags
        raw.queries.append(r)
    raw.validate()
    return raw


def _sub_json(raw: TSQuery, index: int) -> dict:
    """One-subquery POST body for a peer (one request per subquery keeps
    the result->subquery mapping trivial, like one scanner per bucket)."""
    sub = raw.queries[index]
    body = {
        "start": raw.start,
        "msResolution": True,
        "queries": [{
            "aggregator": "none",
            "metric": sub.metric,
            "explicitTags": sub.explicit_tags,
            "filters": [f.to_json() for f in (sub.filters or [])],
        }],
    }
    if raw.end:
        body["end"] = raw.end
    return body


def _fetch_peer(peer: str, body: dict, timeout_s: float,
                trace_id: str | None = None,
                deadline=None, tenant_header: str | None = None,
                extra_headers: dict | None = None) -> list[dict]:
    faults.check("cluster.peer_fetch", peer=peer)
    headers = {"Content-Type": "application/json",
               "X-TSDB-Cluster": "fanout"}
    if extra_headers:
        # sharded serving scopes each peer fetch to its shard cover
        # (X-TSDB-Shards — tsd/replication.py)
        headers.update(extra_headers)
    if trace_id:
        # the receiving TSD adopts this id for ITS trace of the raw
        # fetch — one clustered query, one trace id across every host
        headers["X-TSDB-Trace-Id"] = trace_id
    if tenant_header:
        # the client's RAW tenant header travels with the fan-out (each
        # peer clamps against its own registered table, like the
        # coordinator did) — peer-side per-tenant demand/latency
        # accounting must attribute the load to the real tenant, not
        # "default"
        headers["X-TSDB-Tenant"] = tenant_header
    if deadline is not None:
        # don't even connect when done for — an UNBOUNDED deadline is
        # still a cancellation token (client disconnect, server drain),
        # and each retry attempt re-enters here
        deadline.check()
        if deadline.bounded:
            # forward the coordinator's REMAINDER so the peer aborts
            # its own planning/dispatch once we've given up (it mints
            # its Deadline from this header —
            # rpc_manager._mint_deadline)
            remaining = deadline.remaining_ms()
            headers["X-TSDB-Deadline-Ms"] = str(max(int(remaining), 1))
            timeout_s = min(timeout_s, max(remaining / 1e3, 0.05))
    req = urllib.request.Request(
        "http://%s/api/query" % peer,
        data=json.dumps(body).encode(),
        headers=headers,
        method="POST")
    with urllib.request.urlopen(req, timeout=timeout_s) as resp:
        data = resp.read()
    data = faults.mangle("cluster.peer_body", data, peer=peer)
    return json.loads(data.decode())


def _retry_policy(config, deadline=None) -> RetryPolicy:
    budget_s = max(config.get_int("tsd.network.cluster.timeout_ms"),
                   1000) / 1e3
    if deadline is not None and deadline.bounded:
        # the whole retry stack (attempts + backoff sleeps) is clamped
        # to the request's remainder: a peer fetch must never outlive
        # the deadline the coordinator is serving under
        budget_s = max(min(budget_s, deadline.remaining_ms() / 1e3), 0.05)
    attempt_ms = config.get_int(
        "tsd.network.cluster.retry.attempt_timeout_ms")
    return RetryPolicy(
        max_attempts=max(
            config.get_int("tsd.network.cluster.retry.max_attempts"), 1),
        budget_s=budget_s,
        attempt_timeout_s=attempt_ms / 1e3 if attempt_ms > 0 else 0.0)


class PeerRejectedError(RuntimeError):
    """The peer answered a deterministic 4xx: reachable and responsive,
    so neither retried (same request, same answer) nor a breaker
    failure (availability is fine; the REQUEST is what it rejects)."""


class PeerUnknownNameError(PeerRejectedError):
    """The peer answered 404 — a name-lookup miss (http.error_status
    maps NoSuchUniqueName there): it never assigned a UID for the
    metric, which in a sharded cluster is routine, not a fault.  The
    sharded arm walks the shard's preference list on this; a shard
    whose every live member answers 404 holds nothing for the metric
    (empty contribution), where a plain failure would mean lost data."""


def _guarded_fetch(state: ClusterState, policy: RetryPolicy, peer: str,
                   body: dict, span=None,
                   trace_id: str | None = None,
                   deadline=None,
                   tenant_header: str | None = None,
                   extra_headers: dict | None = None) -> list[dict]:
    """One peer fetch under the full fault-tolerance stack: breaker
    fast-fail, then retries with backoff inside the overall budget
    (already clamped to the request deadline's remainder).

    `span` (an obs.trace.Span created by the submitting thread) records
    the fetch's fate: retry count, final breaker state, and the error
    when the peer lost — the annotations the degraded response's trace
    carries so an operator can see WHY a 200 is partial."""
    try:
        return _guarded_fetch_inner(state, policy, peer, body, span,
                                    trace_id, deadline, tenant_header,
                                    extra_headers)
    finally:
        if span is not None:
            span.tags["breaker"] = state.breaker(peer).state
            span.finish()


def _guarded_fetch_inner(state: ClusterState, policy: RetryPolicy,
                         peer: str, body: dict, span,
                         trace_id: str | None,
                         deadline=None,
                         tenant_header: str | None = None,
                         extra_headers: dict | None = None) -> list[dict]:
    breaker = state.breaker(peer)
    if span is not None:
        span.tags.setdefault("retries", 0)
    start = time.monotonic()
    allowed = breaker.allow()
    if not allowed and breaker.probe_pending():
        # a sibling subquery of this same query is the half-open probe:
        # wait for its verdict instead of fast-failing — the probe's
        # success must not fail the query that triggered it.  The tick
        # parks on the deadline's cancellation token (a throwaway
        # unbounded Deadline when the caller passed none) so a client
        # disconnect releases this wait within one tick instead of
        # polling out the whole fetch budget
        dl = deadline if deadline is not None else Deadline()
        wait_until = start + policy.budget_s
        while (not allowed and breaker.probe_pending()
               and time.monotonic() < wait_until):
            if dl.wait_cancelled(_PROBE_TICK_S):
                dl.check()
            allowed = breaker.allow()
        # the wait spent part of THIS fetch's overall budget — the
        # retries below get only the remainder, keeping timeout_ms the
        # true per-fetch ceiling
        waited = time.monotonic() - start
        if waited > 0.01:
            import dataclasses
            policy = dataclasses.replace(
                policy, budget_s=max(policy.budget_s - waited, 0.1))
    if not allowed:
        state.count("fetch_failures")
        err = BreakerOpenError(
            "peer %s circuit is open (%d consecutive failures; retry "
            "after cooldown)" % (peer, breaker.consecutive_failures))
        obs_trace.annotate(span, error=str(err))
        raise err

    def fetch(timeout_s: float) -> list[dict]:
        try:
            return _fetch_peer(peer, body, timeout_s, trace_id, deadline,
                               tenant_header=tenant_header,
                               extra_headers=extra_headers)
        except urllib.error.HTTPError as e:
            if e.code == 404:
                raise PeerUnknownNameError(
                    "peer %s has no UID for the queried name (404)"
                    % peer) from e
            if 400 <= e.code < 500:
                raise PeerRejectedError(
                    "peer %s rejected the raw-series fetch: HTTP %d"
                    % (peer, e.code)) from e
            raise

    def on_retry(n: int, e: Exception) -> None:
        state.count("fetch_retries")
        if span is not None:
            span.tags["retries"] = span.tags.get("retries", 0) + 1
        LOG.warning("retrying peer %s (attempt %d failed: %s)",
                    peer, n, e)

    try:
        # deadline passed EXPLICITLY: this runs on a fan-out executor
        # worker, where the ambient TLS deadline (responder thread) is
        # not visible — without it the backoff sleeps would be blind to
        # cancellation again
        result = call_with_retries(
            fetch, policy,
            no_retry_on=(PeerRejectedError, QueryException),
            on_retry=on_retry, deadline=deadline)
    except QueryException as e:
        # the COORDINATOR gave up (request deadline expired / cancelled
        # mid-fetch) — the peer did not fail, so its breaker is not
        # charged.  Except as the half-open probe: a probe with no
        # verdict must settle (re-open) or _probing wedges and every
        # sibling busy-waits on a verdict that never comes.
        if breaker.state == CircuitBreaker.HALF_OPEN:
            breaker.record_failure()
        state.count("fetch_failures")
        obs_trace.annotate(span, error=str(e))
        raise
    except PeerUnknownNameError as e:
        # routine in sharded serving (the peer holds nothing for the
        # name): settles the breaker like any responsive answer, and
        # does NOT count as a fetch failure
        breaker.record_success()
        obs_trace.annotate(span, unknown_name=True)
        raise
    except PeerRejectedError as e:
        # responsive peer: availability-wise a SUCCESS — crucially this
        # settles a half-open probe (otherwise _probing would stay set
        # forever and wedge the breaker half-open with every later
        # fetch busy-waiting on a verdict that never comes)
        breaker.record_success()
        state.count("fetch_failures")
        obs_trace.annotate(span, error=str(e))
        raise
    except Exception as e:
        breaker.record_failure()
        state.count("fetch_failures")
        obs_trace.annotate(span, error=str(e))
        raise
    breaker.record_success()
    return result


def _ingest_series(scratch, metric: str, tags: dict,
                   dps_items) -> int:
    """Fold one raw series into the scratch store; returns point count.
    dps_items: iterable of (ts_ms int, value int|float)."""
    pts = [(int(t), v) for t, v in dps_items
           if not (isinstance(v, float) and v != v)]      # drop NaN fills
    if not pts:
        return 0
    pts.sort()
    ts = np.fromiter((t for t, _ in pts), np.int64, len(pts))
    vals = np.fromiter((float(v) for _, v in pts), np.float64, len(pts))
    is_int = np.fromiter(
        (isinstance(v, int) or (isinstance(v, float) and v.is_integer()
                                and abs(v) < 2 ** 53)
         for _, v in pts), bool, len(pts))
    key = scratch._series_key(metric, tags, create=True)
    scratch.store.add_batch(key, ts, vals, is_int)
    return len(pts)


def serve_query(tsdb, ts_query: TSQuery, http_query=None,
                exec_stats: dict | None = None):
    """The single front door for every query-shaped endpoint (/api/query,
    /api/query/exp metric extraction, /api/query/gexp): clustered when
    peers are configured and the request is eligible, local otherwise.
    Eligibility: not a peer's own fan-out (loop guard), not a delete,
    and every subquery metric-named (tsuids are host-local).

    With sharded replication armed (tsd/replication.py) the clustered
    arm fans out only to the owning shards' healthy members, and the
    local arm honors a coordinator's X-TSDB-Shards scope — a node
    holding both owned and replicated copies serves exactly the shards
    it was asked for, so the fold never double-counts a series."""
    if cluster_peers(tsdb.config) \
            and (http_query is None or not is_fanout_request(http_query)) \
            and not getattr(ts_query, "delete", False) \
            and all(sub.metric for sub in ts_query.queries):
        from opentsdb_tpu.tsd.admission import TENANT_HEADER
        tenant_header = (http_query.request.header(TENANT_HEADER)
                         if http_query is not None else None)
        if getattr(tsdb, "replication", None) is not None:
            return run_sharded(tsdb, ts_query, exec_stats=exec_stats,
                               tenant_header=tenant_header)
        return run_clustered(tsdb, ts_query, exec_stats=exec_stats,
                             tenant_header=tenant_header)
    runner = tsdb.new_query_runner()
    out = runner.run(ts_query)
    repl = getattr(tsdb, "replication", None)
    if repl is not None and http_query is not None \
            and is_fanout_request(http_query):
        from opentsdb_tpu.tsd.replication import (SHARDS_HEADER,
                                                  series_shard)
        raw = http_query.request.headers.get(SHARDS_HEADER)
        if raw:
            keep = {int(x) for x in raw.split(",") if x.strip()}
            out = [qr for qr in out
                   if series_shard(qr.metric, qr.tags,
                                   repl.shard_count) in keep]
    if exec_stats is not None:
        exec_stats.update(runner.exec_stats)
    return out


def _scratch_store(tsdb):
    """The per-query aggregation buffer both clustered arms fold raw
    series into before running the ORIGINAL query once, locally."""
    from opentsdb_tpu.core import TSDB
    from opentsdb_tpu.utils.config import Config
    scratch = TSDB(Config({
        "tsd.core.auto_create_metrics": True,
        # a failover refetch can re-fold a series a half-answered member
        # already contributed — identical replicated points, resolved
        # last-write-wins instead of raising
        "tsd.storage.fix_duplicates": "true",
        # serving knobs only — the scratch is a per-query aggregation
        # buffer, not a daemon: no flight recorder or health engine of
        # its own (constructing one per clustered query would be waste,
        # and its ring would be discarded with the scratch)
        "tsd.query.device_cache.enable": "false",
        "tsd.diag.enable": "false",
        "tsd.health.enable": "false",
        # the final fold runs on THIS box: a coordinator whose operator
        # disabled the mesh (e.g. a JAX without shard_map) must not have
        # the scratch re-enable it behind their back
        "tsd.query.mesh.enable": tsdb.config.get_string(
            "tsd.query.mesh.enable"),
    }))
    # the scratch runner's planner events must land in the SERVING
    # daemon's flight recorder — they carry the request's trace id, so
    # a clustered query's plan decisions stay reconstructible from the
    # coordinator's /api/diag ring
    scratch.flightrec = getattr(tsdb, "flightrec", None)
    return scratch


def _local_raw_series(tsdb, raw: TSQuery, unknown_subs: set | None = None):
    """This host's raw-series extraction for the fan-out fold, one
    subquery at a time.  A metric with no local UID contributes nothing
    instead of failing the extraction: in a cluster — sharded routing
    especially, where whole series land on other owners — a node
    routinely coordinates queries over metrics it never ingested.
    ``unknown_subs``, when given, collects the indexes of subqueries
    with no local UID so the caller can tell "empty here" from "no
    such name anywhere"."""
    runner = tsdb.new_query_runner()
    runner.exec_stats = {}
    for i, sub in enumerate(raw.queries):
        try:
            yield from runner.run_sub(raw, sub)
        except NoSuchUniqueName:
            if unknown_subs is not None:
                unknown_subs.add(i)
            continue


def _fold_payload(scratch, payload: list[dict]) -> int:
    """Fold one peer's raw-series response into the scratch store."""
    total = 0
    for item in payload:
        if "metric" not in item:
            continue        # statsSummary etc.
        total += _ingest_series(
            scratch, item["metric"], item.get("tags") or {},
            ((int(t), v)
             for t, v in (item.get("dps") or {}).items()))
    return total


def run_sharded(tsdb, ts_query: TSQuery, exec_stats: dict | None = None,
                tenant_header: str | None = None):
    """The shard-scoped clustered arm (tsd/replication.py): fan out
    only to the owning shards' healthy members — each peer fetch
    carries its shard cover in X-TSDB-Shards, the local extraction is
    filtered the same way, and a peer that fails mid-query has its
    shards REFETCHED from the next healthy preference member, so a
    single peer death serves full (non-partial) results.  Only a shard
    with no live member left degrades to the partial_results stance."""
    from opentsdb_tpu.tsd.replication import series_shard

    repl = tsdb.replication
    state = _state(tsdb)
    deadline = active_deadline()
    policy = _retry_policy(tsdb.config, deadline)
    allow_partial = (tsdb.config.get_string(
        "tsd.network.cluster.partial_results").strip().lower() == "allow")
    raw = _raw_query(ts_query)
    cover, uncovered = repl.query_plan()
    scratch = _scratch_store(tsdb)
    total = 0
    lost_shards: set[int] = set(uncovered)
    failed_nodes: set[str] = set()
    local_shards = set(cover.get(repl.self_id, set()))
    remote = {peer: shards for peer, shards in cover.items()
              if peer != repl.self_id}

    tr = obs_trace.active()
    parent = tr.current() if tr is not None else None
    trace_id = tr.trace_id if tr is not None else None

    def shards_header(shards: set[int]) -> dict:
        return {"X-TSDB-Shards": ",".join(str(s) for s in
                                          sorted(shards))}

    local_series: list | None = None

    def ingest_local(shards: set[int]) -> None:
        # extract once, reuse across failover rounds — each round would
        # otherwise re-scan the whole local store on the degraded path
        nonlocal total, local_series
        if local_series is None:
            local_series = list(_local_raw_series(tsdb, raw))
        for qr in local_series:
            if series_shard(qr.metric, qr.tags,
                            repl.shard_count) in shards:
                total += _ingest_series(scratch, qr.metric, qr.tags,
                                        qr.dps)

    pool = None
    futures: dict = {}
    if remote:
        pool = ThreadPoolExecutor(
            max_workers=min(len(remote) * len(raw.queries), 16))
        for peer, shards in remote.items():
            hdr = shards_header(shards)
            for i in range(len(raw.queries)):
                span = (parent.child("peer_fetch", peer=peer,
                                     subquery=i, shards=len(shards))
                        if parent is not None else None)
                futures[pool.submit(
                    _guarded_fetch, state, policy, peer,
                    _sub_json(raw, i), span, trace_id, deadline,
                    tenant_header, hdr)] = (peer, i, span)
    def local_knows_all() -> bool:
        for sub in raw.queries:
            try:
                tsdb.metrics.get_id(sub.metric)
            except NoSuchUniqueName:
                return False
        return True

    try:
        # consulted[shard]: members already asked for this shard this
        # query — failed OR healthy-but-404 — so the preference walk
        # below never re-asks one
        consulted: dict[int, set[str]] = {}
        todo: set[int] = set()
        if local_shards:
            # contribute whatever is locally known either way; if SOME
            # queried metric has no local UID, additionally walk the
            # covered shards' preference lists like a remote 404 would
            # — a replica may hold series for a metric this node has
            # not caught up to (re-folds of the locally-known metrics
            # resolve as duplicates)
            ingest_local(local_shards)
            if not local_knows_all():
                for shard in local_shards:
                    consulted.setdefault(shard, set()).add(repl.self_id)
                    todo.add(shard)
        if futures:
            for fut, (peer, i, _span) in futures.items():
                try:
                    payload = fut.result()
                except PeerUnknownNameError:
                    # healthy peer, no UID for the metric: walk on to
                    # the shard's next preference member (a replica may
                    # hold series the assigned member has not caught up
                    # to); NOT a breaker/partial event
                    for shard in remote.get(peer, set()):
                        consulted.setdefault(shard, set()).add(peer)
                        todo.add(shard)
                    continue
                except Exception as e:
                    if peer not in failed_nodes:
                        failed_nodes.add(peer)
                        LOG.warning(
                            "sharded peer %s failed; refetching its %d "
                            "shard(s) from replicas: %s",
                            peer, len(remote.get(peer, ())), e)
                    for shard in remote.get(peer, set()):
                        consulted.setdefault(shard, set()).add(peer)
                        todo.add(shard)
                    continue
                total += _fold_payload(scratch, payload)
        # failover walk: reassign every pending shard to its next
        # healthy unconsulted preference member (serving continues with
        # FULL data; a refetch re-folding an already-answered subquery
        # is safe — the scratch resolves identical duplicate points).
        # A shard exhausting its members is LOST (partial stance) only
        # if some consulted member actually failed; members that merely
        # answered 404 prove the shard holds nothing for the metric.
        # Breaker charges from the failed fetches feed the next
        # query_plan's epoch bump.
        while todo:
            reassign: dict[str, set[int]] = {}
            for shard in list(todo):
                nxt = repl.next_member(
                    shard, exclude=consulted[shard] | failed_nodes)
                if nxt is None:
                    # a healthy member's 404 is authoritative — the
                    # replica set is caught up on the ack path, so "no
                    # UID here" proves the shard holds nothing for the
                    # metric; the shard is lost only when NOT ONE
                    # member gave a healthy answer
                    if consulted[shard] <= failed_nodes:
                        lost_shards.add(shard)
                    todo.discard(shard)
                else:
                    reassign.setdefault(nxt, set()).add(shard)
            extra_local = reassign.pop(repl.self_id, set())
            if extra_local:
                # contribute what this node knows; a metric with no
                # local UID walks on like a remote 404 would (a replica
                # may hold series this node has not caught up to)
                ingest_local(extra_local)
                if local_knows_all():
                    todo -= extra_local
                else:
                    for shard in extra_local:
                        consulted[shard].add(repl.self_id)
            for node, shards in reassign.items():
                hdr = shards_header(shards)
                served = True
                for i in range(len(raw.queries)):
                    span = (parent.child("peer_fetch", peer=node,
                                         subquery=i, failover=True,
                                         shards=len(shards))
                            if parent is not None else None)
                    try:
                        payload = _guarded_fetch(
                            state, policy, node, _sub_json(raw, i),
                            span, trace_id, deadline, tenant_header,
                            hdr)
                    except PeerUnknownNameError:
                        served = False
                        for shard in shards:
                            consulted[shard].add(node)
                        break
                    except Exception as e:
                        LOG.warning("sharded failover fetch from %s "
                                    "failed too: %s", node, e)
                        served = False
                        failed_nodes.add(node)
                        for shard in shards:
                            consulted[shard].add(node)
                        break
                    total += _fold_payload(scratch, payload)
                if served:
                    todo -= shards
    finally:
        if pool is not None:
            pool.shutdown(wait=False, cancel_futures=True)
        for fut, (_peer, _i, span) in futures.items():
            if span is not None and span.wall_ms is None:
                if fut.cancelled():
                    span.tags.setdefault(
                        "error", "cancelled: query aborted before "
                                 "this fetch ran")
                span.finish()
    if lost_shards:
        state.count("failed_queries" if not allow_partial
                    else "partial_queries")
        if not allow_partial:
            raise RuntimeError(
                "shard(s) %s have no live member (cover epoch %d)"
                % (sorted(lost_shards), repl.current_epoch()))
    runner = scratch.new_query_runner()
    out = runner.run(ts_query)
    for qr in out:
        qr.tsuids = []      # scratch-store surrogate uids (see
        #                     run_clustered)
    if exec_stats is not None:
        exec_stats.update(runner.exec_stats)
        exec_stats["clusterPeers"] = len(remote)
        exec_stats["clusterRawPoints"] = total
        exec_stats["shardEpoch"] = repl.current_epoch()
        exec_stats["shardCover"] = {node: len(shards)
                                    for node, shards in cover.items()}
        if failed_nodes:
            exec_stats["clusterPeersFailed"] = len(failed_nodes)
        if lost_shards:
            exec_stats["clusterShardsFailed"] = len(lost_shards)
            exec_stats["partialResults"] = True
    return out


def run_clustered(tsdb, ts_query: TSQuery, exec_stats: dict | None = None,
                  tenant_header: str | None = None):
    """Fan the query's raw-series extraction across this host and every
    peer, fold everything into a scratch store, run the ORIGINAL query
    against it.  Returns the planner's QueryResult list (drop-in for
    QueryRunner.run).  `exec_stats`, when given, receives the scratch
    runner's execution telemetry plus cluster counters (the /api/stats/
    query surface must not go dark for clustered queries).

    Peer failures (after retries/breakers): with
    tsd.network.cluster.partial_results=error the first one fails the
    query; with "allow" the surviving peers' data still answers and the
    failed-peer count rides out in exec_stats."""
    peers = cluster_peers(tsdb.config)
    state = _state(tsdb)
    # the ambient deadline is read HERE, on the handler thread that
    # owns it — the pool threads below only carry the object
    deadline = active_deadline()
    policy = _retry_policy(tsdb.config, deadline)
    allow_partial = (tsdb.config.get_string(
        "tsd.network.cluster.partial_results").strip().lower() == "allow")
    raw = _raw_query(ts_query)
    scratch = _scratch_store(tsdb)
    total = 0

    # peer fetches submit FIRST so they overlap the local extraction
    # below (the two are independent; serializing them would make the
    # extraction phase local_scan + max(peer_fetch) instead of the max)
    jobs = [(peer, i) for peer in peers for i in range(len(raw.queries))]
    pool = futures = None
    # per-peer child spans are created HERE, on the thread that owns the
    # trace (children lists are unlocked); the pool threads only finish
    # and annotate their own span.  The trace id travels with every
    # fetch so the peers' traces correlate.
    tr = obs_trace.active()
    parent = tr.current() if tr is not None else None
    trace_id = tr.trace_id if tr is not None else None
    if jobs:
        # no context manager: in "error" mode a peer failure must return
        # its error NOW, not after every straggling in-flight fetch
        # drains its timeout (shutdown(wait=False, cancel_futures=True)
        # drops the queued ones; already-running urllib calls finish in
        # the background)
        pool = ThreadPoolExecutor(max_workers=min(len(jobs), 16))
        futures = {}
        for peer, i in jobs:
            span = (parent.child("peer_fetch", peer=peer, subquery=i)
                    if parent is not None else None)
            futures[pool.submit(_guarded_fetch, state, policy, peer,
                                _sub_json(raw, i), span,
                                trace_id, deadline,
                                tenant_header)] = (peer, i, span)

    failed_peers: set[str] = set()
    unknown_local: set[int] = set()
    unknown_peers: dict[int, int] = {}
    # local extraction: straight off this host's store/planner (objects,
    # no JSON round-trip), concurrent with the in-flight peer fetches
    try:
        for qr in _local_raw_series(tsdb, raw, unknown_local):
            total += _ingest_series(scratch, qr.metric, qr.tags, qr.dps)
        if futures:
            for fut, (peer, i, _span) in futures.items():
                try:
                    payload = fut.result()
                except PeerUnknownNameError:
                    # a healthy name-lookup miss, not a peer failure:
                    # never marks the answer partial
                    unknown_peers[i] = unknown_peers.get(i, 0) + 1
                    continue
                except Exception as e:
                    if not allow_partial:
                        state.count("failed_queries")
                        raise RuntimeError(
                            "cluster peer %s failed the raw-series "
                            "fetch: %s" % (peer, e)) from e
                    if peer not in failed_peers:
                        failed_peers.add(peer)
                        LOG.warning(
                            "cluster peer %s failed; serving partial "
                            "results without it: %s", peer, e)
                    continue
                total += _fold_payload(scratch, payload)
        # a name NO reachable node has assigned answers exactly like a
        # single host: NoSuchUniqueName (HTTP 400 name-lookup error),
        # not an empty 200 — a typo'd dashboard must stay visible
        # (a failed peer might have known it — partial stance covers
        # that; with every peer answering, the verdict is authoritative)
        if not failed_peers:
            for i in sorted(unknown_local):
                if unknown_peers.get(i, 0) == len(peers):
                    raise NoSuchUniqueName("metric",
                                           raw.queries[i].metric)
    finally:
        if pool is not None:
            pool.shutdown(wait=False, cancel_futures=True)
        if futures:
            # the error-mode early exit cancels queued fetches whose
            # spans were created at submit time — close them out so the
            # completed ring never renders a forever-climbing wallMs
            for fut, (_peer, _i, span) in futures.items():
                if span is not None and span.wall_ms is None:
                    if fut.cancelled():
                        span.tags.setdefault(
                            "error", "cancelled: query aborted before "
                                     "this fetch ran")
                    span.finish()
    LOG.debug("cluster fan-out folded %d raw points from %d peers "
              "(%d failed)", total, len(peers), len(failed_peers))
    if failed_peers:
        state.count("partial_queries")
    runner = scratch.new_query_runner()
    out = runner.run(ts_query)
    for qr in out:
        # the scratch store mints its own surrogate uids, so its tsuids
        # name nothing outside this query — without the reference's
        # cluster-global uid table (HBase tsdb-uid) there is no honest
        # cluster-wide tsuid to return
        qr.tsuids = []
    if exec_stats is not None:
        exec_stats.update(runner.exec_stats)
        exec_stats["clusterPeers"] = len(peers)
        exec_stats["clusterRawPoints"] = total
        if failed_peers:
            exec_stats["clusterPeersFailed"] = len(failed_peers)
            exec_stats["partialResults"] = True
    return out
