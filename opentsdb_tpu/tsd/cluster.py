"""Cross-host request serving: one /api/query, the whole cluster's data.

Reference behavior being matched: a single TSD answers a query by
fanning scanners out across every storage node that holds a salt bucket
and aggregating the returned rows itself (SaltScanner — one scanner per
bucket across RegionServers, /root/reference/src/core/SaltScanner.java:269;
the TSD is the aggregation point).  The TPU-native equivalent keeps the
same shape: the TSD that receives a query asks every peer TSD for the
RAW matching series (aggregator "none", no downsample/rate — each peer
runs its own planner over its own store and chips), folds the returned
series together with its local ones into a scratch store, and runs the
ORIGINAL query against that — so downsampling, rate, interpolation,
group-by, and percentiles all execute once, locally, with exactly the
single-host semantics the test suite pins.  DCN traffic is the raw
matching points, as in the reference's scanner model.

This is the REQUEST-DRIVEN serving path for data partitioned across
independent TSD processes (each ingesting its own series).  It is
complementary to the SPMD path (`tsd.network.distributed.*` +
`jax.distributed.initialize`), where every process holds a shard of one
logical store and executes lock-step collectives — that path has the
higher throughput ceiling but needs all processes in one JAX runtime;
this one needs only HTTP reachability.

Config:
  tsd.network.cluster.peers       comma-separated "host:port" of the
                                  OTHER TSDs (empty = single-host serving)
  tsd.network.cluster.timeout_ms  per-peer raw-series fetch timeout

Loop prevention: fan-out requests carry the `X-TSDB-Cluster: fanout`
header; a TSD answering one serves purely from its local store.
A peer failure fails the query (the reference's scanner-error stance:
a partial answer is worse than an error).
"""

from __future__ import annotations

import copy
import json
import logging
import urllib.request
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from opentsdb_tpu.models.tsquery import TSQuery, TSSubQuery

LOG = logging.getLogger(__name__)

CLUSTER_HEADER = "x-tsdb-cluster"


def cluster_peers(config) -> list[str]:
    raw = config.get_string("tsd.network.cluster.peers") or ""
    return [p.strip() for p in raw.split(",") if p.strip()]


def is_fanout_request(http_query) -> bool:
    """True for requests issued by a peer's fan-out (serve locally)."""
    return bool(http_query.request.headers.get(CLUSTER_HEADER))


def _raw_query(ts_query: TSQuery) -> TSQuery:
    """The per-series extraction query: same range/filters, NO
    aggregation, downsampling, or rate — peers ship raw matching points
    and every cross-series semantic runs once at the receiver."""
    raw = TSQuery(start=ts_query.start, end=ts_query.end)
    raw.ms_resolution = True
    for i, sub in enumerate(ts_query.queries):
        if not sub.metric:
            # TSUIDs are per-process surrogate keys here (the reference's
            # are cluster-global via the shared HBase uid table) — a
            # tsuid doesn't name the same series on a peer
            raise ValueError("cluster serving requires metric-named "
                             "subqueries (tsuids are host-local)")
        r = TSSubQuery(aggregator="none", metric=sub.metric, index=i)
        r.filters = copy.deepcopy(sub.filters)
        r.explicit_tags = sub.explicit_tags
        raw.queries.append(r)
    raw.validate()
    return raw


def _sub_json(raw: TSQuery, index: int) -> dict:
    """One-subquery POST body for a peer (one request per subquery keeps
    the result->subquery mapping trivial, like one scanner per bucket)."""
    sub = raw.queries[index]
    body = {
        "start": raw.start,
        "msResolution": True,
        "queries": [{
            "aggregator": "none",
            "metric": sub.metric,
            "explicitTags": sub.explicit_tags,
            "filters": [f.to_json() for f in (sub.filters or [])],
        }],
    }
    if raw.end:
        body["end"] = raw.end
    return body


def _fetch_peer(peer: str, body: dict, timeout_s: float) -> list[dict]:
    req = urllib.request.Request(
        "http://%s/api/query" % peer,
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json",
                 "X-TSDB-Cluster": "fanout"},
        method="POST")
    with urllib.request.urlopen(req, timeout=timeout_s) as resp:
        return json.loads(resp.read().decode())


def _ingest_series(scratch, metric: str, tags: dict,
                   dps_items) -> int:
    """Fold one raw series into the scratch store; returns point count.
    dps_items: iterable of (ts_ms int, value int|float)."""
    pts = [(int(t), v) for t, v in dps_items
           if not (isinstance(v, float) and v != v)]      # drop NaN fills
    if not pts:
        return 0
    pts.sort()
    ts = np.fromiter((t for t, _ in pts), np.int64, len(pts))
    vals = np.fromiter((float(v) for _, v in pts), np.float64, len(pts))
    is_int = np.fromiter(
        (isinstance(v, int) or (isinstance(v, float) and v.is_integer()
                                and abs(v) < 2 ** 53)
         for _, v in pts), bool, len(pts))
    key = scratch._series_key(metric, tags, create=True)
    scratch.store.add_batch(key, ts, vals, is_int)
    return len(pts)


def serve_query(tsdb, ts_query: TSQuery, http_query=None,
                exec_stats: dict | None = None):
    """The single front door for every query-shaped endpoint (/api/query,
    /api/query/exp metric extraction, /api/query/gexp): clustered when
    peers are configured and the request is eligible, local otherwise.
    Eligibility: not a peer's own fan-out (loop guard), not a delete,
    and every subquery metric-named (tsuids are host-local)."""
    if cluster_peers(tsdb.config) \
            and (http_query is None or not is_fanout_request(http_query)) \
            and not getattr(ts_query, "delete", False) \
            and all(sub.metric for sub in ts_query.queries):
        return run_clustered(tsdb, ts_query, exec_stats=exec_stats)
    runner = tsdb.new_query_runner()
    out = runner.run(ts_query)
    if exec_stats is not None:
        exec_stats.update(runner.exec_stats)
    return out


def run_clustered(tsdb, ts_query: TSQuery, exec_stats: dict | None = None):
    """Fan the query's raw-series extraction across this host and every
    peer, fold everything into a scratch store, run the ORIGINAL query
    against it.  Returns the planner's QueryResult list (drop-in for
    QueryRunner.run).  `exec_stats`, when given, receives the scratch
    runner's execution telemetry plus cluster counters (the /api/stats/
    query surface must not go dark for clustered queries)."""
    from opentsdb_tpu.core import TSDB
    from opentsdb_tpu.utils.config import Config

    peers = cluster_peers(tsdb.config)
    timeout_s = max(
        tsdb.config.get_int("tsd.network.cluster.timeout_ms"), 1000) / 1e3
    raw = _raw_query(ts_query)

    scratch = TSDB(Config({
        "tsd.core.auto_create_metrics": True,
        # serving knobs only — the scratch is a per-query aggregation
        # buffer, not a daemon
        "tsd.query.device_cache.enable": "false",
    }))
    total = 0

    # peer fetches submit FIRST so they overlap the local extraction
    # below (the two are independent; serializing them would make the
    # extraction phase local_scan + max(peer_fetch) instead of the max)
    jobs = [(peer, i) for peer in peers for i in range(len(raw.queries))]
    pool = futures = None
    if jobs:
        # no context manager: a peer failure must return its error NOW,
        # not after every straggling in-flight fetch drains its timeout
        # (shutdown(wait=False, cancel_futures=True) drops the queued
        # ones; already-running urllib calls finish in the background)
        pool = ThreadPoolExecutor(max_workers=min(len(jobs), 16))
        futures = {pool.submit(_fetch_peer, peer,
                               _sub_json(raw, i), timeout_s):
                   (peer, i) for peer, i in jobs}

    # local extraction: straight off this host's store/planner (objects,
    # no JSON round-trip), concurrent with the in-flight peer fetches
    try:
        for qr in tsdb.new_query_runner().run(raw):
            total += _ingest_series(scratch, qr.metric, qr.tags, qr.dps)
        if futures:
            for fut, (peer, i) in futures.items():
                try:
                    payload = fut.result()
                except Exception as e:
                    raise RuntimeError(
                        "cluster peer %s failed the raw-series fetch: %s"
                        % (peer, e)) from e
                for item in payload:
                    if "metric" not in item:
                        continue        # statsSummary etc.
                    total += _ingest_series(
                        scratch, item["metric"], item.get("tags") or {},
                        ((int(t), v)
                         for t, v in (item.get("dps") or {}).items()))
    finally:
        if pool is not None:
            pool.shutdown(wait=False, cancel_futures=True)
    LOG.debug("cluster fan-out folded %d raw points from %d peers",
              total, len(peers))
    runner = scratch.new_query_runner()
    out = runner.run(ts_query)
    for qr in out:
        # the scratch store mints its own surrogate uids, so its tsuids
        # name nothing outside this query — without the reference's
        # cluster-global uid table (HBase tsdb-uid) there is no honest
        # cluster-wide tsuid to return
        qr.tsuids = []
    if exec_stats is not None:
        exec_stats.update(runner.exec_stats)
        exec_stats["clusterPeers"] = len(peers)
        exec_stats["clusterRawPoints"] = total
    return out
