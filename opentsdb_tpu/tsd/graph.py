"""/q graph endpoint + the built-in query UI page.

Reference behavior: /root/reference/src/tsd/GraphHandler.java — parse the
same query-string grammar as /api/query, run the queries, render (gnuplot
PNG there, inline SVG here), with a disk result cache keyed by query hash
(:doCacheing, tsd.http.cachedir) and `ascii`/`json` output modes; plot
options wxh/yrange/ylog/nokey/title/ylabel mirror the CVE-2020-35476
allowlisted parameter set (:191).
"""

from __future__ import annotations

import hashlib
import json
import os

from opentsdb_tpu.graph.plot import Plot
from opentsdb_tpu.tsd.http import BadRequestError, HttpQuery
from opentsdb_tpu.tsd.rpcs import QueryRpc, allowed_methods


class GraphHandler:
    """GET /q."""

    def execute_http(self, tsdb, query: HttpQuery) -> None:
        allowed_methods(query, "GET", "POST")
        ts_query = QueryRpc().parse_query_string(tsdb, query)
        ts_query.validate()

        cachedir = tsdb.config.get_string("tsd.http.cachedir")
        nocache = query.has_query_string_param("nocache")
        cache_key = None
        mode = ("ascii" if query.has_query_string_param("ascii")
                else "json" if query.has_query_string_param("json")
                else "svg")
        if cachedir and not nocache:
            basis = json.dumps(sorted(query.request.query.items()))
            cache_key = os.path.join(
                cachedir, "q_%s.%s"
                % (hashlib.sha1(basis.encode()).hexdigest(), mode))
            cached = self._read_cache(cache_key, ts_query)
            if cached is not None:
                query.send_reply(cached, content_type=_CONTENT_TYPES[mode])
                return

        # same cluster front door as /api/query — the UI draws via /q,
        # so a clustered operator's graphs must span the cluster too.
        # Cache consistency holds: clustered-vs-local depends only on
        # static config, so one cache key always maps to one mode.
        # Same admission gate too: /q dispatches the same device work,
        # so it takes a permit (and may be shed or degraded) exactly
        # like /api/query.
        from opentsdb_tpu.tsd import admission
        from opentsdb_tpu.tsd.cluster import partial_annotation, serve_query
        from opentsdb_tpu.utils import faults
        exec_stats: dict = {}
        permit = admission.admit(tsdb, ts_query, query, route="q")
        with permit:
            faults.check("rpc.slow_handler", route="q")
            results = serve_query(tsdb, ts_query, query,
                                  exec_stats=exec_stats)
        if permit.degrade_note:
            exec_stats["partialResults"] = True
            exec_stats["degraded"] = permit.degrade_note
        partial = partial_annotation(exec_stats)
        if mode == "ascii":
            body = self._ascii(results)
        elif mode == "json":
            reply = {
                "plotted": sum(len(r.dps) for r in results),
                "points": sum(len(r.dps) for r in results),
                "etags": [sorted(r.tags.keys()) for r in results],
                "timing": round(query.elapsed_ms()),
            }
            if partial:
                reply.update(partial)
            body = json.dumps(reply)
        else:
            body = self._svg(query, ts_query, results)

        if cache_key is not None and not partial:
            # a degraded answer must never be cached as the full one —
            # later clients would read a silently partial graph
            self._write_cache(cache_key, body)
        query.send_reply(body, content_type=_CONTENT_TYPES[mode])
        if partial:
            # ascii/svg can't carry a body annotation; the header marks
            # every /q mode uniformly
            query.response.headers["X-TSDB-Partial-Results"] = str(
                partial["clusterPeersFailed"])

    # -- renderers --

    @staticmethod
    def _ascii(results) -> str:
        from opentsdb_tpu.utils import format_ascii_point
        lines = []
        for r in results:
            for ts, value in r.dps:
                lines.append(format_ascii_point(r.metric, ts, value,
                                                r.tags))
        return "\n".join(lines) + ("\n" if lines else "")

    @staticmethod
    def _svg(query: HttpQuery, ts_query, results) -> str:
        wxh = query.get_query_string_param("wxh") or "1024x576"
        try:
            w, h = (int(p) for p in wxh.lower().split("x"))
        except ValueError:
            raise BadRequestError("Invalid wxh parameter: " + wxh)
        plot = Plot(start_time=ts_query.start_time,
                    end_time=ts_query.end_time, width=w, height=h)
        # allowlisted display params (GraphHandler.java:191)
        plot.title = query.get_query_string_param("title") or ""
        plot.ylabel = query.get_query_string_param("ylabel") or ""
        plot.nokey = query.has_query_string_param("nokey")
        plot.ylog = query.has_query_string_param("ylog")
        yrange = query.get_query_string_param("yrange")
        if yrange:
            try:
                lo, hi = yrange.strip("[]").split(":")
                # either end may be open ("[0:]" / "[:100]"), gnuplot
                # style (GraphHandler.java yrange; review r4)
                plot.yrange = (float(lo) if lo.strip() else None,
                               float(hi) if hi.strip() else None)
            except ValueError:
                raise BadRequestError("Invalid yrange parameter: " + yrange)
            if plot.yrange == (None, None):
                plot.yrange = None
            elif (plot.yrange[0] is not None and plot.yrange[1] is not None
                    and plot.yrange[0] >= plot.yrange[1]):
                raise BadRequestError(
                    "Invalid yrange parameter: low must be below high")
        for r in results:
            tags = " ".join("%s=%s" % kv for kv in sorted(r.tags.items()))
            label = ("%s{%s}" % (r.metric, tags)) if tags else r.metric
            plot.add_series(label, [(ts, float(v)) for ts, v in r.dps])
        return plot.render_svg()

    # -- cache (GraphHandler disk cache) --

    @staticmethod
    def _read_cache(path: str, ts_query) -> str | None:
        try:
            if os.path.exists(path):
                # expire entries once the query's end time stops moving the
                # data (anything touching "now" expires quickly)
                import time
                age = time.time() - os.path.getmtime(path)
                recent = ts_query.end_time >= (time.time() - 60) * 1000
                if age < (15 if recent else 900):
                    with open(path) as fh:
                        return fh.read()
        except OSError:
            pass
        return None

    @staticmethod
    def _write_cache(path: str, body: str) -> None:
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w") as fh:
                fh.write(body)
            os.replace(tmp, path)
        except OSError:
            pass


_CONTENT_TYPES = {
    "ascii": "text/plain; charset=UTF-8",
    "json": "application/json",
    "svg": "image/svg+xml",
}
