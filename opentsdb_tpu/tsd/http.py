"""HTTP request/response primitives + the HttpQuery handler context.

Reference behavior: /root/reference/src/tsd/AbstractHttpQuery.java +
HttpQuery.java — query-string access, API versioning (`/api/v1/...`,
explodeAPIPath), serializer selection, sendReply/sendError with standard
cache headers, and BadRequestException carrying {code, message, details}
(BadRequestException.java).
"""

from __future__ import annotations

import json
import time
import traceback
from dataclasses import dataclass, field
from urllib.parse import urlsplit, parse_qs, unquote

HTTP_STATUS_TEXT = {
    200: "OK", 204: "No Content", 301: "Moved Permanently", 302: "Found",
    304: "Not Modified", 400: "Bad Request", 401: "Unauthorized",
    403: "Forbidden", 404: "Not Found", 405: "Method Not Allowed",
    408: "Request Timeout", 413: "Request Entity Too Large",
    500: "Internal Server Error", 501: "Not Implemented",
    503: "Service Unavailable",
}


class BadRequestError(Exception):
    """HTTP error with status + user message + details (BadRequestException)."""

    def __init__(self, message: str, status: int = 400, details: str = ""):
        super().__init__(message)
        self.status = status
        self.message = message
        self.details = details

    @staticmethod
    def missing_parameter(name: str) -> "BadRequestError":
        return BadRequestError("Missing parameter <code>%s</code>" % name)


@dataclass
class HttpRequest:
    """One parsed HTTP/1.1 request."""
    method: str
    uri: str
    headers: dict[str, str] = field(default_factory=dict)
    body: bytes = b""
    version: str = "HTTP/1.1"

    @property
    def path(self) -> str:
        return urlsplit(self.uri).path

    @property
    def query(self) -> dict[str, list[str]]:
        return parse_qs(urlsplit(self.uri).query, keep_blank_values=True)

    def header(self, name: str) -> str | None:
        return self.headers.get(name.lower())


@dataclass
class HttpResponse:
    status: int = 200
    headers: dict[str, str] = field(default_factory=dict)
    body: bytes = b""

    def to_bytes(self, keep_alive: bool = True) -> bytes:
        reason = HTTP_STATUS_TEXT.get(self.status, "Unknown")
        head = ["HTTP/1.1 %d %s" % (self.status, reason)]
        headers = dict(self.headers)
        headers.setdefault("Content-Length", str(len(self.body)))
        if self.status != 204:
            headers.setdefault("Content-Type", "application/json")
        headers.setdefault("Connection",
                           "keep-alive" if keep_alive else "close")
        for k, v in headers.items():
            head.append("%s: %s" % (k, v))
        return ("\r\n".join(head) + "\r\n\r\n").encode() + self.body


class HttpQuery:
    """Handler-facing request context (HttpQuery.java / AbstractHttpQuery).

    Wraps the request, resolves the API version from `/api/v{N}/...` paths,
    exposes query-string helpers, and captures the response the handler
    sends.  One instance per request; never shared.
    """

    def __init__(self, tsdb, request: HttpRequest, remote: str = "unknown"):
        self.tsdb = tsdb
        self.request = request
        self.remote = remote
        self.start_time = time.time()
        self.response: HttpResponse | None = None
        self.api_version = 0
        self._route = self._explode_api_path()
        self.serializer = None   # set by RpcManager from tsd.http.serializer
        self.show_stack_trace = (
            tsdb is not None
            and tsdb.config.get_bool("tsd.http.show_stack_trace"))

    # -- path / routing (AbstractHttpQuery.getQueryBaseRoute,
    #    HttpQuery.explodeAPIPath) --

    def _explode_api_path(self) -> str:
        path = self.request.path.lstrip("/")
        parts = path.split("/")
        if parts and parts[0] == "api":
            if len(parts) > 1 and parts[1][:1] == "v" and \
                    parts[1][1:].isdigit():
                self.api_version = int(parts[1][1:])
                parts = ["api"] + parts[2:]
                path = "/".join(parts)
            else:
                self.api_version = 1
        return path

    @property
    def path(self) -> str:
        """Versionless path, e.g. "api/query/last"."""
        return self._route

    def base_route(self) -> str:
        """First one or two path components, the RpcManager routing key."""
        parts = self._route.split("/")
        if parts[0] == "api" and len(parts) > 1:
            return "api/" + parts[1]
        return parts[0]

    def api_subpath(self) -> list[str]:
        """Path components after the base route (e.g. uid endpoints)."""
        parts = self._route.split("/")
        if parts[0] == "api":
            return parts[2:]
        return parts[1:]

    @property
    def method(self) -> str:
        return self.request.method

    # -- query string helpers (AbstractHttpQuery:163-230) --

    def get_query_string_param(self, name: str) -> str | None:
        vals = self.request.query.get(name)
        return vals[-1] if vals else None

    def get_query_string_params(self, name: str) -> list[str]:
        return self.request.query.get(name, [])

    def has_query_string_param(self, name: str) -> bool:
        return name in self.request.query

    def required_query_string_param(self, name: str) -> str:
        value = self.get_query_string_param(name)
        if value is None or value == "":
            raise BadRequestError.missing_parameter(name)
        return value

    # -- body helpers --

    def json_body(self):
        if not self.request.body:
            raise BadRequestError("Missing request content")
        try:
            return json.loads(self.request.body)
        except json.JSONDecodeError as e:
            raise BadRequestError("Unable to parse the given JSON",
                                  details=str(e))

    # -- replies (AbstractHttpQuery.sendReply/sendStatusOnly/sendBuffer) --

    def send_reply(self, body, status: int = 200,
                   content_type: str = "application/json") -> None:
        if isinstance(body, (dict, list)):
            jsonp = self.get_query_string_param("jsonp")
            text = json.dumps(body)
            if jsonp:
                text = "%s(%s)" % (jsonp, text)
                content_type = "text/javascript"
            body = text.encode()
        elif isinstance(body, str):
            body = body.encode()
        self.response = HttpResponse(
            status=status, body=body,
            headers={"Content-Type": content_type})

    def send_status_only(self, status: int) -> None:
        self.response = HttpResponse(status=status)

    def send_error(self, exc: Exception) -> None:
        """Standard error envelope {error: {code, message, details,
        trace?}} (HttpJsonSerializer.formatErrorV1)."""
        status = error_status(exc)
        if isinstance(exc, BadRequestError):
            message, details = exc.message, exc.details
        else:
            # QueryException carries an optional structured payload
            # (grid-budget 413s: computed MB, limit, suggested config)
            message = str(exc) or repr(exc)
            details = getattr(exc, "details", None) or ""
        err = {"code": status, "message": message}
        if details:
            err["details"] = details
        if self.show_stack_trace:
            err["trace"] = "".join(traceback.format_exception(exc))
        self.send_reply({"error": err}, status=status)
        retry_after = getattr(exc, "retry_after_s", None)
        if retry_after:
            # admission-shed 503s tell the client WHEN to come back
            # (tsd/admission.py ShedError)
            self.response.headers["Retry-After"] = str(int(retry_after))

    def elapsed_ms(self) -> float:
        return (time.time() - self.start_time) * 1000.0

    def effective_method(self) -> str:
        """HTTP method honoring the method_override query param
        (HttpQuery.getAPIMethod)."""
        override = self.get_query_string_param("method_override")
        return (override or self.method).upper()


def error_status(exc: Exception) -> int:
    """HTTP status for an exception: name-lookup misses are 404, user input
    errors 400 (KeyError from malformed bodies included), budget/timeout
    rejections carry their own status (413, SaltScanner.java:564-601), the
    rest 500."""
    from opentsdb_tpu.query.limits import QueryException
    from opentsdb_tpu.uid import NoSuchUniqueName, NoSuchUniqueId
    if isinstance(exc, BadRequestError):
        return exc.status
    if isinstance(exc, QueryException):
        return exc.status
    if isinstance(exc, (NoSuchUniqueName, NoSuchUniqueId)):
        return 404
    if isinstance(exc, (ValueError, KeyError, IndexError, TypeError)):
        return 400
    return 500


def parse_http_head(data: bytes) -> tuple[HttpRequest, int] | None:
    """Parse request line + headers from a buffer.

    Returns (request-without-body, header_end_offset) or None when the
    buffer does not yet hold the full header block.
    """
    end = data.find(b"\r\n\r\n")
    sep = 4
    if end < 0:
        end = data.find(b"\n\n")
        sep = 2
        if end < 0:
            return None
    head = data[:end].decode("latin-1")
    lines = head.splitlines()
    if not lines:
        raise BadRequestError("Empty request")
    parts = lines[0].split(" ")
    if len(parts) < 3:
        raise BadRequestError("Malformed request line: %r" % lines[0])
    method, uri, version = parts[0], parts[1], parts[2]
    headers: dict[str, str] = {}
    for line in lines[1:]:
        if ":" in line:
            k, v = line.split(":", 1)
            headers[k.strip().lower()] = v.strip()
    return (HttpRequest(method=method.upper(), uri=unquote_safe(uri),
                        headers=headers, version=version), end + sep)


def unquote_safe(uri: str) -> str:
    """Decode %-escapes in the path but preserve the query string raw
    (parse_qs decodes it per-parameter)."""
    split = urlsplit(uri)
    path = unquote(split.path)
    if split.query:
        return path + "?" + split.query
    return path
