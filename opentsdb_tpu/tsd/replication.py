"""Replicated sharded serving: consistent-hash ownership + WAL shipping.

The reference TSD never owns durability or replication — HBase does
(PAPER.md: the TSD is "stateless-ish").  This rebuild owns the memstore
and the WAL (storage/persist.py), so peer death without this module
silently loses that peer's series.  Here every (metric, tags) series
hashes into one of ``tsd.network.cluster.shard.count`` logical shards;
a consistent-hash ring with virtual nodes maps each shard onto a
preference list of ``tsd.network.cluster.shard.replicas`` distinct
nodes — the first is the shard's OWNER, the rest its replicas.

  * **Ingest** routes to the owner: a write arriving anywhere else is
    forwarded (one hop, ``X-TSDB-Replication: routed`` stops loops).
    The owner applies + journals the record (the WAL frame carries the
    shard id), then SYNCHRONOUSLY ships the framed record to every
    healthy replica before the write acks — a kill -9 of any single
    node after the ack can no longer lose the point.  When the owner's
    breaker is open, the next healthy preference member accepts the
    write (failover ownership) with the same contract.
  * **Catch-up** is pull-based: every node polls each peer's
    ``/api/replication/tail?since=<seq>`` on the
    ``tsd.replication.pull_interval_ms`` cadence, filling any gap the
    synchronous ship path missed (replica briefly down, ship timeout).
    A rejoining node replays its own WAL, restores its per-origin
    positions from the journaled ``rr`` records, and catches up from
    its peers' tails BEFORE re-accepting ownership (``catch_up()``,
    driven by the server at startup).
  * **Queries** fan out only to the owning shards' healthy members:
    ``query_plan()`` picks, per shard, the first healthy preference
    member, and tsd/cluster.py scopes each peer fetch to its shard set
    (``X-TSDB-Shards``).  A peer that dies mid-query has its shards
    refetched from the next member — serving continues with FULL data,
    not partialResults.  Each cover change bumps the ownership epoch
    and lands in the flight recorder.
  * **Anti-entropy**: every applied record folds into a per
    (origin, shard) CRC chain, in sequence order.  ``verify_with()``
    compares chains against a peer; a divergent chain resets the
    per-origin position to the last agreed point and re-pulls (the
    divergent tail is logically truncated — re-applied records are
    idempotent under tsd.storage.fix_duplicates).

Apply ordering: shipped records may arrive ahead of the contiguous
stream (the ship path skips shards the replica does not hold, and a
failed ship leaves a gap until the next pull).  Ahead-of-stream records
apply IMMEDIATELY (an acked point must be servable from the replica the
moment the ack returns) but are stashed; positions, CRC chains, and the
local ``rr`` journal advance only as the per-origin stream becomes
contiguous, so chains are well-defined and restarts restore exact
positions.

Replication traffic never touches the query admission gate
(tsd/admission.py) — it is bounded by its own
``tsd.replication.max_inflight_mb`` byte gate instead, so an overloaded
query tier can shed work without also severing durability.
"""

from __future__ import annotations

import hashlib
import json
import logging
import threading
import urllib.error
import urllib.parse
import urllib.request
import zlib

from opentsdb_tpu.obs.registry import REGISTRY
from opentsdb_tpu.query.limits import active_deadline
from opentsdb_tpu.tsd.http import BadRequestError, HttpQuery
from opentsdb_tpu.utils import faults

LOG = logging.getLogger(__name__)

ROUTED_HEADER = "x-tsdb-replication"
SHARDS_HEADER = "x-tsdb-shards"

# thread-local ingest context: a routed /api/put (or a replication
# apply) must not be forwarded again by the receiving TSDB
_INGEST_CTX = threading.local()


def series_shard(metric: str, tags, shard_count: int) -> int:
    """Stable shard id of one series — crc32 over the canonical
    "metric|k=v|..." form (sorted tags), identical across processes and
    restarts (unlike hash()).  ``tags`` is a dict or a tag-pair
    iterable."""
    items = sorted(tags.items() if isinstance(tags, dict) else tags)
    canon = metric + "|" + "|".join("%s=%s" % kv for kv in items)
    return zlib.crc32(canon.encode("utf-8")) % max(shard_count, 1)


def _chain_next(chain: int, crc: int) -> int:
    """Fold one record CRC into a per-(origin, shard) rolling chain."""
    return zlib.crc32(b"%08x%08x" % (chain, crc)) & 0xFFFFFFFF


class HashRing:
    """Consistent-hash ring with virtual nodes.  Adding or removing one
    of n nodes moves ~1/n of the keys (the rebalance bound the tests
    pin); everything is derived from sha1 so placement is stable across
    processes."""

    def __init__(self, nodes: list[str], virtual_nodes: int = 32):
        self.nodes = sorted(set(nodes))
        self.virtual_nodes = max(virtual_nodes, 1)
        points: list[tuple[int, str]] = []
        for node in self.nodes:
            for v in range(self.virtual_nodes):
                points.append((self._hash("%s#%d" % (node, v)), node))
        points.sort()
        self._points = points

    @staticmethod
    def _hash(key: str) -> int:
        return int.from_bytes(
            hashlib.sha1(key.encode("utf-8")).digest()[:8], "big")

    def preference(self, key: str, n: int) -> list[str]:
        """The first ``n`` DISTINCT nodes clockwise from the key's
        point: owner first, then replicas."""
        if not self._points:
            return []
        n = min(max(n, 1), len(self.nodes))
        h = self._hash(key)
        import bisect
        i = bisect.bisect_right(self._points, (h, "￿"))
        out: list[str] = []
        for step in range(len(self._points)):
            node = self._points[(i + step) % len(self._points)][1]
            if node not in out:
                out.append(node)
                if len(out) == n:
                    break
        return out


def shard_preferences(ring: HashRing, shard_count: int, rf: int
                      ) -> list[list[str]]:
    """Preference list per shard id — the ownership table."""
    return [ring.preference("shard-%d" % s, rf)
            for s in range(shard_count)]


def plan_cover(preferences: list[list[str]], healthy
               ) -> tuple[dict[str, set[int]], set[int]]:
    """THE shard-scoped fan-out verdict, one pure function with two
    callers (the plan_decision convention): the executor
    (cluster.run_clustered) dispatches on it and EXPLAIN
    (query/explain.py) serializes it, so report and execution cannot
    drift.  Per shard: the first healthy preference member serves it.
    Returns ``(cover: node -> shard set, uncovered shards)``."""
    cover: dict[str, set[int]] = {}
    uncovered: set[int] = set()
    for shard, pref in enumerate(preferences):
        for node in pref:
            if healthy(node):
                cover.setdefault(node, set()).add(shard)
                break
        else:
            uncovered.add(shard)
    return cover, uncovered


class _Origin:
    """Per-peer apply state: the contiguous position in that origin's
    WAL stream, the ahead-of-stream stash, and the per-shard CRC
    chains.  All fields are guarded by the manager's ``_lock``."""

    def __init__(self):
        self.pos = 0                       # guarded-by: _lock
        # seq -> (crc, shard, payload, already_applied)
        self.pending: dict[int, tuple] = {}  # guarded-by: _lock
        # shard -> (count, chain crc)
        self.chains: dict[int, tuple[int, int]] = {}  # guarded-by: _lock


class ReplicationManager:
    """Sharded-ownership + replication state of one TSDB node."""

    def __init__(self, tsdb):
        cfg = tsdb.config
        self.tsdb = tsdb
        self.self_id = cfg.get_string("tsd.network.cluster.self").strip()
        if not self.self_id:
            raise ValueError(
                "tsd.network.cluster.shard.enable requires "
                "tsd.network.cluster.self (this node's host:port on "
                "the ring)")
        if not cfg.get_string("tsd.storage.directory"):
            raise ValueError(
                "tsd.network.cluster.shard.enable requires "
                "tsd.storage.directory: replication ships WAL records "
                "and a node without a WAL has nothing to ship or tail")
        from opentsdb_tpu.tsd.cluster import cluster_peers
        self.peers = [p for p in cluster_peers(cfg) if p != self.self_id]
        self.shard_count = max(
            cfg.get_int("tsd.network.cluster.shard.count"), 1)
        self.rf = max(cfg.get_int("tsd.network.cluster.shard.replicas"), 1)
        self.ring = HashRing(
            [self.self_id] + self.peers,
            cfg.get_int("tsd.network.cluster.shard.virtual_nodes"))
        self.preferences = shard_preferences(
            self.ring, self.shard_count, self.rf)
        self.ship_timeout_s = max(
            cfg.get_int("tsd.replication.ship_timeout_ms"), 100) / 1e3
        self.pull_interval_s = max(
            cfg.get_int("tsd.replication.pull_interval_ms"), 20) / 1e3
        self.tail_batch_bytes = max(
            cfg.get_int("tsd.replication.tail_batch_mb"), 1) * 2 ** 20
        self.max_inflight_bytes = max(
            cfg.get_int("tsd.replication.max_inflight_mb"), 1) * 2 ** 20
        self._lock = threading.Lock()
        # origin node id -> _Origin apply state  # guarded-by: _lock
        self._origins: dict[str, _Origin] = {}
        # own per-shard chains over records THIS node originated
        # (shard -> (count, chain))  # guarded-by: _lock
        self._own_chains: dict[int, tuple[int, int]] = {}
        # replica ack positions in OUR stream (ship acks + tail since
        # marks)  # guarded-by: _lock
        self._peer_positions: dict[str, int] = {}
        self.epoch = 0  # guarded-by: _lock
        self._cover_fp = None  # guarded-by: _lock
        self._inflight_bytes = 0  # guarded-by: _lock
        # ship must stay seq-ordered per replica: one lock per peer
        # serializes the synchronous POSTs  # guarded-by: _lock
        self._ship_locks: dict[str, threading.Lock] = {}
        # one drain at a time per origin: the contiguity pop is per-seq
        # atomic under _lock, but the rr JOURNAL writes happen outside
        # it, and two interleaved drains (ship handler + puller) could
        # journal rr records out of seq order — which restore_applied's
        # duplicate guard would then mis-skip on replay
        # guarded-by: _lock
        self._drain_locks: dict[str, threading.Lock] = {}
        # set False only during an explicit catch_up() window (server
        # startup): while catching up this node routes even its owned
        # writes to the failover member  # guarded-by: _lock
        self.ready = True
        self._puller: threading.Thread | None = None
        self._stop = threading.Event()
        self._m_ship = REGISTRY.counter(
            "tsd.replication.ship.records",
            "WAL records synchronously shipped to a replica on the "
            "ingest ack path, by replica peer")
        self._m_ship_err = REGISTRY.counter(
            "tsd.replication.ship.errors",
            "Synchronous ship attempts that failed (the pull cadence "
            "fills the gap), by replica peer")
        self._m_tail_req = REGISTRY.counter(
            "tsd.replication.tail.requests",
            "/api/replication/tail pages served to catching-up peers")
        self._m_tail_rec = REGISTRY.counter(
            "tsd.replication.tail.records",
            "WAL records served through /api/replication/tail")
        self._m_catch_up = REGISTRY.counter(
            "tsd.replication.catch_up.records",
            "Peer WAL records applied from pulled tails (the catch-up "
            "path), by origin peer")
        self._m_forwarded = REGISTRY.counter(
            "tsd.replication.forwarded",
            "Ingest writes forwarded to the owning node, by "
            "destination peer")
        self._m_divergence = REGISTRY.counter(
            "tsd.replication.divergence",
            "Anti-entropy chain divergences detected (position reset "
            "to the last agreed record + re-pull), by peer")
        self._m_rejected = REGISTRY.counter(
            "tsd.replication.inflight_rejected",
            "Replication ship/tail requests refused by the "
            "tsd.replication.max_inflight_mb byte gate (503; the "
            "sender falls back to the pull cadence)")

    # ---------------------------------------------------------------- #
    # Identity / topology                                               #
    # ---------------------------------------------------------------- #

    def shard_of(self, metric: str, tags) -> int:
        return series_shard(metric, tags, self.shard_count)

    def current_epoch(self) -> int:
        with self._lock:
            return self.epoch

    def members(self, shard: int) -> list[str]:
        return self.preferences[shard]

    def _breaker_state(self):
        from opentsdb_tpu.tsd.cluster import _state
        return _state(self.tsdb)

    def _healthy(self, node: str) -> bool:
        if node == self.self_id:
            with self._lock:
                return self.ready
        from opentsdb_tpu.tsd.cluster import CircuitBreaker
        b = self._breaker_state().breaker(node)
        return b.state != CircuitBreaker.OPEN

    def owned_shards(self) -> set[int]:
        return {s for s, pref in enumerate(self.preferences)
                if pref and pref[0] == self.self_id}

    def replicated_shards(self) -> set[int]:
        """Shards this node holds a copy of (owner or replica)."""
        return {s for s, pref in enumerate(self.preferences)
                if self.self_id in pref}

    # ---------------------------------------------------------------- #
    # Ingest routing                                                    #
    # ---------------------------------------------------------------- #

    def should_route(self) -> bool:
        """False inside a routed request or a replication apply: the
        record has already been placed; re-forwarding would loop."""
        return not getattr(_INGEST_CTX, "accepting", False)

    class _Accepting:
        def __enter__(self):
            self.prev = getattr(_INGEST_CTX, "accepting", False)
            _INGEST_CTX.accepting = True
            return self

        def __exit__(self, *exc):
            _INGEST_CTX.accepting = self.prev

    @staticmethod
    def accepting():
        """Context marking this thread's ingest as already routed
        (a forwarded put or a replication apply)."""
        return ReplicationManager._Accepting()

    @staticmethod
    def is_routed_request(http_query) -> bool:
        return bool(http_query.request.headers.get(ROUTED_HEADER))

    def route_point(self, metric, timestamp, value, tags) -> bool:
        """True when the point was forwarded to its accepting member
        (nothing to do locally); False when THIS node accepts it."""
        shard = self.shard_of(metric, tags)
        return self._route_group(shard, [
            {"metric": metric, "timestamp": timestamp,
             "value": value, "tags": dict(tags)}])

    class RoutedRejection(ValueError):
        """The accepting member answered 400: the VALID points in the
        body were stored, the rest rejected — ``errors`` maps the
        rejected indexes (into the forwarded group) to their reason so
        bulk callers don't report stored points as failed."""

        def __init__(self, node: str, errors: dict[int, str]):
            super().__init__(
                "owning node %s rejected %d routed point(s): %s"
                % (node, len(errors),
                   next(iter(errors.values()), "")))
            self.node = node
            self.errors = errors

    @staticmethod
    def _rejected_indexes(dps: list[dict], body: bytes
                          ) -> dict[int, str] | None:
        """Map a ?details 400 body's errored datapoints back to their
        indexes in the forwarded group (None: body unparseable, treat
        the whole group as rejected)."""
        try:
            errors = json.loads(body.decode("utf-8"))["errors"]
            out: dict[int, str] = {}
            used: set[int] = set()
            for err in errors:
                dp = err.get("datapoint")
                for i, mine in enumerate(dps):
                    if i not in used and mine == dp:
                        out[i] = str(err.get("error"))
                        used.add(i)
                        break
                else:
                    return None     # unmatchable error: be conservative
            return out
        except (ValueError, KeyError, TypeError):
            return None

    def _route_group(self, shard: int, dps: list[dict]) -> bool:
        """Walk the shard's preference list in order: forward to the
        first healthy REMOTE member before reaching self; accept
        locally (return False) when self comes first, when a remote
        attempt falls through to self, or — last resort — when every
        remote member is down but self holds a copy.  Raises only when
        this node holds no copy and nobody answers: the client must
        see the refusal, not a silent drop."""
        state = self._breaker_state()
        last_err: Exception | None = None
        pref = self.preferences[shard]
        for node in pref:
            if node == self.self_id:
                if self._healthy(node):
                    return False        # this node accepts
                continue                # catching up: prefer a peer
            breaker = state.breaker(node)
            if not breaker.allow():
                continue
            try:
                req = urllib.request.Request(
                    "http://%s/api/put?details" % node,
                    data=json.dumps(dps).encode("utf-8"),
                    headers={"Content-Type": "application/json",
                             "X-TSDB-Replication": "routed"},
                    method="POST")
                with urllib.request.urlopen(
                        req, timeout=self._request_timeout_s()) as resp:
                    resp.read()
                breaker.record_success()
                self._m_forwarded.labels(peer=node).inc()
                return True
            except urllib.error.HTTPError as e:
                if 400 <= e.code < 500:
                    # the member answered: routing worked, SOME payload
                    # was rejected (bad point) — surface exactly which,
                    # don't failover (the valid points were stored)
                    breaker.record_success()
                    self._m_forwarded.labels(peer=node).inc()
                    rejected = self._rejected_indexes(dps, e.read())
                    if rejected is None:
                        rejected = {
                            i: "owning node %s rejected the routed "
                               "write: HTTP %d" % (node, e.code)
                            for i in range(len(dps))}
                    raise self.RoutedRejection(node, rejected) from e
                # 5xx: the member is unwell (journal failure, inflight
                # gate) — charge the breaker and walk to the next
                # preference member like any other transport failure
                breaker.record_failure()
                last_err = e
                continue
            except Exception as e:
                breaker.record_failure()
                last_err = e
                continue
        if self.self_id in pref:
            return False                # last resort: local copy
        raise ConnectionError(
            "no member of shard %d accepted the routed write "
            "(preference %s): %s" % (shard, pref, last_err))

    def ingest_bulk(self, dps: list[dict]
                    ) -> tuple[int, list[tuple[int, Exception]]]:
        """The sharded half of TSDB.add_points_bulk: partition the body
        by shard, forward each remotely-owned group in one POST, apply
        locally-accepted groups per shard (one WAL record + ship per
        shard group).  Index mapping back into ``dps`` is preserved."""
        by_shard: dict[int, list[int]] = {}
        errors: list[tuple[int, Exception]] = []
        forwarding = self.should_route()
        for i, dp in enumerate(dps):
            try:
                metric = dp["metric"]
                tags = dict(dp["tags"])
            except (KeyError, TypeError):
                # malformed point: let the local validation path report
                # the same error it reports today
                by_shard.setdefault(-1, []).append(i)
                continue
            by_shard.setdefault(self.shard_of(metric, tags), []).append(i)
        success = 0
        for shard, idxs in sorted(by_shard.items()):
            group = [dps[i] for i in idxs]
            if shard >= 0 and forwarding:
                try:
                    if self._route_group(shard, group):
                        success += len(idxs)
                        continue
                except self.RoutedRejection as e:
                    # the member stored the valid points: only the
                    # rejected ones are errors (a retry of the "failed"
                    # set must not re-send stored points)
                    success += len(idxs) - len(e.errors)
                    errors.extend((idxs[j], ValueError(msg))
                                  for j, msg in sorted(e.errors.items()))
                    continue
                except Exception as e:
                    errors.extend((i, e) for i in idxs)
                    continue
            s, errs = self.tsdb._add_points_bulk_local(
                group, shard=shard if shard >= 0 else None)
            success += s
            errors.extend((idxs[j], e) for j, e in errs)
        errors.sort(key=lambda t: t[0])
        return success, errors

    # ---------------------------------------------------------------- #
    # Owner side: commit + synchronous ship                             #
    # ---------------------------------------------------------------- #

    def on_committed(self, entries: list[tuple[int, int, int, dict]]
                     ) -> None:
        """Called after locally-accepted records are applied and
        journaled: fold them into this node's own chains, then ship
        them synchronously to every healthy replica of their shards —
        the ack path's durability step."""
        with self._lock:
            for seq, crc, shard, _rec in entries:
                count, chain = self._own_chains.get(shard, (0, 0))
                self._own_chains[shard] = (count + 1,
                                           _chain_next(chain, crc))
        by_peer: dict[str, list[tuple[int, int, int, dict]]] = {}
        for entry in entries:
            for node in self.members(entry[2]):
                if node != self.self_id:
                    by_peer.setdefault(node, []).append(entry)
        for node, group in by_peer.items():
            self._ship(node, group)                  # order-event: replica-ship

    def _request_timeout_s(self) -> float:
        """The bound for one synchronous replication HTTP call: the
        configured ship timeout, clamped to the ambient request
        deadline's remainder when one is active.  The ack-path ship
        (`on_committed` -> `_ship`) and the routed-ingest forward run
        INSIDE the client's put request — they must never outlive the
        deadline that request is served under.  Background callers
        (the puller cadence) see no ambient deadline and keep the
        plain config bound."""
        timeout_s = self.ship_timeout_s
        dl = active_deadline()
        if dl is not None and dl.bounded:
            timeout_s = min(timeout_s, max(dl.remaining_ms() / 1e3, 0.05))
        return timeout_s

    def _ship_lock(self, peer: str) -> threading.Lock:
        with self._lock:
            lock = self._ship_locks.get(peer)
            if lock is None:
                lock = self._ship_locks[peer] = threading.Lock()
            return lock

    def _drain_lock(self, origin: str) -> threading.Lock:
        with self._lock:
            lock = self._drain_locks.get(origin)
            if lock is None:
                lock = self._drain_locks[origin] = threading.Lock()
            return lock

    def _ship(self, peer: str, entries: list[tuple[int, int, int, dict]]
              ) -> None:
        """Synchronous best-effort ship.  A failure is counted and left
        to the pull cadence (the replica's tail poll) — the write has
        already journaled locally, so this never fails the client."""
        state = self._breaker_state()
        breaker = state.breaker(peer)
        if not breaker.allow():
            self._m_ship_err.labels(peer=peer).inc()
            return
        records = [[seq, crc,
                    json.dumps(rec, separators=(",", ":"))]
                   for seq, crc, _shard, rec in entries]
        body = json.dumps({"from": self.self_id,
                           "records": records}).encode("utf-8")
        try:
            faults.check("replication.ship", peer=peer)
            with self._ship_lock(peer):
                req = urllib.request.Request(
                    "http://%s/api/replication/ship" % peer,
                    data=body,
                    headers={"Content-Type": "application/json"},
                    method="POST")
                with urllib.request.urlopen(
                        req, timeout=self._request_timeout_s()) as resp:
                    ack = json.loads(resp.read().decode("utf-8"))
            breaker.record_success()
            self._m_ship.labels(peer=peer).inc(len(records))
            with self._lock:
                self._peer_positions[peer] = max(
                    self._peer_positions.get(peer, 0),
                    int(ack.get("applied", 0)))
        except Exception as e:
            breaker.record_failure()
            self._m_ship_err.labels(peer=peer).inc()
            LOG.warning("replication ship to %s failed (%d records; "
                        "the pull cadence will fill the gap): %s",
                        peer, len(records), e)

    # ---------------------------------------------------------------- #
    # Apply side: ship receipt, tail pulls, WAL restore                 #
    # ---------------------------------------------------------------- #

    def _origin_locked(self, node: str) -> _Origin:
        o = self._origins.get(node)
        if o is None:
            o = self._origins[node] = _Origin()
        return o

    def receive(self, origin: str, records: list, applied_now: bool,
                counter=None) -> int:
        """Stash framed records from ``origin`` (a ship POST or a
        pulled tail page) and drain the contiguous prefix.  Returns the
        origin's contiguous position after the drain."""
        from opentsdb_tpu.storage.persist import record_crc
        verified = []
        for seq, crc, payload in records:
            if record_crc(payload) != int(crc):
                # a corrupt record must not enter the stream: stop at
                # the last valid one (the sender's replay will heal its
                # own tail; we re-pull)
                LOG.error("replication: CRC mismatch on record %s from "
                          "%s; dropping the rest of the page", seq,
                          origin)
                break
            verified.append((int(seq), int(crc), payload))
        if not verified:
            with self._lock:
                return self._origin_locked(origin).pos
        mine = self.replicated_shards()
        applied = 0
        for seq, crc, payload in verified:
            rec = json.loads(payload)
            shard = rec.get("sh")
            if rec.get("k") == "rr":
                # a record the ORIGIN itself replicated from a third
                # node: it keeps its slot in the origin's seq stream
                # (the contiguity drain must step over it) but is never
                # applied or chained here — each pair of nodes pulls
                # the true origin directly
                shard = None
            responsible = shard is not None and shard in mine
            with self._lock:
                o = self._origin_locked(origin)
                if seq <= o.pos or seq in o.pending:
                    continue            # duplicate delivery
                do_apply = applied_now and responsible
                o.pending[seq] = (crc, shard, payload,
                                  do_apply or not responsible)
            if applied_now and responsible:
                self._apply(rec)
                applied += 1
        drained = self._drain(origin, mine)
        applied += drained
        if counter is not None and applied:
            counter.inc(applied)
        with self._lock:
            return self._origin_locked(origin).pos

    def _apply(self, rec: dict) -> None:
        from opentsdb_tpu.storage.persist import apply_record
        tsdb = self.tsdb
        with self.accepting():
            tsdb._replay_tls.on = True
            try:
                apply_record(tsdb, rec)
            finally:
                tsdb._replay_tls.on = False

    def _drain(self, origin: str, mine: set[int]) -> int:
        """Advance the origin's contiguous position through the stash:
        apply what still needs applying, fold chains in seq order,
        journal the ``rr`` wrapper so a restart restores position.
        One drain at a time per origin (``_drain_lock``): the rr
        journal writes must land in seq order or replay's duplicate
        guard would skip the lower-seq record."""
        with self._drain_lock(origin):
            return self._drain_contiguous(origin, mine)

    def _drain_contiguous(self, origin: str, mine: set[int]) -> int:
        applied = 0
        while True:
            with self._lock:
                o = self._origin_locked(origin)
                nxt = o.pos + 1
                entry = o.pending.pop(nxt, None)
                if entry is None:
                    return applied
                crc, shard, payload, already = entry
                o.pos = nxt
                if shard is not None and shard in mine:
                    count, chain = o.chains.get(shard, (0, 0))
                    o.chains[shard] = (count + 1,
                                       _chain_next(chain, crc))
            rec = None
            if not already and shard is not None and shard in mine:
                rec = json.loads(payload)
                self._apply(rec)
                applied += 1
            if shard is not None and shard in mine \
                    and self.tsdb.persistence is not None:
                if rec is None:
                    rec = json.loads(payload)
                with self.accepting():
                    self.tsdb.persistence.journal(
                        {"k": "rr", "o": origin, "q": nxt, "c": crc,
                         "sh": shard, "r": rec})

    def restore_applied(self, origin: str, seq: int, crc: int,
                        shard, rec: dict) -> None:
        """WAL-replay hook for journaled ``rr`` records: re-apply the
        peer's record and rebuild the per-origin position + chain
        (persist.apply_record dispatches here)."""
        from opentsdb_tpu.storage.persist import apply_record
        with self._lock:
            o = self._origin_locked(origin)
            if int(seq) <= o.pos:
                return      # duplicate rr (post-divergence re-pull):
                #             already applied and folded this replay
        apply_record(self.tsdb, rec)     # caller owns _replaying
        with self._lock:
            o = self._origin_locked(origin)
            o.pos = max(o.pos, int(seq))
            if shard is not None:
                count, chain = o.chains.get(int(shard), (0, 0))
                o.chains[int(shard)] = (count + 1,
                                        _chain_next(chain, int(crc)))

    def note_local_replayed(self, seq: int, crc: int, shard) -> None:
        """WAL-replay hook for this node's own framed records: rebuild
        the own-origin chains the ship path maintains live."""
        if shard is None:
            return
        with self._lock:
            count, chain = self._own_chains.get(int(shard), (0, 0))
            self._own_chains[int(shard)] = (count + 1,
                                            _chain_next(chain, int(crc)))

    # ---------------------------------------------------------------- #
    # Pull cadence / catch-up                                           #
    # ---------------------------------------------------------------- #

    def pull_from(self, peer: str) -> tuple[int, int]:
        """One tail page from ``peer``.  Returns (applied position,
        peer's lastSeq)."""
        faults.check("replication.tail", peer=peer)
        with self._lock:
            since = self._origin_locked(peer).pos
        url = ("http://%s/api/replication/tail?since=%d&node=%s"
               % (peer, since, urllib.parse.quote(self.self_id)))
        req = urllib.request.Request(url, method="GET")
        with urllib.request.urlopen(
                req, timeout=self._request_timeout_s()) as resp:
            page = json.loads(resp.read().decode("utf-8"))
        records = page.get("records") or []
        first = int(page.get("firstSeq", 1))
        if first > since + 1:
            self._fast_forward(peer, first)
        pos = self.receive(peer, records, applied_now=False,
                           counter=self._m_catch_up.labels(peer=peer))
        return pos, int(page.get("lastSeq", 0))

    def _fast_forward(self, peer: str, first: int) -> None:
        """The origin snapshotted: seqs below ``first`` now live only in
        its snapshot, never its tail, so waiting for them would stall
        the contiguity drain forever (fresh replicas and post-divergence
        resets both start at position 0).  Advance the position —
        stashed records below the mark drain NOW (chain fold + ``rr``
        journal + any deferred apply): they were delivered, only their
        predecessors' seq slots weren't."""
        mine = self.replicated_shards()
        with self._drain_lock(peer):
            self._fast_forward_drains_held(peer, first, mine)

    def _fast_forward_drains_held(self, peer: str, first: int,
                                  mine: set[int]) -> None:
        flush: list[tuple[int, int, int, str, bool]] = []
        with self._lock:
            o = self._origin_locked(peer)
            if o.pos >= first - 1:
                return
            LOG.warning(
                "replication: origin %s's WAL starts at seq %d "
                "(snapshot reset); fast-forwarding position %d -> %d — "
                "earlier records live only in its snapshot/store, not "
                "its tail", peer, first, o.pos, first - 1)
            for seq in sorted(s for s in o.pending if s < first):
                crc, shard, payload, already = o.pending.pop(seq)
                if shard is not None and shard in mine:
                    count, chain = o.chains.get(shard, (0, 0))
                    o.chains[shard] = (count + 1,
                                       _chain_next(chain, crc))
                    flush.append((seq, crc, shard, payload, already))
            o.pos = first - 1
        for seq, crc, shard, payload, already in flush:
            rec = json.loads(payload)
            if not already:
                self._apply(rec)
            if self.tsdb.persistence is not None:
                with self.accepting():
                    self.tsdb.persistence.journal(
                        {"k": "rr", "o": peer, "q": seq, "c": crc,
                         "sh": shard, "r": rec})

    def pull_once(self) -> None:
        """One pull round over every peer (the puller-thread body; also
        what tests drive directly for determinism)."""
        state = self._breaker_state()
        for peer in self.peers:
            breaker = state.breaker(peer)
            if not breaker.allow():
                continue
            try:
                self.pull_from(peer)
                breaker.record_success()
            except Exception as e:
                breaker.record_failure()
                LOG.debug("replication pull from %s failed: %s", peer, e)

    def verify_once(self) -> None:
        """One anti-entropy round over every reachable peer (the
        standing production caller of verify_with — every
        VERIFY_EVERY-th pull round; tests drive verify_with directly
        for determinism)."""
        state = self._breaker_state()
        for peer in self.peers:
            if not state.breaker(peer).allow():
                continue
            try:
                self.verify_with(peer)
            except Exception as e:
                LOG.debug("anti-entropy pass against %s failed: %s",
                          peer, e)

    # order: catch-up-pull before rejoin-ready
    def catch_up(self, max_rounds: int = 64) -> None:
        """Rejoin protocol: pull every reachable peer's tail until this
        node reaches their last sequence numbers, THEN mark ready (and
        with it, re-accept ownership).  Unreachable peers don't block —
        a full cluster cold start must come up.  The pull-before-ready
        ordering is a checked contract (tools/lint/ordering.py)."""
        with self._lock:
            self.ready = False
        try:
            for _ in range(max_rounds):
                behind = False
                for peer in self.peers:
                    try:
                        pos, last = self.pull_from(peer)  # order-event: catch-up-pull
                        if pos < last:
                            behind = True
                    except Exception as e:
                        LOG.warning("catch-up: peer %s unreachable "
                                    "(%s); proceeding without it",
                                    peer, e)
                if not behind:
                    break
        finally:
            with self._lock:
                self.ready = True                    # order-event: rejoin-ready
        self._record_epoch_event("catch_up_complete")

    # pull rounds between anti-entropy passes: cheap (one status GET +
    # chain compare per peer) but pointless at every round
    VERIFY_EVERY = 8

    def start_puller(self) -> None:
        def loop():
            rounds = 0
            while not self._stop.wait(self.pull_interval_s):
                try:
                    self.pull_once()
                    rounds += 1
                    if rounds % self.VERIFY_EVERY == 0:
                        self.verify_once()
                except Exception:
                    LOG.exception("replication pull round failed")

        with self._lock:
            if self._puller is not None:
                return
            self._stop.clear()
            t = threading.Thread(
                target=loop, name="replication-puller", daemon=True)
            self._puller = t
        t.start()

    def stop_puller(self) -> None:
        self._stop.set()
        with self._lock:
            t, self._puller = self._puller, None
        if t is not None:
            t.join(5)

    # ---------------------------------------------------------------- #
    # Query-side cover                                                  #
    # ---------------------------------------------------------------- #

    def query_plan(self) -> tuple[dict[str, set[int]], set[int]]:
        """The executor's shard cover (and EXPLAIN's — plan_cover is
        the shared pure function).  Bumps the ownership epoch and logs
        a flight-recorder event when the assignment changed."""
        cover, uncovered = plan_cover(self.preferences, self._healthy)
        fp = tuple(sorted((n, tuple(sorted(s))) for n, s in
                          cover.items()))
        bumped = None
        with self._lock:
            if fp != self._cover_fp:
                self._cover_fp = fp
                self.epoch += 1
                bumped = self.epoch
        if bumped is not None:
            self._record_epoch_event(
                "cover_change",
                cover={n: len(s) for n, s in cover.items()},
                uncovered=len(uncovered))
        return cover, uncovered

    def next_member(self, shard: int, exclude: set[str]) -> str | None:
        """Failover refetch target: the first healthy preference member
        outside ``exclude`` (nodes that already failed this query)."""
        for node in self.preferences[shard]:
            if node not in exclude and self._healthy(node):
                return node
        return None

    def _record_epoch_event(self, reason: str, **fields) -> None:
        recorder = getattr(self.tsdb, "flightrec", None)
        if recorder is None:
            return
        with self._lock:
            epoch = self.epoch
        recorder.record("replication", reason=reason, epoch=epoch,
                        node=self.self_id, **fields)

    # ---------------------------------------------------------------- #
    # Anti-entropy / status                                             #
    # ---------------------------------------------------------------- #

    def status(self) -> dict:
        persistence = self.tsdb.persistence
        with self._lock:
            chains = {self.self_id: {
                str(s): [c, "%08x" % h]
                for s, (c, h) in sorted(self._own_chains.items())}}
            for origin, o in self._origins.items():
                chains[origin] = {
                    str(s): [c, "%08x" % h]
                    for s, (c, h) in sorted(o.chains.items())}
            positions = {origin: o.pos
                         for origin, o in self._origins.items()}
            epoch = self.epoch
            ready = self.ready
        return {
            "node": self.self_id,
            "epoch": epoch,
            "ready": ready,
            "rf": self.rf,
            "shardCount": self.shard_count,
            "lastSeq": persistence.last_seq if persistence is not None
            else 0,
            "positions": positions,
            "chains": chains,
        }

    def verify_with(self, peer: str) -> list[int]:
        """Anti-entropy pass against one peer: compare per-shard CRC
        chains for every origin both sides track.  A divergence resets
        this node's position for that origin to the last agreed record
        — 0, since chains are cumulative — and lets the pull cadence
        rebuild the tail (re-applied records are idempotent under
        fix_duplicates).  Returns the divergent shard ids."""
        url = "http://%s/api/replication/status" % peer
        req = urllib.request.Request(url, method="GET")
        with urllib.request.urlopen(
                req, timeout=self._request_timeout_s()) as resp:
            theirs = json.loads(resp.read().decode("utf-8"))
        divergent: list[int] = []
        their_chains = theirs.get("chains") or {}
        mine = self.status()["chains"]
        for origin, my_shards in mine.items():
            other = their_chains.get(origin)
            if other is None:
                continue
            for shard_s, (count, chain) in my_shards.items():
                pair = other.get(shard_s)
                if pair is None:
                    continue
                o_count, o_chain = pair
                if int(o_count) == count and o_chain != chain:
                    divergent.append(int(shard_s))
        if divergent:
            self._m_divergence.labels(peer=peer).inc(len(divergent))
            LOG.error(
                "replication anti-entropy: chain divergence with %s on "
                "shard(s) %s; truncating to the last agreed record and "
                "re-pulling", peer, sorted(set(divergent)))
            with self._lock:
                o = self._origins.get(peer)
                if o is not None:
                    o.pos = 0
                    o.pending.clear()
                    # the re-pull re-drains the whole stream: every
                    # chain for this origin rebuilds from zero
                    o.chains.clear()
        return sorted(set(divergent))

    # ---------------------------------------------------------------- #
    # Inflight byte gate (the admission exemption's own bound)          #
    # ---------------------------------------------------------------- #

    class _Inflight:
        def __init__(self, mgr, nbytes: int):
            self.mgr = mgr
            self.nbytes = nbytes

        def __enter__(self):
            mgr = self.mgr
            with mgr._lock:
                if mgr._inflight_bytes + self.nbytes \
                        > mgr.max_inflight_bytes:
                    mgr._m_rejected.inc()
                    raise BadRequestError(
                        "replication inflight byte budget exhausted",
                        status=503,
                        details="tsd.replication.max_inflight_mb")
                mgr._inflight_bytes += self.nbytes
            return self

        def __exit__(self, *exc):
            with self.mgr._lock:
                self.mgr._inflight_bytes -= self.nbytes

    def bounded(self, nbytes: int) -> "_Inflight":
        return self._Inflight(self, nbytes)

    # ---------------------------------------------------------------- #
    # Health / stats                                                    #
    # ---------------------------------------------------------------- #

    def health_snapshot(self) -> dict:
        """The health engine's view (obs/health.py eighth invariant):
        under-replicated shard count + the worst replica's backlog in
        OUR stream."""
        under = 0
        for pref in self.preferences:
            healthy = sum(1 for n in pref if self._healthy(n))
            if healthy < min(self.rf, len(self.ring.nodes)):
                under += 1
        last = self.tsdb.persistence.last_seq \
            if self.tsdb.persistence is not None else 0
        with self._lock:
            positions = dict(self._peer_positions)
            epoch = self.epoch
        lag = 0
        if self.rf > 1 and self.peers:
            acked = [positions.get(p, 0) for p in self.peers
                     if any(p in pref and pref[0] == self.self_id
                            for pref in self.preferences)]
            if acked:
                lag = max(last - min(acked), 0)
        return {"under_replicated": under, "lag": lag, "epoch": epoch,
                "last_seq": last}

    def stats_hook(self, collector) -> None:
        snap = self.health_snapshot()
        collector.record("replication.epoch", snap["epoch"])
        collector.record("replication.last_seq", snap["last_seq"])
        collector.record("replication.under_replicated",
                         snap["under_replicated"])
        collector.record("replication.lag", snap["lag"])
        with self._lock:
            positions = dict(self._peer_positions)
        for peer, pos in sorted(positions.items()):
            collector.record("replication.peer_position", pos,
                             "peer=%s" % peer)


# -------------------------------------------------------------------- #
# HTTP surface                                                          #
# -------------------------------------------------------------------- #

class ReplicationRpc:
    """/api/replication/{tail,ship,status} — the WAL-shipping wire.

    Deliberately NOT behind the query admission gate (an overloaded
    query tier shedding work must not sever durability); bounded by the
    manager's own max_inflight_mb byte gate instead."""

    def execute_http(self, tsdb, query: HttpQuery) -> None:
        mgr = getattr(tsdb, "replication", None)
        if mgr is None:
            raise BadRequestError(
                "Sharded replication is disabled", status=404,
                details="Set tsd.network.cluster.shard.enable=true")
        sub = query.api_subpath()
        endpoint = sub[0] if sub else ""
        if endpoint == "tail":
            return self._tail(tsdb, mgr, query)
        if endpoint == "ship":
            return self._ship(mgr, query)
        if endpoint == "status":
            query.send_reply(mgr.status())
            return
        raise BadRequestError(
            "Unknown replication endpoint %r" % endpoint, status=404)

    @staticmethod
    def _tail(tsdb, mgr: ReplicationManager, query: HttpQuery) -> None:
        if query.method != "GET":
            raise BadRequestError("tail is GET-only", status=405)
        since_raw = query.get_query_string_param("since") or "0"
        try:
            since = max(int(since_raw), 0)
        except ValueError:
            raise BadRequestError("since must be an integer")
        persistence = tsdb.persistence
        if persistence is None:
            raise BadRequestError(
                "no WAL on this node (tsd.storage.directory unset)",
                status=404)
        with mgr.bounded(mgr.tail_batch_bytes):
            records, last_seq, first_seq = persistence.read_since(
                since, max_bytes=mgr.tail_batch_bytes)
            # "rr" wrappers (records this node merely replicated) ride
            # along as skip markers: the puller advances past their
            # seq slots without applying — dropping them here would
            # leave permanent holes the contiguity drain could never
            # cross.  The true origin serves the real record.
            out = [[seq, crc, payload]
                   for seq, crc, payload in records]
            node = query.get_query_string_param("node")
            if node:
                with mgr._lock:
                    mgr._peer_positions[node] = max(
                        mgr._peer_positions.get(node, 0), since)
            mgr._m_tail_req.inc()
            if out:
                mgr._m_tail_rec.inc(len(out))
            query.send_reply({"node": mgr.self_id,
                              "epoch": mgr.current_epoch(),
                              "lastSeq": last_seq,
                              "firstSeq": first_seq,
                              "records": out})

    @staticmethod
    def _ship(mgr: ReplicationManager, query: HttpQuery) -> None:
        if query.method != "POST":
            raise BadRequestError("ship is POST-only", status=405)
        body = query.request.body or b""
        with mgr.bounded(len(body)):
            try:
                payload = json.loads(body.decode("utf-8"))
                origin = payload["from"]
                records = payload["records"]
            except (ValueError, KeyError, TypeError) as e:
                raise BadRequestError("malformed ship body: %s" % e)
            pos = mgr.receive(origin, records, applied_now=True)
            query.send_reply({"node": mgr.self_id, "applied": pos})
