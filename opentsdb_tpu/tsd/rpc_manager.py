"""RPC route table: command/path -> handler, per operation mode.

Reference behavior: /root/reference/src/tsd/RpcManager.java (:251-364
initializeBuiltinRpcs — the authoritative route list per READWRITE/READONLY/
WRITEONLY mode with tsd.core.enable_api / enable_ui / no_diediedie gates)
and RpcHandler.java dispatch.
"""

from __future__ import annotations

import logging
import math
import threading
import time

from opentsdb_tpu.obs import latattr
from opentsdb_tpu.obs import trace as obs_trace
from opentsdb_tpu.obs.registry import REGISTRY
from opentsdb_tpu.query import limits
from opentsdb_tpu.stats.query_stats import QueryStatsRegistry
from opentsdb_tpu.tsd import admin_rpcs, rpcs
from opentsdb_tpu.tsd.admission import DEADLINE_HEADER
from opentsdb_tpu.tsd.http import (BadRequestError, HttpQuery, HttpRequest,
                                   error_status)
from opentsdb_tpu.tsd.serializers import serializer_for

LOG = logging.getLogger("tsd.rpc")


class RpcManager:
    """Builds and owns the telnet + HTTP route tables."""

    def __init__(self, tsdb, server=None, shutdown_cb=None):
        self.tsdb = tsdb
        self.server = server
        self.shutdown_cb = shutdown_cb or (lambda: None)
        self.query_stats = QueryStatsRegistry()
        self.telnet_commands: dict[str, rpcs.TelnetRpc] = {}
        self.http_commands: dict[str, rpcs.HttpRpc] = {}
        self._initialize_builtin_rpcs()
        self.telnet_plugins: dict[str, rpcs.TelnetRpc] = {}
        self.http_plugins: dict[str, rpcs.HttpRpc] = {}
        # error-envelope accounting (surfaced as http.errors by
        # /api/stats): handler failures must leave an operator-visible
        # trail, not just a client-side status code
        self._err_lock = threading.Lock()
        # guarded-by: _err_lock
        self.client_errors = 0          # 4xx envelopes sent
        self.server_errors = 0          # 5xx envelopes sent  # guarded-by: _err_lock
        # register as a stats source on the TSDB so the self-report
        # loop (obs/selfreport.py) sees the same ingest/error counters
        # /api/stats serves; keyed so a replacement manager supersedes
        if not hasattr(tsdb, "stats_hooks"):
            tsdb.stats_hooks = {}
        tsdb.stats_hooks["rpc_manager"] = self._stats_hook

    def _count_error(self, status: int) -> None:
        with self._err_lock:
            if status >= 500:
                self.server_errors += 1
            else:
                self.client_errors += 1

    def collect_stats(self, collector) -> None:
        with self._err_lock:
            client, server = self.client_errors, self.server_errors
        collector.record("http.errors", client, "family=4xx")
        collector.record("http.errors", server, "family=5xx")

    def _stats_hook(self, collector) -> None:
        """The self-report view of this manager: ingest RPC counters,
        error envelopes, and the server's connection stats — exactly
        what StatsRpc folds in for /api/stats."""
        for rpc in self.ingest_rpcs:
            rpc.collect_stats(collector)
        self.collect_stats(collector)
        if self.server is not None:
            self.server.collect_stats(collector)

    def _initialize_builtin_rpcs(self) -> None:
        cfg = self.tsdb.config
        mode = self.tsdb.mode             # rw / ro / wo
        enable_api = cfg.get_bool("tsd.core.enable_api")
        enable_ui = cfg.get_bool("tsd.core.enable_ui")
        enable_die = not cfg.get_bool("tsd.no_diediedie")

        telnet = self.telnet_commands
        http = self.http_commands

        stats = admin_rpcs.StatsRpc(self.query_stats)
        aggregators = admin_rpcs.ListAggregators()
        dropcaches = admin_rpcs.DropCachesRpc()
        version = admin_rpcs.VersionRpc()

        telnet["stats"] = stats
        telnet["dropcaches"] = dropcaches
        telnet["version"] = version
        telnet["exit"] = admin_rpcs.ExitRpc()
        telnet["help"] = admin_rpcs.HelpRpc(lambda: self.telnet_commands)

        if enable_ui:
            http["aggregators"] = aggregators
            http["logs"] = admin_rpcs.LogsRpc()
            http["stats"] = stats
            http["version"] = version
        if enable_api:
            http["api/aggregators"] = aggregators
            http["api/config"] = admin_rpcs.ShowConfig()
            http["api/dropcaches"] = dropcaches
            http["api/stats"] = stats
            http["api/version"] = version
            http["api/serializers"] = admin_rpcs.SerializersRpc()
            # flight recorder + health engine (obs/flightrec.py,
            # obs/health.py): /api/diag, /api/diag/slow,
            # /api/diag/health — mounted in every mode like /api/stats
            http["api/diag"] = admin_rpcs.DiagRpc()
            if getattr(self.tsdb, "replication", None) is not None:
                # WAL-shipping replication wire (tsd/replication.py):
                # tail/ship/status, mounted in every mode — a ro
                # replica must still accept ships and serve tails.
                # Exempt from the query admission gate; bounded by its
                # own tsd.replication.max_inflight_mb byte gate.
                from opentsdb_tpu.tsd.replication import ReplicationRpc
                http["api/replication"] = ReplicationRpc()

        put = rpcs.PutDataPointRpc()
        rollups = rpcs.RollupDataPointRpc()
        histos = rpcs.HistogramDataPointRpc()
        suggest = rpcs.SuggestRpc()
        annotation = rpcs.AnnotationRpc()
        staticfile = admin_rpcs.StaticFileRpc()
        self.put_rpc = put
        self.ingest_rpcs = [put, rollups, histos]

        writes = mode in ("rw", "wo")
        reads = mode in ("rw", "ro")

        if writes:
            telnet["put"] = put
            telnet["rollup"] = rollups
            telnet["histogram"] = histos
            if enable_api:
                http["api/annotation"] = annotation
                http["api/annotations"] = annotation
                http["api/put"] = put
                http["api/rollup"] = rollups
                http["api/histogram"] = histos
                http["api/tree"] = admin_rpcs.TreeRpc()
                http["api/uid"] = rpcs.UniqueIdRpc()
        if reads:
            if enable_ui:
                http[""] = admin_rpcs.HomePage()
                http["s"] = staticfile
                http["favicon.ico"] = staticfile
                http["suggest"] = suggest
                try:
                    from opentsdb_tpu.tsd.graph import GraphHandler
                    http["q"] = GraphHandler()
                except ImportError:
                    pass
            if enable_api:
                http["api/query"] = rpcs.QueryRpc(self.query_stats)
                http["api/search"] = admin_rpcs.SearchRpc()
                http["api/suggest"] = suggest
                http.setdefault("api/uid", rpcs.UniqueIdRpc())
                http.setdefault("api/annotation", annotation)
                http.setdefault("api/annotations", annotation)

        if enable_die:
            die = admin_rpcs.DieDieDie(self.shutdown_cb)
            telnet["diediedie"] = die
            if enable_ui:
                http["diediedie"] = die

    # -- plugin registration (RpcManager.initializeRpcPlugins analog) --

    def register_telnet_plugin(self, command: str, handler) -> None:
        if command in self.telnet_commands:
            raise ValueError("Duplicate telnet command: %s" % command)
        self.telnet_commands[command] = handler

    def register_http_plugin(self, route: str, handler) -> None:
        route = route.strip("/")
        if route in self.http_plugins:
            raise ValueError("Duplicate HTTP plugin route: %s" % route)
        self.http_plugins[route] = handler

    # -- dispatch (RpcHandler.messageReceived :125) --

    def handle_telnet(self, conn, line: str) -> str | None:
        words = line.split()
        if not words:
            return None
        handler = self.telnet_commands.get(words[0])
        if handler is None:
            return "unknown command: %s.  Try `help'.\n" % words[0]
        return handler.execute_telnet(self.tsdb, conn, words)

    def handle_telnet_batch(self, conn, block: bytes) -> str:
        """Consecutive telnet put lines batched by the server loop.

        Dispatches to the put handler's batch arm (native columnar
        ingest) when one is installed; otherwise — e.g. read-only mode
        drops `put` from the table — each line walks handle_telnet so
        per-line replies ("unknown command: put") stay identical.
        """
        from opentsdb_tpu.tsd.rpcs import PutDataPointRpc
        handler = self.telnet_commands.get("put")
        if type(handler) is PutDataPointRpc:
            return handler.execute_telnet_batch(self.tsdb, conn, block,
                                                self)
        return PutDataPointRpc._telnet_lines_one_by_one(conn, block, self)

    def handle_http(self, request: HttpRequest,
                    remote: str = "unknown") -> "HttpQuery":
        """Trace + metrics envelope around the route dispatch.

        When tsd.trace.enable is on every request gets a span tree
        rooted here; an X-TSDB-Trace-Id header (a peer's fan-out, or
        an operator correlating across TSDs) is adopted as the trace
        id, so one clustered query is one id across every host.

        One request-scoped Deadline is minted here — from
        tsd.query.timeout and/or the client's X-TSDB-Deadline-Ms
        header (whichever is smaller; a coordinating TSD forwards its
        remainder so a peer aborts when the coordinator has already
        given up) — activated as the responder thread's ambient
        deadline (query/limits.py) for every QueryBudget, retry policy,
        and admission wait downstream, and bound to the server's
        cancellation handle so a client disconnect flips its token."""
        cfg = self.tsdb.config
        trace = None
        if cfg.get_bool("tsd.trace.enable"):
            trace = obs_trace.Trace(
                "http", trace_id=request.header(obs_trace.TRACE_HEADER),
                device_time=cfg.get_bool("tsd.trace.device_time"))
            trace.root.tags["method"] = request.method
            trace.root.tags["path"] = request.path
            obs_trace.activate(trace)
        deadline = self._mint_deadline(request)
        limits.activate_deadline(deadline)
        handle = getattr(request, "cancel_handle", None)
        if handle is not None:
            handle.bind(deadline)
        # always-on latency attribution (obs/latattr.py): stamps on
        # EVERY request, independent of tsd.trace.enable — the engine
        # is per-TSDB so library/test managers without one just carry
        # inert ambient stamps
        stamps = None
        if getattr(self.tsdb, "latattr", None) is not None:
            stamps = latattr.PhaseStamps(
                trace_id=trace.trace_id if trace is not None else None)
            latattr.activate(stamps)
        start = time.perf_counter()
        try:
            query = self._dispatch_http(request, remote)
        finally:
            limits.deactivate_deadline()
            if stamps is not None:
                latattr.deactivate()
            if trace is not None:
                obs_trace.deactivate()
                trace.finish()
        # route label clamped to the registered table: client-chosen
        # paths must not mint unbounded label cardinality
        route = query.base_route()
        if route not in self.http_commands:
            route = "other"
        if stamps is not None:
            # the trailing mark absorbs the handler tail (reply
            # buffering, error envelope) so the phase deltas sum to
            # the handler wall time
            stamps.mark("flush")
            stamps.route = route
            self.tsdb.latattr.observe(stamps)
        status = query.response.status if query.response is not None else 0
        REGISTRY.counter(
            "tsd.http.requests", "HTTP requests served").labels(
                route=route, status=str(status)).inc()
        REGISTRY.histogram(
            "tsd.http.latency_ms", "HTTP request latency (ms)").labels(
                route=route).observe(
                    (time.perf_counter() - start) * 1e3,
                    exemplar=trace.trace_id if trace is not None
                    else None)
        return query

    def _mint_deadline(self, request: HttpRequest) -> "limits.Deadline":
        """min(tsd.query.timeout, X-TSDB-Deadline-Ms); 0/absent on both
        sides mints an unbounded deadline — still the cancellation
        token every check site observes."""
        timeout_ms = float(self.tsdb.config.get_int("tsd.query.timeout"))
        raw = request.header(DEADLINE_HEADER)
        if raw:
            try:
                client_ms = float(raw)
            except ValueError:
                client_ms = 0.0
            if not math.isfinite(client_ms):
                # "inf"/"1e309" parse to float inf — a bounded deadline
                # must stay finite (int(remaining) travels to peers)
                client_ms = 0.0
            if client_ms > 0:
                timeout_ms = (min(timeout_ms, client_ms)
                              if timeout_ms > 0 else client_ms)
        return limits.Deadline(max(timeout_ms, 0.0))

    def _dispatch_http(self, request: HttpRequest,
                       remote: str = "unknown") -> "HttpQuery":
        query = HttpQuery(self.tsdb, request, remote)
        if request.method == "OPTIONS":
            # CORS preflight (RpcHandler.java:204-223): 200 + allow headers
            # when the origin is whitelisted, 400 without dispatching
            # otherwise; no-Origin OPTIONS falls through to a 405.
            if self._preflight(query):
                return query
            if query.request.header("origin"):
                self._count_error(400)
                query.send_error(BadRequestError(
                    "CORS domain not allowed",
                    details="Origin is not in tsd.http.request.cors_domains"))
                return query
        auth = self.tsdb.authentication
        if auth is not None:
            # Per-request HTTP auth (AuthenticationChannelHandler HTTP arm).
            from opentsdb_tpu.auth import AuthStatus
            try:
                state = auth.authenticate_http(None, request)
            except Exception:
                LOG.exception("Authentication plugin failed on HTTP "
                              "request from %s; failing closed", remote)
                state = None
            if state is None or state.status != AuthStatus.SUCCESS:
                self._count_error(401)
                query.send_error(BadRequestError(
                    "Authentication failed", status=401))
                return query
            query.auth_state = state
        try:
            query.serializer = serializer_for(query)
            # plugin routes live under /plugin/<route>
            parts = query.path.split("/")
            if parts and parts[0] == "plugin":
                # Longest registered prefix wins (HttpRpcPlugin routes may
                # span several path segments).
                plugin = None
                for depth in range(len(parts) - 1, 0, -1):
                    plugin = self.http_plugins.get("/".join(parts[1:depth + 1]))
                    if plugin is not None:
                        break
                if plugin is None:
                    raise BadRequestError("No plugin at route", status=404)
                plugin.execute_http(self.tsdb, query)
            else:
                handler = self.http_commands.get(query.base_route())
                if handler is None:
                    raise BadRequestError(
                        "Page not found", status=404,
                        details="The requested page [%s] was not found"
                                % request.path)
                handler.execute_http(self.tsdb, query)
            if query.response is None:
                raise RuntimeError("handler sent no response")
        except Exception as e:  # uniform error envelope
            status = error_status(e)
            self._count_error(status)
            recorder = getattr(self.tsdb, "flightrec", None)
            if recorder is not None:
                # deadline expiries/cancellations and 5xx envelopes are
                # flight-recorder events: a wedge's last moments must
                # be reconstructible from the ring alone
                if isinstance(e, limits.QueryDeadlineExpired):
                    recorder.record("deadline", outcome="expired",
                                    path=request.path, status=status)
                elif isinstance(e, limits.QueryCancelledException):
                    recorder.record("deadline", outcome="cancelled",
                                    path=request.path, status=status)
                if status >= 500:
                    recorder.record("http_error", status=status,
                                    path=request.path)
            if status >= 500 and not isinstance(e, limits.QueryException):
                # expected client mistakes (4xx) stay quiet, and so do
                # deliberate 5xx query verdicts (admission sheds,
                # cancellations — they carry their own status and are
                # counted on their own metrics); an internal failure
                # gets the full trace in the daemon log
                LOG.exception("handler for [%s] from %s failed with an "
                              "internal error", request.path, remote)
            query.send_error(e)
        self._apply_cors(query)
        return query

    def _origin_allowed(self, origin: str | None) -> bool:
        if not origin:
            return False
        domains = self.tsdb.config.get_string(
            "tsd.http.request.cors_domains").strip()
        if not domains:
            return False
        allowed = {d.strip().lower() for d in domains.split(",") if d.strip()}
        return "*" in allowed or origin.lower() in allowed

    def _preflight(self, query: HttpQuery) -> bool:
        """OPTIONS preflight; returns True when this produced the response."""
        origin = query.request.header("origin")
        if not self._origin_allowed(origin):
            return False
        query.send_status_only(200)
        self._apply_cors(query)
        return True

    def _apply_cors(self, query: HttpQuery) -> None:
        """tsd.http.request.cors_domains handling (RpcHandler :249-320)."""
        origin = query.request.header("origin")
        if query.response is None or not self._origin_allowed(origin):
            return
        query.response.headers["Access-Control-Allow-Origin"] = origin
        query.response.headers["Access-Control-Allow-Methods"] = \
            "GET, POST, PUT, DELETE"
        headers = self.tsdb.config.get_string(
            "tsd.http.request.cors_headers").strip()
        if headers:
            query.response.headers["Access-Control-Allow-Headers"] = headers
