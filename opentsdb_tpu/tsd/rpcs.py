"""Core RPC handlers: put/rollup/histogram ingest + query + suggest +
annotation + uid endpoints.

Reference behavior: /root/reference/src/tsd/PutDataPointRpc.java (telnet
`put` :129 / POST /api/put :272, processDataPoint :309 with details/summary/
sync modes), RollupDataPointRpc.java (telnet grammar
`rollup interval-agg[:spatial] metric ts value tags` :95-150), QueryRpc.java
(:89 — GET query-string grammar, POST JSON, DELETE, /api/query/last :346),
SuggestRpc.java, AnnotationRpc.java, UniqueIdRpc.java (:63-77).
"""

from __future__ import annotations

import logging
import threading
import time

from opentsdb_tpu.models.tsquery import (
    TSQuery, parse_m_subquery, parse_tsuid_subquery)
from opentsdb_tpu.obs import latattr
from opentsdb_tpu.obs import trace as obs_trace
from opentsdb_tpu.obs.registry import REGISTRY
from opentsdb_tpu.storage.memstore import Annotation
from opentsdb_tpu.tsd import admission
from opentsdb_tpu.tsd.http import BadRequestError, HttpQuery
from opentsdb_tpu.uid import NoSuchUniqueName
from opentsdb_tpu.stats.query_stats import QueryStats, DuplicateQueryException
from opentsdb_tpu.utils import faults

LOG = logging.getLogger("tsd.rpcs")


class TelnetRpc:
    def execute_telnet(self, tsdb, conn, words: list[str]) -> str | None:
        raise NotImplementedError


class HttpRpc:
    def execute_http(self, tsdb, query: HttpQuery) -> None:
        raise NotImplementedError


def allowed_methods(query: HttpQuery, *methods: str) -> None:
    if query.method not in methods:
        raise BadRequestError(
            "Method not allowed", status=405,
            details="The HTTP method [%s] is not permitted for this endpoint"
                    % query.method)


def parse_tags(words: list[str]) -> dict[str, str]:
    """`tag=value` words -> dict (Tags.parse)."""
    tags: dict[str, str] = {}
    for w in words:
        if not w:
            continue
        if "=" not in w:
            raise ValueError("invalid tag: %s" % w)
        k, v = w.split("=", 1)
        if not k or not v:
            raise ValueError("invalid tag: %s" % w)
        if tags.get(k, v) != v:
            raise ValueError("duplicate tag: %s, tags so far: %s" % (w, tags))
        tags[k] = v
    return tags


class PutDataPointRpc(TelnetRpc, HttpRpc):
    """Telnet `put` + POST /api/put."""

    kind = "put"

    def __init__(self):
        # guarded-by: _lock
        self.requests = 0
        self.http_requests = 0  # guarded-by: _lock
        self.hbase_errors = 0  # guarded-by: _lock
        self.invalid_values = 0  # guarded-by: _lock
        self.illegal_arguments = 0  # guarded-by: _lock
        self.unknown_metrics = 0  # guarded-by: _lock
        self.writes_blocked = 0  # guarded-by: _lock
        self._lock = threading.Lock()

    def _count(self, attr: str) -> None:
        with self._lock:
            setattr(self, attr, getattr(self, attr) + 1)

    # -- telnet: put <metric> <ts> <value> <tag=v> [...] --

    def execute_telnet(self, tsdb, conn, words: list[str]) -> str | None:
        self._count("requests")
        try:
            self.import_telnet_point(tsdb, words)
            return None
        except NoSuchUniqueName as e:
            self._count("unknown_metrics")
            return "put: unknown metric: %s\n" % e
        except ValueError as e:
            self._count("illegal_arguments")
            return "put: %s\n" % e
        except Exception as e:
            self._count("hbase_errors")
            return "put: %s: %s\n" % (type(e).__name__, e)

    def import_telnet_point(self, tsdb, words: list[str]) -> None:
        if len(words) < 5:
            raise ValueError("not enough arguments (need least 4, got %d)"
                             % (len(words) - 1))
        metric = words[1]
        if not metric:
            raise ValueError("empty metric name")
        timestamp = parse_telnet_timestamp(words[2])
        value = words[3]
        if not value:
            raise ValueError("empty value")
        tags = parse_tags(words[4:])
        tsdb.add_point(metric, timestamp, value, tags)

    def execute_telnet_batch(self, tsdb, conn, block: bytes,
                             manager) -> str:
        """A block of consecutive telnet put lines in ONE call.

        The native parser lands every clean line columnar
        (TSDB.add_telnet_batch_native); lines it refuses replay through
        the per-line handler individually, so replies keep line order
        and per-line semantics exactly.  Without the native library the
        whole block walks the per-line path.
        """
        native = None
        if type(self).import_telnet_point \
                is PutDataPointRpc.import_telnet_point:
            native = tsdb.add_telnet_batch_native(block)
        if native is None:
            return self._telnet_lines_one_by_one(conn, block, manager)
        from opentsdb_tpu.storage.native_engine import LINE_FALLBACK
        tb, point_errors = native
        out: list[str] = []
        # tally counters locally: one lock round-trip per BATCH, not per
        # line (the per-line lock is exactly the overhead batching kills)
        requests = unknown = illegal = storage = 0
        for li in range(tb.n_lines):
            if tb.status[li] == LINE_FALLBACK:
                s, e = tb.spans[li]
                text = block[s:e].decode("utf-8", "replace").strip("\r\n")
                reply = manager.handle_telnet(conn, text)
                if reply:
                    out.append(reply)
                continue
            requests += 1
            exc = point_errors.get(int(tb.point_index[li]))
            if exc is None:
                continue
            if isinstance(exc, NoSuchUniqueName):
                unknown += 1
                out.append("put: unknown metric: %s\n" % exc)
            elif isinstance(exc, (ValueError, TypeError)):
                illegal += 1
                out.append("put: %s\n" % exc)
            else:
                storage += 1
                out.append("put: %s: %s\n" % (type(exc).__name__, exc))
        with self._lock:
            self.requests += requests
            self.unknown_metrics += unknown
            self.illegal_arguments += illegal
            self.hbase_errors += storage
        return "".join(out)

    @staticmethod
    def _telnet_lines_one_by_one(conn, block: bytes, manager) -> str:
        out = []
        for raw in block.splitlines():
            text = raw.decode("utf-8", "replace").strip("\r\n")
            if not text.strip():
                continue
            reply = manager.handle_telnet(conn, text)
            if reply:
                out.append(reply)
        return "".join(out)

    # -- HTTP --

    def execute_http(self, tsdb, query: HttpQuery) -> None:
        self._count("http_requests")
        allowed_methods(query, "POST")
        if getattr(tsdb, "replication", None) is not None:
            from opentsdb_tpu.tsd.replication import ReplicationManager
            if ReplicationManager.is_routed_request(query):
                # a peer already routed this body here (one hop): this
                # node is the accepting member — apply locally, never
                # re-forward (the loop guard)
                with ReplicationManager.accepting():
                    return self._execute_put(tsdb, query)
        return self._execute_put(tsdb, query)

    def _execute_put(self, tsdb, query: HttpQuery) -> None:
        native = self._try_native_put(tsdb, query)
        if native is not None:
            # the native parser fuses decode + columnar ingest: the
            # write path's device-equivalent work counts as dispatch
            latattr.mark("dispatch")
            success, errors, spans = native
            if success == 0 and not errors:
                raise BadRequestError("No datapoints found in content")
            body = query.request.body

            def dp_at(i: int) -> dict:
                # original datapoint for details-mode error reporting,
                # recovered lazily from its recorded byte span
                import json
                s, e = spans[i]
                try:
                    return json.loads(body[int(s):int(e)])
                except Exception:
                    # a span the native parser mis-recorded: the error
                    # report ships without its datapoint, which is worth
                    # an operator trace (the ingest verdict itself is
                    # unaffected)
                    LOG.warning(
                        "could not recover datapoint %d (bytes %d:%d) "
                        "for details-mode error reporting", i, s, e)
                    return {}

            self._respond_put(tsdb, query, success, errors, dp_at)
            return
        dps = query.serializer.parse_put_v1()
        latattr.mark("parse")
        self.process_data_points(tsdb, query, dps)

    def _try_native_put(self, tsdb, query: HttpQuery):
        """The C++ body parser, when nothing needs per-point Python:
        base put RPC only (rollup/histogram subclasses parse their own
        records), the stock JSON serializer, and a TSDB without
        per-point hooks (checked inside add_points_bulk_native)."""
        from opentsdb_tpu.tsd.serializers import HttpJsonSerializer
        if (type(self).ingest_points is not PutDataPointRpc.ingest_points
                or type(query.serializer).parse_put_v1
                is not HttpJsonSerializer.parse_put_v1
                or not query.request.body):
            return None
        return tsdb.add_points_bulk_native(query.request.body)

    def store_point(self, tsdb, dp: dict) -> None:
        for field in ("metric", "timestamp", "value", "tags"):
            if field not in dp or dp[field] in (None, "", {}):
                raise ValueError("Missing required field: %s" % field)
        tsdb.add_point(dp["metric"], dp["timestamp"], dp["value"],
                       dict(dp["tags"]))

    def ingest_points(self, tsdb, dps: list[dict]
                      ) -> tuple[int, list[tuple[int, Exception]]]:
        """(success, [(index, exception)]).  Raw puts take the vectorized
        bulk path; rollup/histogram records override with the per-point
        loop through their own store_point."""
        return tsdb.add_points_bulk(dps)

    def _ingest_one_by_one(self, tsdb, dps: list[dict]
                           ) -> tuple[int, list[tuple[int, Exception]]]:
        success = 0
        errors: list[tuple[int, Exception]] = []
        for i, dp in enumerate(dps):
            try:
                self.store_point(tsdb, dp)
                success += 1
            except Exception as e:
                errors.append((i, e))
        return success, errors

    def process_data_points(self, tsdb, query: HttpQuery,
                            dps: list[dict]) -> None:
        """processDataPoint (:309) semantics over the vectorized bulk
        ingest: points validate individually (per-point error collection,
        204 on clean success, details/summary modes) but land as one
        columnar batch per series (TSDB.add_points_bulk)."""
        if not dps:
            raise BadRequestError("No datapoints found in content")
        success, errors = self.ingest_points(tsdb, dps)
        latattr.mark("dispatch")
        self._respond_put(tsdb, query, success, errors, lambda i: dps[i])

    # The ack-path durability contract (PR 15), checked at the tree
    # level by tools/lint/ordering.py: by the time either ack statement
    # below runs, the accepted points must have journaled and shipped.
    # order: wal-append before ingest-ack
    # order: replica-ship before ingest-ack
    def _respond_put(self, tsdb, query: HttpQuery, success: int,
                     errors: list, dp_at) -> None:
        """Shared response tail: per-error counters + SEH spillway +
        204/details/summary shaping (same for both ingest parsers)."""
        show_details = query.has_query_string_param("details")
        show_summary = query.has_query_string_param("summary")
        details: list[dict] = []
        failed = len(errors)
        for i, e in errors:
            dp = dp_at(i)
            if isinstance(e, NoSuchUniqueName):
                self._count("unknown_metrics")
                details.append({"error": "Unknown metric",
                                "datapoint": dp})
            elif isinstance(e, (ValueError, TypeError)):
                self._count("illegal_arguments")
                details.append({"error": str(e), "datapoint": dp})
            else:
                self._count("hbase_errors")
                if tsdb.storage_exception_handler is not None:
                    # Failed-write spillway (TSDB.storeIntoDB error
                    # callbacks -> StorageExceptionHandler.handleError).
                    tsdb.storage_exception_handler.handle_error(dp, e)
                details.append({"error": "Storage exception: %s" % e,
                                "datapoint": dp})
        if not show_details and not show_summary:
            if failed:
                raise BadRequestError(
                    "One or more data points had errors",
                    details="Please see the TSD logs or append \"details\" "
                            "to the put request")
            query.send_status_only(204)              # order-event: ingest-ack
            return
        summary = {"success": success, "failed": failed}
        if show_details:
            summary["errors"] = details
        status = 200 if failed == 0 else 400
        query.send_reply(query.serializer.format_put_v1(summary),  # order-event: ingest-ack
                         status=status)

    def collect_stats(self, collector) -> None:
        collector.record("rpc.received", self.requests,
                         "type=%s" % self.kind)
        collector.record("rpc.received", self.http_requests,
                         "type=%s_http" % self.kind)
        collector.record("%s.errors" % self.kind, self.hbase_errors,
                         "type=storage_errors")
        collector.record("%s.errors" % self.kind, self.illegal_arguments,
                         "type=illegal_arguments")
        collector.record("%s.errors" % self.kind, self.unknown_metrics,
                         "type=unknown_metrics")


class RollupDataPointRpc(PutDataPointRpc):
    """Telnet `rollup` + POST /api/rollup.

    Telnet grammar (RollupDataPointRpc.java:95-150):
    ``rollup <interval>-<agg>[:<spatial_agg>] metric ts value tag=v...``
    or ``rollup <spatial_agg> ...`` for interval-less pre-aggregates.
    """

    kind = "rollup"

    def ingest_points(self, tsdb, dps):
        return self._ingest_one_by_one(tsdb, dps)

    def import_telnet_point(self, tsdb, words: list[str]) -> None:
        if len(words) < 6:
            raise ValueError("not enough arguments (need least 5, got %d)"
                             % (len(words) - 1))
        interval_agg = words[1]
        if not interval_agg:
            raise ValueError("Missing interval or aggregator")
        interval, temporal_agg, spatial_agg = parse_interval_agg(interval_agg)
        metric = words[2]
        if not metric:
            raise ValueError("empty metric name")
        timestamp = parse_telnet_timestamp(words[3])
        value = words[4]
        if not value:
            raise ValueError("empty value")
        tags = parse_tags(words[5:])
        tsdb.add_aggregate_point(metric, timestamp, value, tags,
                                 spatial_agg is not None, interval,
                                 temporal_agg, spatial_agg)

    def store_point(self, tsdb, dp: dict) -> None:
        for field in ("metric", "timestamp", "value", "tags"):
            if field not in dp or dp[field] in (None, "", {}):
                raise ValueError("Missing required field: %s" % field)
        interval = dp.get("interval")
        agg = dp.get("aggregator") or dp.get("aggregate")
        groupby = dp.get("groupbyAggregator") or dp.get("groupby_aggregator")
        is_groupby = bool(dp.get("groupby", groupby is not None))
        tsdb.add_aggregate_point(dp["metric"], dp["timestamp"], dp["value"],
                                 dict(dp["tags"]), is_groupby, interval,
                                 agg, groupby or agg)


def parse_interval_agg(interval_agg: str
                       ) -> tuple[str | None, str | None, str | None]:
    """"1h-sum", "1h-sum:count", or bare "sum" (RollupDataPointRpc:108-123)."""
    parts = interval_agg.split(":")
    interval = temporal = spatial = None
    dash = parts[0].find("-")
    if dash > -1:
        interval = parts[0][:dash]
        temporal = parts[0][dash + 1:]
    elif len(parts) == 1:
        spatial = parts[0]
    if len(parts) > 1:
        spatial = parts[1]
    return interval, temporal, spatial


def parse_telnet_timestamp(text: str) -> float:
    if not text:
        raise ValueError("empty timestamp")
    ts = float(text) if "." in text else int(text)
    if ts <= 0:
        raise ValueError("invalid timestamp: %s" % text)
    return ts


class HistogramDataPointRpc(PutDataPointRpc):
    """Telnet `histogram` + POST /api/histogram."""

    kind = "histogram"

    def ingest_points(self, tsdb, dps):
        return self._ingest_one_by_one(tsdb, dps)

    def import_telnet_point(self, tsdb, words: list[str]) -> None:
        # histogram <codec_id> <metric> <ts> <base64 or json value> tag=v...
        if len(words) < 6:
            raise ValueError("not enough arguments (need least 5, got %d)"
                             % (len(words) - 1))
        if tsdb.histogram_manager is None:
            raise ValueError("histograms are not configured "
                             "(tsd.core.histograms.config)")
        codec_id = int(words[1])
        metric = words[2]
        timestamp = parse_telnet_timestamp(words[3])
        tags = parse_tags(words[5:])
        tsdb.add_histogram_point_raw(metric, timestamp, codec_id, words[4],
                                     tags)

    def store_point(self, tsdb, dp: dict) -> None:
        if tsdb.histogram_manager is None:
            raise ValueError("histograms are not configured "
                             "(tsd.core.histograms.config)")
        for field in ("metric", "timestamp", "tags"):
            if field not in dp or dp[field] in (None, "", {}):
                raise ValueError("Missing required field: %s" % field)
        tsdb.add_histogram_point_json(dp["metric"], dp["timestamp"], dp,
                                      dict(dp["tags"]))


class QueryRpc(HttpRpc):
    """/api/query + /last (+ gexp/exp once the expression engines mount)."""

    def __init__(self, stats_registry=None):
        self.stats_registry = stats_registry

    def execute_http(self, tsdb, query: HttpQuery) -> None:
        sub = query.api_subpath()
        endpoint = sub[0] if sub else ""
        if endpoint == "last":
            return self.handle_last_query(tsdb, query)
        if endpoint == "gexp":
            return self.handle_gexp(tsdb, query)
        if endpoint == "exp":
            return self.handle_exp(tsdb, query)
        if endpoint == "explain":
            return self.handle_explain(tsdb, query)
        return self.handle_query(tsdb, query)

    # -- /api/query --

    def handle_query(self, tsdb, query: HttpQuery) -> None:
        allowed_methods(query, "GET", "POST", "DELETE")
        if query.method == "POST":
            ts_query = query.serializer.parse_query_v1()
        else:
            ts_query = self.parse_query_string(tsdb, query)
        if query.method == "DELETE" or ts_query.delete:
            if not tsdb.config.get_bool("tsd.http.query.allow_delete"):
                raise BadRequestError(
                    "Deleting data is not enabled",
                    details="Set tsd.http.query.allow_delete=true")
            ts_query.delete = True
        ts_query.validate()
        latattr.mark("parse")
        # Admission: concurrency permit + costmodel shedding/degrading
        # BEFORE any stats registration or device work.  May raise
        # ShedError (503 + Retry-After) or the deadline's own error;
        # may mutate ts_query down the degradation ladder
        # (permit.degrade_note annotates the 200 below).
        permit = admission.admit(tsdb, ts_query, query, route="api/query")
        # The permit must outlive the response write: releasing it first
        # would let the next queued query start while this one still
        # owns the serializer/socket (checked contract; the with-exit IS
        # the release event).
        # order: response-write before permit-release
        with permit:                                 # order-event: permit-release
            # injectable stall INSIDE the permit: tools/chaos_soak.py
            # --overload wedges the gate with it to prove the queue
            # bounds + sheds instead of stalling
            faults.check("rpc.slow_handler", route="api/query")
            self._serve_admitted(tsdb, query, ts_query, permit)

    def _serve_admitted(self, tsdb, query: HttpQuery, ts_query: TSQuery,
                        permit) -> None:
        """The admitted half of handle_query: stats registration,
        cluster-aware execution, serialization, response."""
        qs = QueryStats(query.remote, ts_query_json(ts_query),
                        query.request.headers)
        trace = obs_trace.active()
        if trace is not None:
            # the span tree rides the completed-query ring
            # (/api/stats/query) alongside the flat milestone marks
            qs.trace = trace
        if self.stats_registry is not None:
            try:
                self.stats_registry.start(qs)
            except DuplicateQueryException as e:
                if tsdb.config.get_bool("tsd.query.allow_simultaneous_duplicates"):
                    qs = None
                else:
                    raise BadRequestError(str(e))
        try:
            # one query, the whole cluster's data when peers are
            # configured (SaltScanner role:
            # /root/reference/src/core/SaltScanner.java:269); peers'
            # fan-out requests, deletes, and tsuid subqueries serve
            # purely locally — see cluster.serve_query
            from opentsdb_tpu.tsd.cluster import serve_query
            exec_stats: dict = {}
            results = serve_query(tsdb, ts_query, query,
                                  exec_stats=exec_stats)
            if ts_query.delete:
                deleted = self._delete(tsdb, ts_query)
            if permit.degrade_note:
                # the ladder coarsened/truncated this query at
                # admission: the 200 must say so out loud, through the
                # same partialResults trailer degraded cluster serving
                # uses (tsd/cluster.py partial_annotation)
                exec_stats["partialResults"] = True
                exec_stats["degraded"] = permit.degrade_note
            if qs is not None:
                qs.mark("aggregationTime")
                qs.stats.update(exec_stats)
            with obs_trace.stage("serialize") as ssp:
                payload = query.serializer.format_query_v1(ts_query,
                                                           results)
                obs_trace.annotate(ssp, results=len(payload))
            latattr.mark("serialize")
            from opentsdb_tpu.tsd.cluster import partial_annotation
            partial = partial_annotation(exec_stats)
            if partial:
                # degraded serving (tsd.network.cluster.partial_results=
                # allow): the 200 must say out loud that peers were
                # missing from the fold — a trailer entry (no "metric"
                # key, so fan-out receivers and statsSummary-aware
                # clients already skip it)
                payload.append(partial)
            if ts_query.show_summary or ts_query.show_stats:
                summary = {
                    "datapoints": sum(len(r.dps) for r in results),
                    "queryTime": round(query.elapsed_ms(), 3),
                }
                if partial:
                    summary.update(partial)
                if trace is not None and ts_query.show_stats:
                    # the span tree inline, as of this instant — the
                    # serialize span above is closed, the http root is
                    # still open and renders elapsed-so-far
                    summary["trace"] = trace.to_json()
                payload.append({"statsSummary": summary})
            query.send_reply(payload)                # order-event: response-write
            REGISTRY.counter(
                "tsd.query.count", "Queries served").labels(
                    status="200").inc()
            REGISTRY.histogram(
                "tsd.query.latency_ms",
                "End-to-end /api/query latency (ms), by tenant").labels(
                    tenant=permit.tenant).observe(
                        query.elapsed_ms(),
                        exemplar=trace.trace_id if trace is not None
                        else None)
            self._maybe_capture_slow(tsdb, query, trace, qs, 200,
                                     permit.tenant)
            if qs is not None and self.stats_registry is not None:
                qs.mark("serializationTime")
                self.stats_registry.finish(qs, 200)
        except Exception as e:
            from opentsdb_tpu.tsd.http import error_status
            status = error_status(e)
            REGISTRY.counter(
                "tsd.query.count", "Queries served").labels(
                    status=str(status)).inc()
            self._maybe_capture_slow(tsdb, query, trace, qs, status,
                                     permit.tenant)
            if qs is not None and self.stats_registry is not None:
                self.stats_registry.finish(qs, status, str(e))
            raise

    @staticmethod
    def _maybe_capture_slow(tsdb, query: HttpQuery, trace, qs,
                            status: int, tenant: str) -> None:
        """Flight-recorder slow-query capture (obs/flightrec.py): a
        query past the absolute/rolling-quantile latency threshold
        retains its span tree + ring slice at /api/diag/slow — no
        showStats required."""
        recorder = getattr(tsdb, "flightrec", None)
        if recorder is None:
            return
        recorder.maybe_capture_slow(
            trace, query.elapsed_ms(), status,
            qs.query if qs is not None else None, tenant)

    # -- /api/query/explain (docs/query_explain.md) --

    def handle_explain(self, tsdb, query: HttpQuery) -> None:
        """The no-dispatch what-if engine: the full /api/query request
        shape (+ what-if overrides) in, the complete routing decision
        tree out — admission preview, rollup/agg-cache/device-cache
        consult verdicts, grid-budget/tiling decision, per-axis
        costmodel pricing, and the stable plan fingerprint the
        executor stamps into flight-recorder ``plan`` events.

        Deliberately NOT behind the admission gate: an overloaded
        daemon must still be explainable (the ambient request deadline
        still bounds the planning walk, and the per-sub QueryBudget
        charges the same scan the executor would)."""
        allowed_methods(query, "GET", "POST")
        if not tsdb.config.get_bool("tsd.explain.enable"):
            raise BadRequestError(
                "The explain endpoint is disabled", status=404,
                details="Set tsd.explain.enable=true")
        from opentsdb_tpu.query import explain as explain_mod
        if query.method == "POST":
            ts_query = query.serializer.parse_query_v1()
            raw_what_if = (query.json_body() or {}).get("whatIf") or {}
        else:
            ts_query = self.parse_query_string(tsdb, query)
            raw_what_if = {}
            for spec in query.get_query_string_params("what_if"):
                if "=" not in spec:
                    raise BadRequestError(
                        "what_if must be key=value, got %r" % spec)
                k, v = spec.split("=", 1)
                raw_what_if[k.strip()] = v
        ts_query.validate()
        latattr.mark("parse")
        try:
            what_if = explain_mod.parse_what_if(raw_what_if)
        except explain_mod.WhatIfError as e:
            raise BadRequestError(str(e))
        start = time.perf_counter()
        try:
            with obs_trace.stage("explain") as span:
                report = explain_mod.explain_query(tsdb, ts_query,
                                                   what_if)
                obs_trace.annotate(
                    span, sub_queries=len(report["subQueries"]),
                    what_if=bool(what_if.active))
            # the whole no-dispatch planning walk is "plan" time
            latattr.mark("plan")
        except Exception:
            REGISTRY.counter(
                "tsd.query.explain.requests",
                "Explain requests served, by outcome").labels(
                    outcome="error").inc()
            raise
        query.send_reply(report)
        REGISTRY.counter(
            "tsd.query.explain.requests",
            "Explain requests served, by outcome").labels(
                outcome="ok").inc()
        REGISTRY.histogram(
            "tsd.query.explain.latency_ms",
            "Explain planning latency (ms) — the no-dispatch walk"
        ).observe((time.perf_counter() - start) * 1e3)

    def _delete(self, tsdb, ts_query: TSQuery) -> int:
        """Drop the matched datapoints after serving them (delete flag).

        Deletes from the stores the query actually read: the reference
        issues DeleteRequests for the scanned rows, which are rollup-table
        rows for rollup-served queries (TsdbQuery delete path)."""
        runner = tsdb.new_query_runner()
        fix_dups = tsdb.config.fix_duplicates
        deleted = 0
        for sub in ts_query.queries:
            for seg in runner._plan_segments(ts_query, sub):
                stores = []
                if seg.kind == "raw":
                    stores.append(tsdb.store)
                else:
                    stores.append(seg.lane)
                    if seg.count_lane is not None:
                        stores.append(seg.count_lane)
                for store in stores:
                    for series, _ in runner._resolve_series(sub, store):
                        deleted += series.delete_range(
                            seg.start_ms, seg.end_ms, fix_dups)
                        store.notify_mutation(series.key.metric,
                                              seg.start_ms, seg.end_ms)
        return deleted

    def parse_query_string(self, tsdb, query: HttpQuery) -> TSQuery:
        """GET grammar (QueryRpc.parseQuery :521-535)."""
        ts_query = TSQuery(
            start=query.required_query_string_param("start"),
            end=query.get_query_string_param("end"),
            timezone=query.get_query_string_param("tz"),
            ms_resolution=query.has_query_string_param("ms"),
            show_tsuids=query.has_query_string_param("show_tsuids"),
            no_annotations=query.has_query_string_param("no_annotations"),
            global_annotations=query.has_query_string_param(
                "global_annotations"),
            show_summary=query.has_query_string_param("show_summary"),
            show_stats=query.has_query_string_param("show_stats"),
            show_query=query.has_query_string_param("show_query"),
            padding=query.has_query_string_param("padding"),
            use_calendar=query.has_query_string_param("use_calendar"),
        )
        for m in query.get_query_string_params("m"):
            ts_query.queries.append(parse_m_subquery(m))
        for t in query.get_query_string_params("tsuid"):
            ts_query.queries.append(parse_tsuid_subquery(t))
        if not ts_query.queries:
            raise BadRequestError.missing_parameter("m or tsuid")
        return ts_query

    # -- /api/query/last (QueryRpc.handleLastDataPointQuery :346) --

    def handle_last_query(self, tsdb, query: HttpQuery) -> None:
        allowed_methods(query, "GET", "POST")
        if query.method == "POST":
            body = query.json_body()
            specs = body.get("queries", [])
            resolve = bool(body.get("resolveNames", False))
            back_scan = int(body.get("backScan", 0))
        else:
            specs = []
            for ts_spec in query.get_query_string_params("timeseries"):
                specs.append({"metric": ts_spec})
            for t in query.get_query_string_params("tsuids"):
                specs.append({"tsuids": t.split(",")})
            resolve = query.has_query_string_param("resolve")
            back_scan = int(query.get_query_string_param("back_scan") or 0)
        if not specs:
            raise BadRequestError.missing_parameter("timeseries or tsuids")
        cutoff_ms = None
        if back_scan > 0:
            cutoff_ms = int(time.time() * 1000) - back_scan * 3_600_000
        results = []
        for spec in specs:
            results.extend(self._last_points(tsdb, spec, resolve, cutoff_ms))
        query.send_reply(
            query.serializer.format_last_point_query_v1(results))

    def _last_points(self, tsdb, spec: dict, resolve: bool,
                     cutoff_ms: int | None) -> list[dict]:
        from opentsdb_tpu.query.filters import parse_metric_with_filters
        out = []
        if spec.get("tsuids"):
            wanted = {t.upper() for t in spec["tsuids"]}
            chosen = [s for s in tsdb.store.all_series()
                      if tsdb.tsuid(s.key) in wanted]
        else:
            filters: list = []
            metric = parse_metric_with_filters(spec["metric"], filters)
            try:
                metric_uid = tsdb.metrics.get_id(metric)
            except NoSuchUniqueName:
                raise BadRequestError("No such name for 'metrics': '%s'"
                                      % metric, status=404)
            chosen = []
            for series in tsdb.store.series_for_metric(metric_uid):
                tags = tsdb.resolve_key_tags(series.key)
                if all(f.match(tags) for f in filters):
                    chosen.append(series)
        for series in chosen:
            ts, fv, iv, isint = series.arrays()
            if len(ts) == 0:
                continue
            last_ts = int(ts[-1])
            if cutoff_ms is not None and last_ts < cutoff_ms:
                continue
            value = int(iv[-1]) if isint[-1] else float(fv[-1])
            entry = {
                "timestamp": last_ts,
                "value": str(value),
                "tsuid": tsdb.tsuid(series.key),
            }
            if resolve or spec.get("metric"):
                entry["metric"] = tsdb.metrics.get_name(series.key.metric)
                entry["tags"] = tsdb.resolve_key_tags(series.key)
            out.append(entry)
        return out

    # -- expression endpoints (mounted by the expression engine) --

    def handle_gexp(self, tsdb, query: HttpQuery) -> None:
        try:
            from opentsdb_tpu.expression.gexp import handle_gexp_query
        except ImportError:
            raise BadRequestError("The gexp endpoint is not available",
                                  status=501)
        handle_gexp_query(tsdb, query)

    def handle_exp(self, tsdb, query: HttpQuery) -> None:
        try:
            from opentsdb_tpu.expression.executor import handle_exp_query
        except ImportError:
            raise BadRequestError("The exp endpoint is not available",
                                  status=501)
        handle_exp_query(tsdb, query)


def ts_query_json(ts_query: TSQuery) -> dict:
    return {
        "start": str(ts_query.start),
        "end": str(ts_query.end) if ts_query.end else None,
        "queries": [sub.to_json() for sub in ts_query.queries],
    }


class SuggestRpc(HttpRpc):
    """/api/suggest + /suggest (SuggestRpc.java)."""

    def execute_http(self, tsdb, query: HttpQuery) -> None:
        allowed_methods(query, "GET", "POST")
        if (query.method == "POST"
                and "json" in (query.request.header("content-type") or "")):
            body = query.serializer.parse_suggest_v1()
            stype = body.get("type")
            prefix = body.get("q", "")
            max_results = int(body.get("max", 25))
        else:
            stype = query.required_query_string_param("type")
            prefix = query.get_query_string_param("q") or ""
            mx = query.get_query_string_param("max")
            try:
                max_results = int(mx) if mx else 25
            except ValueError:
                raise BadRequestError("Unable to parse 'max' as a number")
        if stype == "metrics":
            results = tsdb.suggest_metrics(prefix, max_results)
        elif stype == "tagk":
            results = tsdb.suggest_tagk(prefix, max_results)
        elif stype == "tagv":
            results = tsdb.suggest_tagv(prefix, max_results)
        else:
            raise BadRequestError("Invalid 'type' parameter:" + str(stype))
        query.send_reply(query.serializer.format_suggest_v1(results))


class AnnotationRpc(HttpRpc):
    """/api/annotation + /api/annotations (AnnotationRpc.java)."""

    def execute_http(self, tsdb, query: HttpQuery) -> None:
        sub = query.api_subpath()
        if query.path.startswith("api/annotations") or (
                sub and sub[0] == "bulk"):
            return self._bulk(tsdb, query)
        method = query.method
        if method == "GET":
            self._get(tsdb, query)
        elif method in ("POST", "PUT"):
            self._upsert(tsdb, query)
        elif method == "DELETE":
            self._delete(tsdb, query)
        else:
            raise BadRequestError("Method not allowed", status=405)

    def _params(self, query: HttpQuery) -> dict:
        if query.request.body:
            return query.serializer.parse_annotation_v1()
        out = {}
        for name in ("tsuid", "description", "notes"):
            v = query.get_query_string_param(name)
            if v is not None:
                out[name] = v
        for name in ("start_time", "end_time"):
            v = query.get_query_string_param(name)
            if v is not None:
                out["startTime" if name == "start_time" else "endTime"] = v
        return out

    @staticmethod
    def _note_from(params: dict) -> Annotation:
        start = params.get("startTime")
        if start in (None, ""):
            raise BadRequestError("Missing start time")
        return Annotation(
            start_time=int(start),
            end_time=int(params.get("endTime") or 0),
            tsuid=(params.get("tsuid") or "").upper(),
            description=params.get("description") or "",
            notes=params.get("notes") or "",
            custom=params.get("custom"))

    def _get(self, tsdb, query: HttpQuery) -> None:
        params = self._params(query)
        start = params.get("startTime")
        if start in (None, ""):
            raise BadRequestError("Missing start time")
        tsuid = (params.get("tsuid") or "").upper()
        notes = [a for a in tsdb.store.get_annotations(
                    tsuid, int(start), int(start))
                 if a.start_time == int(start)]
        if not notes:
            raise BadRequestError(
                "Unable to locate annotation in storage", status=404)
        query.send_reply(
            query.serializer.format_annotation_v1(notes[0].to_json()))

    def _upsert(self, tsdb, query: HttpQuery) -> None:
        note = self._note_from(self._params(query))
        tsdb.store.delete_annotation(note.tsuid, note.start_time)
        tsdb.add_annotation(note)
        query.send_reply(query.serializer.format_annotation_v1(
            note.to_json()))

    def _delete(self, tsdb, query: HttpQuery) -> None:
        params = self._params(query)
        start = params.get("startTime")
        if start in (None, ""):
            raise BadRequestError("Missing start time")
        tsuid = (params.get("tsuid") or "").upper()
        if tsdb.store.delete_annotation(tsuid, int(start)):
            if tsdb.search_plugin is not None:
                tsdb.search_plugin.delete_annotation(
                    Annotation(start_time=int(start), tsuid=tsuid))
            query.send_status_only(204)
        else:
            raise BadRequestError(
                "Unable to locate annotation in storage", status=404)

    def _bulk(self, tsdb, query: HttpQuery) -> None:
        method = query.method
        if method in ("POST", "PUT"):
            notes = [self._note_from(p)
                     for p in query.serializer.parse_annotation_bulk_v1()]
            for n in notes:
                tsdb.store.delete_annotation(n.tsuid, n.start_time)
                tsdb.add_annotation(n)
            query.send_reply(query.serializer.format_annotations_v1(
                [n.to_json() for n in notes]))
        elif method == "DELETE":
            start = query.get_query_string_param("start_time")
            end = query.get_query_string_param("end_time")
            if query.request.body:
                body = query.json_body()
                start = body.get("startTime", start)
                end = body.get("endTime", end)
                tsuids = body.get("tsuids")
                global_notes = bool(body.get("global", False))
            else:
                tsuids_param = query.get_query_string_param("tsuids")
                tsuids = tsuids_param.split(",") if tsuids_param else None
                global_notes = query.has_query_string_param("global")
            if start in (None, ""):
                raise BadRequestError("Missing start time")
            end_ms = int(end) if end not in (None, "") else int(
                time.time() * 1000)
            norm_tsuids = [t.upper() for t in tsuids] if tsuids else None
            if tsdb.search_plugin is not None:
                # De-index exactly what delete_annotation_range will drop —
                # its precedence is global > tsuids > everything.
                if global_notes:
                    pools = [""]
                elif norm_tsuids:
                    pools = norm_tsuids
                else:
                    pools = tsdb.store.annotation_keys()
                for t in pools:
                    for note in tsdb.store.get_annotations(
                            t, int(start), end_ms):
                        tsdb.search_plugin.delete_annotation(note)
            count = tsdb.store.delete_annotation_range(
                norm_tsuids, int(start), end_ms, global_notes)
            query.send_reply({"totalDeleted": count})
        else:
            raise BadRequestError("Method not allowed", status=405)


class UniqueIdRpc(HttpRpc):
    """/api/uid/{assign,rename,uidmeta,tsmeta} (UniqueIdRpc.java:63-77)."""

    def execute_http(self, tsdb, query: HttpQuery) -> None:
        sub = query.api_subpath()
        endpoint = sub[0] if sub else ""
        if endpoint == "assign":
            self._assign(tsdb, query)
        elif endpoint == "rename":
            self._rename(tsdb, query)
        elif endpoint == "uidmeta":
            self._uidmeta(tsdb, query)
        elif endpoint == "tsmeta":
            self._tsmeta(tsdb, query)
        else:
            raise BadRequestError(
                "Other UID endpoints have not been implemented yet",
                status=501,
                details="Accessed endpoint: /api/uid/%s" % endpoint)

    def _assign(self, tsdb, query: HttpQuery) -> None:
        allowed_methods(query, "GET", "POST")
        if query.method == "POST" and query.request.body:
            kinds = query.serializer.parse_uid_assign_v1()
        else:
            kinds = {}
            for kind in ("metric", "tagk", "tagv"):
                v = query.get_query_string_param(kind)
                if v:
                    kinds[kind] = v.split(",")
        if not kinds:
            raise BadRequestError("Missing values to assign UIDs")
        response: dict = {}
        any_errors = False
        for kind, names in kinds.items():
            good: dict[str, str] = {}
            errors: dict[str, str] = {}
            for name in names:
                try:
                    uid = tsdb.assign_uid(kind, name)
                    table = tsdb.uid_table(kind)
                    good[name] = table.uid_to_hex(uid)
                except ValueError as e:
                    errors[name] = str(e)
                    any_errors = True
            response[kind] = good
            response[kind + "_errors"] = errors
        query.send_reply(query.serializer.format_uid_assign_v1(response),
                         status=400 if any_errors else 200)

    def _rename(self, tsdb, query: HttpQuery) -> None:
        allowed_methods(query, "POST", "PUT")
        if query.request.body:
            body = query.serializer.parse_uid_rename_v1()
        else:
            body = {k: query.get_query_string_param(k)
                    for k in ("metric", "tagk", "tagv", "name")}
            body = {k: v for k, v in body.items() if v}
        name = body.pop("name", None)
        if not name:
            raise BadRequestError("Missing or empty new name")
        kinds = [(k, v) for k, v in body.items()
                 if k in ("metric", "tagk", "tagv")]
        if len(kinds) != 1:
            raise BadRequestError("Missing or invalid UID type/name to "
                                  "rename")
        kind, old_name = kinds[0]
        try:
            tsdb.rename_uid(kind, old_name, name)
        except ValueError as e:
            query.send_reply({"error": str(e), "result": "false"})
            return
        query.send_reply(query.serializer.format_uid_rename_v1(
            {"result": "true"}))

    def _uidmeta(self, tsdb, query: HttpQuery) -> None:
        try:
            from opentsdb_tpu.meta.rpc import handle_uidmeta
        except ImportError:
            raise BadRequestError("uidmeta is not available", status=501)
        handle_uidmeta(tsdb, query)

    def _tsmeta(self, tsdb, query: HttpQuery) -> None:
        try:
            from opentsdb_tpu.meta.rpc import handle_tsmeta
        except ImportError:
            raise BadRequestError("tsmeta is not available", status=501)
        handle_tsmeta(tsdb, query)
