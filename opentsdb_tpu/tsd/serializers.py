"""HttpSerializer SPI + the default JSON implementation.

Reference behavior: /root/reference/src/tsd/HttpSerializer.java (:930,
pluggable parse/format per endpoint) and HttpJsonSerializer.java (:1283 —
parsePutV1 :~200, parseQueryV1 :250, formatQueryAsyncV1 :516 producing
[{metric, tags, aggregateTags, tsuids?, annotations?, dps}], error envelope).
Serializers register by name; requests pick one via the `serializer` query
param (HttpQuery.setSerializer).
"""

from __future__ import annotations

from opentsdb_tpu.models.tsquery import (
    TSQuery, TSSubQuery, parse_m_subquery, parse_tsuid_subquery,
    parse_rate_options, parse_percentiles)
from opentsdb_tpu.query.filters import build_filter, tags_to_filters
from opentsdb_tpu.tsd.http import BadRequestError, HttpQuery


class HttpSerializer:
    """Base SPI: every hook raises 501 unless the subclass implements it."""

    name = "unknown"
    request_content_type = "application/json"
    response_content_type = "application/json; charset=UTF-8"

    def __init__(self, query: HttpQuery | None = None):
        self.query = query

    def shutdown(self) -> None:
        pass

    @classmethod
    def descriptor(cls) -> dict:
        """/api/serializers entry (HttpSerializer.java doc)."""
        parsers = [m[len("parse_"):-len("_v1")] for m in dir(cls)
                   if m.startswith("parse_") and m.endswith("_v1")]
        formatters = [m[len("format_"):-len("_v1")] for m in dir(cls)
                      if m.startswith("format_") and m.endswith("_v1")]
        return {
            "serializer": cls.name,
            "class": cls.__name__,
            "request_content_type": cls.request_content_type,
            "response_content_type": cls.response_content_type,
            "parsers": sorted(parsers),
            "formatters": sorted(formatters),
        }

    def __getattr__(self, item):
        if item.startswith(("parse_", "format_")):
            raise BadRequestError(
                "The requested API endpoint has not been implemented",
                status=501,
                details="The serializer %s has not implemented %s"
                        % (self.name, item))
        raise AttributeError(item)


class HttpJsonSerializer(HttpSerializer):
    """Default JSON (de)serializer."""

    name = "json"

    # -- parsers --

    def parse_put_v1(self) -> list[dict]:
        """POST /api/put body: one datapoint object or a list of them."""
        body = self.query.json_body()
        if isinstance(body, dict):
            body = [body]
        if not isinstance(body, list):
            raise BadRequestError("Unparseable data content",
                                  details="Expected a JSON object or array")
        for dp in body:
            if not isinstance(dp, dict):
                raise BadRequestError("Unparseable data content",
                                      details="Expected datapoint objects")
        return body

    def parse_suggest_v1(self) -> dict:
        body = self.query.json_body()
        if not isinstance(body, dict):
            raise BadRequestError("Unparseable data content")
        return body

    def parse_query_v1(self) -> TSQuery:
        """POST /api/query body -> TSQuery (HttpJsonSerializer.parseQueryV1)."""
        body = self.query.json_body()
        return ts_query_from_json(body)

    def parse_annotation_v1(self) -> dict:
        body = self.query.json_body()
        if not isinstance(body, dict):
            raise BadRequestError("Unparseable data content")
        return body

    def parse_annotation_bulk_v1(self) -> list[dict]:
        body = self.query.json_body()
        if isinstance(body, dict):
            return [body]
        if not isinstance(body, list):
            raise BadRequestError("Annotations must be in an array to bulk "
                                  "process")
        return body

    def parse_search_query_v1(self) -> dict:
        body = self.query.json_body()
        if not isinstance(body, dict):
            raise BadRequestError("Unparseable data content")
        return body

    def parse_uid_assign_v1(self) -> dict[str, list[str]]:
        """POST /api/uid/assign body {metric: [...], tagk: [...], tagv: [...]}."""
        body = self.query.json_body()
        if not isinstance(body, dict):
            raise BadRequestError("Unparseable data content")
        out = {}
        for kind, names in body.items():
            if isinstance(names, str):
                names = [names]
            out[kind] = list(names)
        return out

    def parse_uid_rename_v1(self) -> dict:
        body = self.query.json_body()
        if not isinstance(body, dict):
            raise BadRequestError("Unparseable data content")
        return body

    # -- formatters (each returns a JSON-able object; HttpQuery renders) --

    def format_put_v1(self, results: dict) -> dict:
        return results

    def format_suggest_v1(self, suggestions: list[str]) -> list[str]:
        return suggestions

    def format_aggregators_v1(self, aggregators: list[str]) -> list[str]:
        return aggregators

    def format_serializers_v1(self, serializers: list[dict]) -> list[dict]:
        return serializers

    def format_version_v1(self, version: dict) -> dict:
        return version

    def format_dropcaches_v1(self, response: dict) -> dict:
        return response

    def format_config_v1(self, config: dict) -> dict:
        return config

    def format_stats_v1(self, stats: list[dict]) -> list[dict]:
        return stats

    def format_query_stats_v1(self, stats: dict) -> dict:
        return stats

    def format_annotation_v1(self, note: dict) -> dict:
        return note

    def format_annotations_v1(self, notes: list[dict]) -> list[dict]:
        return notes

    def format_uid_assign_v1(self, response: dict) -> dict:
        return response

    def format_uid_rename_v1(self, response: dict) -> dict:
        return response

    def format_search_results_v1(self, results: dict) -> dict:
        return results

    def format_query_v1(self, data_query: TSQuery, results: list,
                        globals_list: list | None = None) -> list[dict]:
        """The /api/query result array (formatQueryAsyncV1 :516)."""
        out = []
        for r in results:
            out.append(r.to_json(
                ms_resolution=data_query.ms_resolution,
                show_tsuids=data_query.show_tsuids,
                fill_policy=(data_query.queries[r.index].fill_policy
                             if r.index < len(data_query.queries) else "none"),
                show_query=data_query.show_query,
                sub_query=(data_query.queries[r.index]
                           if r.index < len(data_query.queries) else None),
                no_annotations=data_query.no_annotations,
                global_annotations=data_query.global_annotations))
        return out

    def format_last_point_query_v1(self, results: list[dict]) -> list[dict]:
        return results


def ts_query_from_json(body) -> TSQuery:
    """JSON /api/query body -> TSQuery object model."""
    if not isinstance(body, dict):
        raise BadRequestError("Unparseable data content",
                              details="Expected a JSON object")
    if "queries" not in body or not body["queries"]:
        raise BadRequestError("Missing queries")
    q = TSQuery(
        start=str(body.get("start", "")),
        end=str(body["end"]) if body.get("end") not in (None, "") else None,
        timezone=body.get("timezone"),
        ms_resolution=bool(body.get("msResolution",
                                    body.get("ms", False))),
        show_tsuids=bool(body.get("showTSUIDs", False)),
        no_annotations=bool(body.get("noAnnotations", False)),
        global_annotations=bool(body.get("globalAnnotations", False)),
        show_summary=bool(body.get("showSummary", False)),
        show_stats=bool(body.get("showStats", False)),
        show_query=bool(body.get("showQuery", False)),
        delete=bool(body.get("delete", False)),
        use_calendar=bool(body.get("useCalendar", False)),
    )
    for i, sq in enumerate(body["queries"]):
        q.queries.append(sub_query_from_json(sq, i))
    return q


def sub_query_from_json(sq: dict, index: int) -> TSSubQuery:
    if not isinstance(sq, dict):
        raise BadRequestError("Unparseable sub query")
    sub = TSSubQuery(
        aggregator=sq.get("aggregator", ""),
        metric=sq.get("metric"),
        tsuids=sq.get("tsuids"),
        downsample=sq.get("downsample"),
        rate=bool(sq.get("rate", False)),
        explicit_tags=bool(sq.get("explicitTags", False)),
        pre_aggregate=bool(sq.get("preAggregate", False)),
        rollup_usage=sq.get("rollupUsage"),
        index=index,
    )
    ro = sq.get("rateOptions")
    if ro:
        from opentsdb_tpu.ops.rate import RateOptions
        sub.rate_options = RateOptions(
            counter=bool(ro.get("counter", False)),
            counter_max=int(ro.get("counterMax", RateOptions().counter_max)),
            reset_value=int(ro.get("resetValue", 0)),
            drop_resets=bool(ro.get("dropResets", False)))
    filters = []
    for f in sq.get("filters", []) or []:
        filters.append(build_filter(
            f["tagk"], f.get("type", "literal_or"), f.get("filter", ""),
            group_by=bool(f.get("groupBy", False))))
    # legacy "tags" map (2.1-style {host: "web01"} / {host: "*"})
    tags = sq.get("tags") or {}
    if tags:
        tags_to_filters(dict(tags), filters)
    sub.filters = filters
    pct = sq.get("percentiles")
    if pct:
        sub.percentiles = [float(p) for p in pct]
    sub.show_histogram_buckets = bool(sq.get("showHistogramBuckets", False))
    return sub


SERIALIZERS: dict[str, type[HttpSerializer]] = {
    HttpJsonSerializer.name: HttpJsonSerializer,
}


def register_serializer(cls: type[HttpSerializer]) -> None:
    existing = SERIALIZERS.get(cls.name)
    if existing is not None and existing is not cls:
        raise ValueError(
            "Serializer name collision: %s already registered by %s"
            % (cls.name, existing.__name__))
    SERIALIZERS[cls.name] = cls


def serializer_for(query: HttpQuery) -> HttpSerializer:
    name = query.get_query_string_param("serializer") or "json"
    cls = SERIALIZERS.get(name)
    if cls is None:
        raise BadRequestError("Could not find a serializer named: %s" % name,
                              status=400)
    return cls(query)
