"""Asyncio server: one port, telnet line protocol + HTTP/1.1, sniffed from
the first bytes of each connection.

Reference behavior: /root/reference/src/tsd/PipelineFactory.java (:44) —
ConnectionManager -> DetectHttpOrRpc (:134, first-byte sniff: ASCII letters
'A'-'Z' mean an HTTP verb, anything else is the telnet line protocol) ->
framing -> timeout -> RpcHandler — and ConnectionManager.java (:37-41
connection limit).

Handlers run on a bounded thread pool (the "OpenTSDB Responder" analog,
RpcResponder.java) so jit-compiled query work never blocks the accept loop.
"""

from __future__ import annotations

import asyncio
import functools
import logging
import threading
import time
from concurrent.futures import ThreadPoolExecutor

from opentsdb_tpu.tsd.http import (
    BadRequestError, HttpQuery, HttpResponse, parse_http_head)
from opentsdb_tpu.tsd.rpc_manager import RpcManager

LOG = logging.getLogger("tsd.server")

MAX_REQUEST_BYTES = 64 * 1024 * 1024   # HttpRequestDecoder aggregator cap
MAX_TELNET_LINE = 1024 * 1024
# After the graceful drain window (tsd.network.drain_timeout_ms)
# expires, force-cancelled handlers get this long to observe their
# cancellation token and unwind before TSDB teardown proceeds anyway.
POST_CANCEL_GRACE_S = 5.0

# Telnet put batching peeks at asyncio.StreamReader's buffered bytes to
# decide whether another complete line can be consumed WITHOUT awaiting
# more input.  There is no public API for this; `_buffer` (a bytearray)
# has been the implementation since CPython 3.4.  The peek is isolated
# here so a future rename degrades loudly (one warning, correct
# unbatched behavior) instead of silently costing the 14x batching win.
_warned_no_buffer = False


def _has_buffered_line(reader: asyncio.StreamReader) -> bool:
    """True when a complete line is already in the reader's buffer."""
    buf = getattr(reader, "_buffer", None)
    if buf is None:
        global _warned_no_buffer
        if not _warned_no_buffer:
            _warned_no_buffer = True
            LOG.warning(
                "asyncio.StreamReader._buffer is gone in this CPython; "
                "telnet put batching disabled (correct but slower)")
        return False
    return b"\n" in buf


class ConnectionRefused(Exception):
    pass


class TelnetConn:
    """Handler-facing handle on one telnet connection."""

    def __init__(self, writer: asyncio.StreamWriter):
        self.writer = writer
        self.close_after_write = False


class TSDServer:
    """The daemon: TSDB + RpcManager + asyncio socket server."""

    def __init__(self, tsdb, port: int = 4242, bind: str = "0.0.0.0",
                 worker_threads: int = 8):
        self.tsdb = tsdb
        self.port = port
        self.bind = bind
        self.rpc_manager = RpcManager(tsdb, server=self,
                                      shutdown_cb=self.request_shutdown)
        self.connections_established = 0  # guarded-by: _conn_lock
        self.connections_rejected = 0  # guarded-by: _conn_lock
        self.exceptions_caught = 0
        self.telnet_rpcs = 0
        self.http_rpcs = 0
        # RPCs dispatched but whose reply has not hit the socket yet.
        # Touched only on the event-loop thread (no lock); stop() waits
        # on it so a drained handler's response still gets delivered
        # before the TSDB (and then the loop) tears down.
        self._inflight_rpcs = 0
        self._open_connections = 0  # guarded-by: _conn_lock
        self._conn_lock = threading.Lock()
        self.max_connections = tsdb.config.get_int(
            "tsd.core.connections.limit")
        self.idle_timeout = tsdb.config.get_int(
            "tsd.network.keep_alive_timeout") if tsdb.config.has_property(
            "tsd.network.keep_alive_timeout") else 300
        # graceful-shutdown budget for in-flight responder work:
        # generous enough for the longest legitimate request, bounded
        # so one wedged handler can't hold the daemon past its
        # supervisor's patience — at expiry every in-flight request's
        # cancellation token is force-flipped (stop() below)
        self.drain_grace_s = max(
            tsdb.config.get_int("tsd.network.drain_timeout_ms"), 0) / 1e3
        # cancellation handles of in-flight HTTP requests.  Touched
        # only on the event-loop thread, like _inflight_rpcs; stop()
        # (also on the loop) force-cancels them at drain expiry.
        self._active_handles: set = set()
        self._executor = ThreadPoolExecutor(
            max_workers=worker_threads, thread_name_prefix="tsd-responder")
        self._server: asyncio.AbstractServer | None = None
        self._shutdown_event: asyncio.Event | None = None
        self._loop: asyncio.AbstractEventLoop | None = None
        # Process-global installs come LAST: everything fallible
        # (RpcManager construction, config reads) has already run, so a
        # failed construction never arms global state with no instance
        # left to stop().  _log_buffer_installed is this instance's
        # share of the refcount — a second stop() (owner finally +
        # shutdown-event path both reach stop) must not decrement on
        # behalf of another still-running server.
        self._compile_counting = tsdb.config.get_bool("tsd.trace.enable")
        self._log_buffer_installed = False
        # staged arming with ONE rollback path: a failure part-way in
        # must release exactly what already installed, newest first
        undo: list = []
        try:
            from opentsdb_tpu.tsd.admin_rpcs import (install_log_buffer,
                                                     uninstall_log_buffer)
            # global-install: uninstall_log_buffer paired-with: stop
            install_log_buffer()
            self._log_buffer_installed = True
            undo.append(uninstall_log_buffer)
            if self._compile_counting:
                # per-kernel XLA compile counters (tsd.jax.compiles at
                # /api/stats/prometheus) — the same capture tsdbsan uses
                from opentsdb_tpu.obs import jaxprof
                # global-install: stop_compile_counting paired-with: stop
                jaxprof.start_compile_counting()
                undo.append(jaxprof.stop_compile_counting)
            if tsdb.flightrec is not None:
                # steady-state recompile events into the flight
                # recorder, off the SAME shared capture — armed
                # REGARDLESS of tsd.trace.enable (the recorder is the
                # always-on black box; tracing only governs the span
                # surfaces).  The recorder unsubscribes in its own
                # shutdown (tsdb.shutdown, reached from stop()).
                tsdb.flightrec.start()
        except BaseException:
            self._compile_counting = False
            self._log_buffer_installed = False
            for release in reversed(undo):
                release()
            raise

    # -- lifecycle --

    async def start(self) -> None:
        self._loop = asyncio.get_running_loop()
        self._shutdown_event = asyncio.Event()
        self._server = await asyncio.start_server(
            self._handle_connection, self.bind, self.port,
            limit=MAX_TELNET_LINE)
        repl = getattr(self.tsdb, "replication", None)
        if repl is not None:
            # rejoin protocol (tsd/replication.py): catch up from
            # peers' WAL tails BEFORE re-accepting ownership, then keep
            # the pull cadence running.  Off the event loop — catch-up
            # is blocking HTTP against peers.
            await self._loop.run_in_executor(None, repl.catch_up)
            repl.start_puller()
        LOG.info("Ready to serve on %s:%d", self.bind, self.port)

    async def serve_forever(self) -> None:
        if self._server is None:
            await self.start()
        assert self._shutdown_event is not None
        await self._shutdown_event.wait()
        await self.stop()

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
        # Drain in-flight responder work BEFORE tearing down the TSDB:
        # handlers may still be mid-write (a put landing, a query
        # serializing), and shutdown(wait=False) + tsdb.shutdown() would
        # snapshot/close the WAL underneath them.  cancel_futures drops
        # QUEUED requests (accepted but unstarted — shutdown owes them
        # nothing) while running ones finish; the drain runs in the
        # loop's default executor so the event loop stays live and the
        # draining handlers can still deliver their responses.  The wait
        # is bounded: one wedged handler must not hold the daemon
        # hostage past the grace period (the supervisor's SIGKILL would
        # land us in exactly the mid-write teardown this drain avoids).
        loop = asyncio.get_running_loop()
        try:
            drain = loop.run_in_executor(
                None, functools.partial(self._executor.shutdown, wait=True,
                                        cancel_futures=True))
            try:
                await asyncio.wait_for(asyncio.shield(drain),
                                       timeout=self.drain_grace_s)
            except asyncio.TimeoutError:
                # the drain is OUT of patience: force-flip every
                # in-flight request's cancellation token so cooperative
                # handlers (budget.check_deadline sites, admission
                # waits) unwind now, then give them a short bounded
                # window before tearing the TSDB down regardless
                from opentsdb_tpu.tsd import admission
                handles = list(self._active_handles)
                LOG.warning(
                    "responder drain exceeded %.1fs; force-cancelling "
                    "%d in-flight request(s)", self.drain_grace_s,
                    len(handles))
                for handle in handles:
                    if handle.cancel("server drain timeout"):
                        admission.count_cancelled("drain_timeout")
                try:
                    await asyncio.wait_for(asyncio.shield(drain),
                                           timeout=POST_CANCEL_GRACE_S)
                except asyncio.TimeoutError:
                    LOG.warning(
                        "responder drain still wedged after force-"
                        "cancel; proceeding with TSDB teardown (a "
                        "handler ignores its cancellation token)")
            # The drain guarantees the WORK finished; the handler
            # coroutines still need loop time to write their replies.
            # Yield until the last dispatched reply hits its socket
            # (bounded — a dead client can't block shutdown).
            deadline = loop.time() + 5.0
            while self._inflight_rpcs and loop.time() < deadline:
                await asyncio.sleep(0.02)
        finally:
            # A cancelled drain must still release the process-global
            # installs — a CancelledError here would otherwise pin the
            # /logs handler on the root logger forever.
            if self._compile_counting:
                from opentsdb_tpu.obs import jaxprof
                jaxprof.stop_compile_counting()
                self._compile_counting = False
            if self._log_buffer_installed:
                self._log_buffer_installed = False
                from opentsdb_tpu.tsd.admin_rpcs import uninstall_log_buffer
                uninstall_log_buffer()
            self.tsdb.shutdown()
            LOG.info("Server shut down")

    def request_shutdown(self) -> None:
        """Thread-safe shutdown trigger (diediedie).

        Runs on a responder worker thread, so the server loop captured in
        start() is the only safe way back onto the event loop.
        """
        if self._shutdown_event is not None and self._loop is not None:
            self._loop.call_soon_threadsafe(self._shutdown_event.set)

    # -- connection handling --

    async def _handle_connection(self, reader: asyncio.StreamReader,
                                 writer: asyncio.StreamWriter) -> None:
        with self._conn_lock:
            if self.max_connections and \
                    self._open_connections >= self.max_connections:
                self.connections_rejected += 1
                writer.close()
                return
            self._open_connections += 1
            self.connections_established += 1
        peer = writer.get_extra_info("peername")
        remote = "%s:%s" % (peer[0], peer[1]) if peer else "unknown"
        try:
            # First-byte sniff (DetectHttpOrRpc :134): HTTP verbs start with
            # an uppercase ASCII letter; telnet commands are lowercase.
            first = await asyncio.wait_for(reader.read(1),
                                           timeout=self.idle_timeout)
            if not first:
                return
            if b"A" <= first <= b"Z":
                await self._serve_http(first, reader, writer, remote)
            else:
                await self._serve_telnet(first, reader, writer, remote)
        except (asyncio.TimeoutError, asyncio.IncompleteReadError,
                ConnectionResetError, BrokenPipeError):
            pass
        except Exception:
            self.exceptions_caught += 1
            LOG.exception("Unhandled connection error from %s", remote)
        finally:
            with self._conn_lock:
                self._open_connections -= 1
            try:
                writer.close()
                await writer.wait_closed()
            except Exception:
                # best-effort close of an already-failed/finished
                # connection; nothing to serve and nothing to account
                pass  # tsdblint: disable=except-swallow

    # -- telnet path --

    async def _serve_telnet(self, first: bytes, reader: asyncio.StreamReader,
                            writer: asyncio.StreamWriter,
                            remote: str) -> None:
        conn = TelnetConn(writer)
        conn.auth_state = None
        buffer = first
        pending: bytes | None = None
        loop = asyncio.get_running_loop()
        while True:
            if pending is not None:
                line = pending
                pending = None
            else:
                try:
                    line = await asyncio.wait_for(reader.readline(),
                                                  timeout=self.idle_timeout)
                except asyncio.TimeoutError:
                    return
                except ValueError:
                    # StreamReader limit (MAX_TELNET_LINE) exceeded.
                    writer.write(b"error: line too long\n")
                    await writer.drain()
                    return
            data = buffer + line
            buffer = b""
            if len(data) > MAX_TELNET_LINE:
                writer.write(b"error: line too long\n")
                return
            if not line and not data:
                return
            text = data.decode("utf-8", "replace").strip("\r\n")
            if not text:
                if not line:
                    return
                continue
            self.telnet_rpcs += 1
            auth = self.tsdb.authentication
            if auth is not None and not auth.is_ready(self.tsdb, conn):
                # First-message auth (AuthenticationChannelHandler :87-124):
                # the opening command must authenticate the channel.
                from opentsdb_tpu.auth import AuthStatus
                try:
                    state = auth.authenticate_telnet(conn, text.split())
                except Exception:
                    LOG.exception("Authentication plugin failed on telnet "
                                  "command from %s; failing closed", remote)
                    state = None
                if state is not None and state.status == AuthStatus.SUCCESS:
                    conn.auth_state = state
                    writer.write(b"AUTH_SUCCESS\r\n")
                else:
                    # Channel stays open so the caller can retry
                    # (AuthenticationChannelHandler doc).
                    writer.write(b"AUTH_FAIL\r\n")
                await writer.drain()
                continue
            self._inflight_rpcs += 1
            try:
                if auth is None and data.split(None, 1)[:1] == [b"put"]:
                    # Batch consecutive already-buffered put lines into
                    # ONE executor dispatch (the native columnar
                    # ingest): a pipelined writer otherwise pays a
                    # Python parse AND a thread-pool hop PER LINE.  Only
                    # complete lines already in the reader's buffer join
                    # — this never waits for more input, so single-line
                    # latency is unchanged.
                    block = [data]
                    too_long = False
                    while len(block) < 4096 and _has_buffered_line(reader):
                        try:
                            nxt = await reader.readline()
                        except ValueError:
                            # buffered line beyond MAX_TELNET_LINE: land
                            # the lines collected so far, THEN reply the
                            # same error the unpipelined path would
                            too_long = True
                            break
                        if not nxt:
                            break
                        if (len(nxt) > MAX_TELNET_LINE
                                or nxt.split(None, 1)[:1] != [b"put"]):
                            pending = nxt     # main loop handles it next
                            break
                        block.append(nxt)
                    self.telnet_rpcs += len(block) - 1
                    reply = await loop.run_in_executor(
                        self._executor,
                        self.rpc_manager.handle_telnet_batch,
                        conn, b"".join(block))
                    if too_long:
                        if reply:
                            writer.write(reply.encode())
                        writer.write(b"error: line too long\n")
                        await writer.drain()
                        return
                else:
                    reply = await loop.run_in_executor(
                        self._executor, self.rpc_manager.handle_telnet,
                        conn, text)
                if reply:
                    writer.write(reply.encode())
                    await writer.drain()
            finally:
                self._inflight_rpcs -= 1
            if conn.close_after_write or not line:
                return

    # -- HTTP path --

    async def _serve_http(self, first: bytes, reader: asyncio.StreamReader,
                          writer: asyncio.StreamWriter,
                          remote: str) -> None:
        loop = asyncio.get_running_loop()
        buffer = first
        while True:
            try:
                head = parse_http_head(buffer)
                while head is None:
                    chunk = await asyncio.wait_for(reader.read(65536),
                                                   timeout=self.idle_timeout)
                    if not chunk:
                        return
                    buffer += chunk
                    if len(buffer) > MAX_REQUEST_BYTES:
                        writer.write(HttpResponse(status=413).to_bytes(False))
                        return
                    head = parse_http_head(buffer)
            except BadRequestError as e:
                # Malformed request line/headers answer 400 before closing
                # instead of a bare socket reset (ADVICE round-1).
                writer.write(HttpResponse(
                    status=e.status,
                    body=e.message.encode()).to_bytes(False))
                await writer.drain()
                return
            request, offset = head
            length = int(request.headers.get("content-length", "0") or 0)
            if length > MAX_REQUEST_BYTES:
                writer.write(HttpResponse(status=413).to_bytes(False))
                return
            body = buffer[offset:offset + length]
            if len(body) < length:
                # One exact read instead of quadratic += accumulation.
                try:
                    body += await asyncio.wait_for(
                        reader.readexactly(length - len(body)),
                        timeout=self.idle_timeout)
                except asyncio.IncompleteReadError:
                    return
            request.body = body[:length]
            # Bytes past the body begin the next pipelined request: they sit
            # in `buffer` when the whole body arrived up front, or in `body`
            # when the completion loop over-read.  Exactly one is non-empty.
            buffer = buffer[offset + length:] + body[length:]

            self.http_rpcs += 1
            self._inflight_rpcs += 1
            from opentsdb_tpu.tsd import admission
            # cancellation lever: created HERE (the loop owns disconnect
            # detection), bound to the request's Deadline by
            # rpc_manager.handle_http on the responder thread
            handle = admission.CancellationHandle()
            request.cancel_handle = handle
            self._active_handles.add(handle)
            watcher = None
            try:
                fut = loop.run_in_executor(
                    self._executor, self.rpc_manager.handle_http, request,
                    remote)
                if not buffer:
                    # disconnect watcher: while the handler runs, a read
                    # on the (otherwise idle) connection detects the
                    # client going away — EOF flips the cancellation
                    # token so the query releases its permit without
                    # dispatching.  Skipped when pipelined bytes are
                    # already buffered (the client is clearly alive and
                    # the read would race the next request).
                    watcher = asyncio.ensure_future(reader.read(65536))
                    done, _ = await asyncio.wait(
                        {fut, watcher},
                        return_when=asyncio.FIRST_COMPLETED)
                    if watcher.done():
                        try:
                            chunk = watcher.result()
                        except (ConnectionError, OSError):
                            chunk = b""
                        watcher = None
                        if not chunk:
                            if not fut.done() and handle.cancel(
                                    "client disconnected"):
                                admission.count_cancelled(
                                    "client_disconnect")
                        else:
                            # the next pipelined request arrived while
                            # this one executed: keep its bytes
                            buffer = chunk
                query = await fut
                keep_alive = (request.version != "HTTP/1.0"
                              and (request.header("connection")
                                   or "").lower() != "close")
                response = query.response or HttpResponse(status=500)
                writer.write(response.to_bytes(keep_alive))
                await writer.drain()
            finally:
                if watcher is not None:
                    buffer = await self._drain_watcher(watcher, buffer)
                self._active_handles.discard(handle)
                self._inflight_rpcs -= 1
            if not keep_alive:
                return
            if not buffer:
                try:
                    chunk = await asyncio.wait_for(
                        reader.read(65536), timeout=self.idle_timeout)
                except asyncio.TimeoutError:
                    return
                if not chunk:
                    return
                buffer = chunk

    @staticmethod
    async def _drain_watcher(watcher, buffer: bytes) -> bytes:
        """Retire a still-pending disconnect watcher without losing
        bytes: a read that completed in the race window between the
        handler finishing and this cancel holds the next pipelined
        request — prepend-order is preserved because the watcher only
        ever starts when `buffer` was empty."""
        if not watcher.done():
            watcher.cancel()
        try:
            chunk = await watcher
        except asyncio.CancelledError:
            return buffer
        except (ConnectionError, OSError):
            # the connection died under the watcher; the main loop's
            # own next read/write surfaces it
            return buffer
        return buffer + chunk if chunk else buffer

    # -- stats (ConnectionManager.collectStats :89) --

    def collect_stats(self, collector) -> None:
        collector.record("connectionmgr.connections",
                         self.connections_established, "type=total")
        with self._conn_lock:
            collector.record("connectionmgr.connections",
                             self._open_connections, "type=open")
        collector.record("connectionmgr.connections",
                         self.connections_rejected, "type=rejected")
        collector.record("connectionmgr.exceptions", self.exceptions_caught)
        collector.record("rpc.received", self.telnet_rpcs, "type=telnet")
        collector.record("rpc.received", self.http_rpcs, "type=http")
