"""The built-in interactive query UI (VERDICT r3 #6).

Replaces the reference's GWT client (/root/reference/src/tsd/client/
QueryUi.java + 7 files, 3,068 LoC) with one dependency-free page served
at `/`: multiple metric sub-queries with per-metric aggregator /
downsample / rate controls, tag filter rows with metric/tagk/tagv
autocomplete driven by /api/suggest, date range with relative presets,
graph options (size, log axis, y-range, labels), autoreload, and
permalinks via the location hash — the same capability set QueryUi's
MetricForm/DateTimeBox/graph tabs provided, drawing from the /q SVG
endpoint instead of gnuplot PNGs.
"""

UI_PAGE = r"""<!DOCTYPE html>
<html><head><meta charset="utf-8"><title>OpenTSDB-TPU</title>
<style>
 body{font-family:system-ui,sans-serif;margin:0;color:#1a1a2e;background:#fafafa}
 header{background:#16213e;color:#fff;padding:10px 18px;display:flex;
        align-items:baseline;gap:16px}
 header h1{font-size:18px;margin:0} header span{font-size:12px;opacity:.7}
 main{padding:14px 18px}
 fieldset{border:1px solid #ccd;border-radius:6px;margin:0 0 10px;
          background:#fff;padding:8px 12px}
 legend{font-size:12px;font-weight:600;color:#456;padding:0 6px}
 label{font-size:12.5px;margin-right:10px;white-space:nowrap}
 input,select,button{padding:4px 6px;font-size:13px;border:1px solid #bbc;
   border-radius:4px;background:#fff}
 button{cursor:pointer;background:#e8ecf4} button:hover{background:#dde4f0}
 button.primary{background:#2748a0;color:#fff;border-color:#2748a0}
 button.primary:hover{background:#34569f}
 .mrow{border-top:1px dashed #dde;margin-top:8px;padding-top:8px;
       position:relative}
 .mrow:first-of-type{border-top:none;margin-top:0;padding-top:0}
 .tagrow{margin:4px 0 0 18px}
 .del{color:#a33;border-color:#caa}
 #graphbox{background:#fff;border:1px solid #ccd;border-radius:6px;
           margin-top:10px;min-height:80px;padding:6px;overflow:auto}
 #err{color:#a00;white-space:pre-wrap;font-size:13px;margin:8px 0;
      display:none}
 .sugg{position:absolute;background:#fff;border:1px solid #99b;z-index:9;
   list-style:none;margin:0;padding:0;max-height:220px;overflow:auto;
   box-shadow:0 2px 8px rgba(0,0,0,.15)}
 .sugg li{padding:3px 10px;cursor:pointer;font-size:13px}
 .sugg li.sel,.sugg li:hover{background:#dbe6ff}
 .links{font-size:12px;margin-top:14px;color:#567}
 .links a{color:#2748a0}
 .small{font-size:11.5px;color:#678}
</style></head><body>
<header><h1>OpenTSDB-TPU</h1><span>time series database on TPU</span>
</header>
<main>
<fieldset><legend>Time range</legend>
 <label>From <input id=start value="1h-ago" size=16
   title="relative (1h-ago, 2d-ago) or absolute (2013/01/01-12:00:00)"></label>
 <label>To <input id=end size=16 placeholder="now"></label>
 <span class=small>presets:</span>
 <button type=button onclick="preset('5m')">5m</button>
 <button type=button onclick="preset('1h')">1h</button>
 <button type=button onclick="preset('6h')">6h</button>
 <button type=button onclick="preset('1d')">1d</button>
 <button type=button onclick="preset('1w')">1w</button>
 <button type=button onclick="preset('30d')">30d</button>
 <label style="margin-left:14px"><input type=checkbox id=autoreload>
   autoreload every <input id=reloadsecs value=15 size=3> s</label>
</fieldset>
<fieldset id=metrics><legend>Metrics</legend></fieldset>
<div>
 <button type=button onclick="addMetric()">+ Add metric</button>
 <button class=primary type=button onclick="draw()">Graph</button>
 <a id=permalink href="#" style="font-size:12px;margin-left:8px">permalink</a>
</div>
<fieldset style="margin-top:10px"><legend>Graph options</legend>
 <label>Size <input id=wxh value="980x440" size=8></label>
 <label><input type=checkbox id=ylog> log scale</label>
 <label><input type=checkbox id=nokey> hide legend</label>
 <label>Y range <input id=yrange size=9 placeholder="[0:]"></label>
 <label>Y label <input id=ylabel size=10></label>
 <label>Title <input id=title size=14></label>
</fieldset>
<div id=err></div>
<div id=graphbox><span class=small>Build a query and press Graph.</span></div>
<div class=links>
 <a id=asciilink href="#">ascii</a> | <a id=jsonlink href="#">json</a> |
 <a href="/api/version">version</a> | <a href="/api/aggregators">aggregators</a>
 | <a href="/api/stats">stats</a> | <a href="/api/config">config</a>
 | <a href="/logs?json">logs</a></div>
</main>
<noscript>You must have JavaScript enabled.</noscript>
<script>
"use strict";
var AGGS = ["sum","avg","min","max","count","dev","p99"];
fetch('/api/aggregators').then(function(r){return r.json()})
  .then(function(a){AGGS = a; document.querySelectorAll('select.agg,select.dsfn')
    .forEach(refillAggs);});
function refillAggs(sel){
  var cur = sel.value;
  sel.innerHTML = '';
  AGGS.forEach(function(a){var o=document.createElement('option');
    o.textContent=a; sel.appendChild(o);});
  if (AGGS.indexOf(cur) >= 0) sel.value = cur;
  else sel.value = sel.classList.contains('dsfn') ? 'avg' : 'sum';
}

// ---- autocomplete ------------------------------------------------------
var suggBox = null, suggFor = null, suggSel = -1;
function closeSugg(){ if(suggBox){suggBox.remove(); suggBox=null;
  suggFor=null; suggSel=-1;} }
function attachSuggest(input, type, qfn){
  input.autocomplete = 'off';
  var seq = 0;   // drop out-of-order responses for stale prefixes
  input.addEventListener('input', function(){
    var q = qfn ? qfn(input.value) : input.value;
    if (!q){ closeSugg(); return; }
    var mine = ++seq;
    fetch('/api/suggest?type='+type+'&q='+encodeURIComponent(q)+'&max=15')
      .then(function(r){return r.json()}).then(function(names){
        if (mine !== seq) return;
        closeSugg();
        if (!names.length) return;
        suggBox = document.createElement('ul');
        suggBox.className = 'sugg'; suggFor = input;
        names.forEach(function(n){
          var li = document.createElement('li'); li.textContent = n;
          li.onmousedown = function(e){ e.preventDefault();
            input.value = n; closeSugg();
            input.dispatchEvent(new Event('change')); };
          suggBox.appendChild(li); });
        var r = input.getBoundingClientRect();
        suggBox.style.left = (r.left + window.scrollX) + 'px';
        suggBox.style.top = (r.bottom + window.scrollY) + 'px';
        suggBox.style.minWidth = r.width + 'px';
        document.body.appendChild(suggBox);
      });
  });
  input.addEventListener('keydown', function(e){
    if (!suggBox) return;
    var items = suggBox.querySelectorAll('li');
    if (e.key === 'ArrowDown' || e.key === 'ArrowUp'){
      e.preventDefault();
      if (suggSel < 0)   // first keystroke: Down -> first, Up -> last
        suggSel = e.key === 'ArrowDown' ? 0 : items.length - 1;
      else
        suggSel = (suggSel + (e.key === 'ArrowDown' ? 1 : -1)
                   + items.length) % items.length;
      items.forEach(function(li, i){
        li.classList.toggle('sel', i === suggSel); });
    } else if (e.key === 'Enter' && suggSel >= 0){
      e.preventDefault(); input.value = items[suggSel].textContent;
      closeSugg(); input.dispatchEvent(new Event('change'));
    } else if (e.key === 'Escape'){ closeSugg(); }
  });
  input.addEventListener('blur', function(){ setTimeout(closeSugg, 150); });
}

// ---- metric rows -------------------------------------------------------
var mseq = 0;
function addMetric(state){
  state = state || {};
  var id = 'm' + (mseq++);
  var div = document.createElement('div');
  div.className = 'mrow'; div.id = id;
  div.innerHTML =
   '<label>Aggregator <select class=agg></select></label>' +
   '<label>Metric <input class=metric size=30 ' +
     'placeholder="sys.cpu.user"></label>' +
   '<label>Rate <input type=checkbox class=rate></label>' +
   '<label class=small>counter <input type=checkbox class=counter></label>' +
   '<label>Downsample <input class=dsival size=4 placeholder="1m"> ' +
     '<select class=dsfn></select> fill <select class=dsfill>' +
     '<option value="">none</option><option>nan</option><option>null' +
     '</option><option>zero</option></select></label>' +
   '<button type=button class=del onclick="delMetric(\'' + id + '\')">' +
     'remove</button>' +
   '<div class=tags></div>' +
   '<button type=button class=small style="margin-left:18px" ' +
     'onclick="addTag(\'' + id + '\')">+ tag filter</button>';
  document.getElementById('metrics').appendChild(div);
  refillAggs(div.querySelector('select.agg'));
  refillAggs(div.querySelector('select.dsfn'));
  div.querySelector('select.agg').value = state.agg || 'sum';
  div.querySelector('select.dsfn').value = state.dsfn || 'avg';
  div.querySelector('.metric').value = state.metric || '';
  div.querySelector('.rate').checked = !!state.rate;
  div.querySelector('.counter').checked = !!state.counter;
  div.querySelector('.dsival').value = state.dsival || '';
  div.querySelector('.dsfill').value = state.dsfill || '';
  attachSuggest(div.querySelector('.metric'), 'metrics');
  (state.tags || []).forEach(function(t){ addTag(id, t); });
  return div;
}
function delMetric(id){
  var rows = document.querySelectorAll('.mrow');
  if (rows.length > 1) document.getElementById(id).remove();
}
function addTag(mid, t){
  t = t || {};
  var row = document.createElement('span');
  row.className = 'tagrow';
  row.innerHTML = 'tag <input class=tagk size=10 placeholder="host"> = ' +
    '<input class=tagv size=12 placeholder="* or web01 or web*"> ' +
    '<button type=button class=del>x</button> ';
  row.querySelector('button').onclick = function(){ row.remove(); };
  document.getElementById(mid).querySelector('.tags').appendChild(row);
  row.querySelector('.tagk').value = t.k || '';
  row.querySelector('.tagv').value = t.v || '';
  attachSuggest(row.querySelector('.tagk'), 'tagk');
  attachSuggest(row.querySelector('.tagv'), 'tagv',
                function(v){ return v === '*' ? '' : v.replace(/\*/g,''); });
}

// ---- query building ----------------------------------------------------
function metricParam(div){
  var m = div.querySelector('select.agg').value;
  var ival = div.querySelector('.dsival').value.trim();
  if (ival){
    m += ':' + ival + '-' + div.querySelector('select.dsfn').value;
    var fill = div.querySelector('.dsfill').value;
    if (fill) m += '-' + fill;
  }
  if (div.querySelector('.rate').checked)
    m += div.querySelector('.counter').checked ? ':rate{counter}' : ':rate';
  var name = div.querySelector('.metric').value.trim();
  if (!name) return null;
  m += ':' + name;
  var tags = [];
  div.querySelectorAll('.tagrow').forEach(function(row){
    var k = row.querySelector('.tagk').value.trim();
    var v = row.querySelector('.tagv').value.trim();
    if (k && v) tags.push(k + '=' + v);
  });
  if (tags.length) m += '{' + tags.join(',') + '}';
  return m;
}
function buildQuery(extra){
  var parts = ['start=' + encodeURIComponent(
      document.getElementById('start').value || '1h-ago')];
  var end = document.getElementById('end').value.trim();
  if (end) parts.push('end=' + encodeURIComponent(end));
  var any = false;
  document.querySelectorAll('.mrow').forEach(function(div){
    var m = metricParam(div);
    if (m){ parts.push('m=' + encodeURIComponent(m)); any = true; }
  });
  if (!any) return null;
  (extra || []).forEach(function(p){ parts.push(p); });
  return parts.join('&');
}
function graphParams(){
  var p = ['wxh=' + encodeURIComponent(
      document.getElementById('wxh').value || '980x440')];
  if (document.getElementById('ylog').checked) p.push('ylog');
  if (document.getElementById('nokey').checked) p.push('nokey');
  var yr = document.getElementById('yrange').value.trim();
  if (yr) p.push('yrange=' + encodeURIComponent(yr));
  var yl = document.getElementById('ylabel').value.trim();
  if (yl) p.push('ylabel=' + encodeURIComponent(yl));
  var t = document.getElementById('title').value.trim();
  if (t) p.push('title=' + encodeURIComponent(t));
  return p;
}

// ---- state <-> permalink ----------------------------------------------
function stateObj(){
  var ms = [];
  document.querySelectorAll('.mrow').forEach(function(div){
    var tags = [];
    div.querySelectorAll('.tagrow').forEach(function(row){
      var k = row.querySelector('.tagk').value.trim();
      var v = row.querySelector('.tagv').value.trim();
      if (k || v) tags.push({k: k, v: v});
    });
    ms.push({agg: div.querySelector('select.agg').value,
             metric: div.querySelector('.metric').value,
             rate: div.querySelector('.rate').checked,
             counter: div.querySelector('.counter').checked,
             dsival: div.querySelector('.dsival').value,
             dsfn: div.querySelector('select.dsfn').value,
             dsfill: div.querySelector('.dsfill').value,
             tags: tags});
  });
  return {start: document.getElementById('start').value,
          end: document.getElementById('end').value,
          wxh: document.getElementById('wxh').value,
          ylog: document.getElementById('ylog').checked,
          nokey: document.getElementById('nokey').checked,
          yrange: document.getElementById('yrange').value,
          ylabel: document.getElementById('ylabel').value,
          title: document.getElementById('title').value,
          metrics: ms};
}
function loadState(st){
  try {
    document.getElementById('start').value = st.start || '1h-ago';
    document.getElementById('end').value = st.end || '';
    document.getElementById('wxh').value = st.wxh || '980x440';
    document.getElementById('ylog').checked = !!st.ylog;
    document.getElementById('nokey').checked = !!st.nokey;
    document.getElementById('yrange').value = st.yrange || '';
    document.getElementById('ylabel').value = st.ylabel || '';
    document.getElementById('title').value = st.title || '';
    document.getElementById('metrics').innerHTML = '';
    (st.metrics && st.metrics.length ? st.metrics : [{}])
      .forEach(function(m){ addMetric(m); });
  } catch (e) { addMetric(); }
}

// ---- drawing -----------------------------------------------------------
var reloadTimer = null;
function draw(){
  var q = buildQuery(graphParams().concat(['nocache']));
  var err = document.getElementById('err');
  if (!q){ err.textContent = 'Enter at least one metric.';
    err.style.display = 'block'; return; }
  err.style.display = 'none';
  var hash = encodeURIComponent(JSON.stringify(stateObj()));
  history.replaceState(null, '', '#' + hash);
  document.getElementById('permalink').href = '#' + hash;
  document.getElementById('asciilink').href = '/q?' + q + '&ascii';
  document.getElementById('jsonlink').href = '/api/query?' + buildQuery();
  fetch('/q?' + q).then(function(r){
    return r.text().then(function(body){ return {ok: r.ok, body: body}; });
  }).then(function(r){
    if (!r.ok){
      var msg = r.body;
      try { msg = JSON.parse(r.body).error.message; } catch (e) {}
      err.textContent = msg; err.style.display = 'block';
      return;
    }
    document.getElementById('graphbox').innerHTML = r.body;
  }).catch(function(e){
    err.textContent = String(e); err.style.display = 'block';
  });
  clearTimeout(reloadTimer);
  if (document.getElementById('autoreload').checked){
    var secs = parseInt(document.getElementById('reloadsecs').value) || 15;
    reloadTimer = setTimeout(draw, Math.max(secs, 1) * 1000);
  }
}
function preset(span){
  document.getElementById('start').value = span + '-ago';
  document.getElementById('end').value = '';
  draw();
}
document.getElementById('autoreload').addEventListener('change', function(){
  if (!this.checked) clearTimeout(reloadTimer); else draw();
});

// ---- boot --------------------------------------------------------------
if (location.hash.length > 1){
  try { loadState(JSON.parse(decodeURIComponent(location.hash.slice(1)))); }
  catch (e) { addMetric(); }
} else {
  addMetric();
}
</script></body></html>
"""
