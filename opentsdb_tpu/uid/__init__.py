from opentsdb_tpu.uid.unique_id import (
    UniqueId,
    UniqueIdType,
    NoSuchUniqueId,
    NoSuchUniqueName,
    FailedToAssignUniqueIdException,
)

__all__ = [
    "UniqueId", "UniqueIdType", "NoSuchUniqueId", "NoSuchUniqueName",
    "FailedToAssignUniqueIdException",
]
