"""Bidirectional name <-> UID dictionaries.

Reference behavior: /root/reference/src/uid/UniqueId.java (:62) — three
dictionaries (metrics, tagk, tagv) mapping strings to fixed-width byte UIDs
with atomic assignment, prefix `suggest` (max 25, :89), `rename` (:1095) and
`delete` (:1212).  The reference persists these in the `tsdb-uid` HBase table;
here the dictionary is an in-process store with optional snapshot persistence
handled by the storage layer.  Random-UID mode mirrors RandomUniqueId.java.
"""

from __future__ import annotations

import random
import re
import threading
from enum import Enum
from typing import Iterable


class UniqueIdType(Enum):
    METRIC = "metric"
    TAGK = "tagk"
    TAGV = "tagv"

    @staticmethod
    def from_string(value: str) -> "UniqueIdType":
        v = value.lower()
        for t in UniqueIdType:
            if t.value == v:
                return t
        raise ValueError("Invalid type: " + value)


class NoSuchUniqueName(LookupError):
    def __init__(self, kind: str, name: str):
        super().__init__("No such name for '%s': '%s'" % (kind, name))
        self.kind = kind
        self.name = name


class NoSuchUniqueId(LookupError):
    def __init__(self, kind: str, uid: bytes):
        super().__init__("No such unique ID for '%s': %s" % (kind, uid.hex()))
        self.kind = kind
        self.uid = uid


class FailedToAssignUniqueIdException(RuntimeError):
    pass


MAX_SUGGESTIONS = 25  # UniqueId.java:89

_VALID_NAME = re.compile(r"^[-_./a-zA-Z0-9À-ヿ]+$")


def validate_uid_name(what: str, name: str) -> None:
    """Charset check mirroring Tags.validateString (Tags.java) used at assignment."""
    if name is None:
        raise ValueError("Invalid %s: null" % what)
    if not _VALID_NAME.match(name):
        raise ValueError(
            "Invalid %s (\"%s\"): illegal character" % (what, name))


class UniqueId:
    """One name<->UID dictionary of a given kind and byte width."""

    def __init__(self, kind: UniqueIdType, width: int = 3,
                 random_ids: bool = False):
        if width <= 0 or width > 8:
            raise ValueError("Invalid width: %d" % width)
        self.kind = kind
        self.width = width
        self.random_ids = random_ids
        self._lock = threading.RLock()
        # guarded-by: _lock
        self._name_to_id: dict[str, int] = {}
        self._id_to_name: dict[int, str] = {}  # guarded-by: _lock
        # MAXID counter row equivalent (UniqueId.java:79)  # guarded-by: _lock
        self._max_id = 0
        self.cache_hits = 0  # guarded-by: _lock
        self.cache_misses = 0  # guarded-by: _lock
        self.assigned = 0  # guarded-by: _lock
        self._id_filter = None  # UniqueIdFilterPlugin hook  # guarded-by: _lock
        self.on_create = None   # callable(name, uid) on new assignment

    @property
    def max_possible_id(self) -> int:
        return (1 << (8 * self.width)) - 1

    def set_filter(self, plugin) -> None:
        with self._lock:
            self._id_filter = plugin

    # -- lookups --

    def get_id(self, name: str) -> int:
        """Name -> UID, raising NoSuchUniqueName (UniqueId.getId)."""
        # counters bump inside the same hold as the lookup: the lockless
        # form lost increments under concurrent resolution (tsdblint
        # lock-unguarded-mutation)
        with self._lock:
            uid = self._name_to_id.get(name)
            if uid is None:
                self.cache_misses += 1
            else:
                self.cache_hits += 1
        if uid is None:
            raise NoSuchUniqueName(self.kind.value, name)
        return uid

    def get_name(self, uid: int) -> str:
        """UID -> name, raising NoSuchUniqueId (UniqueId.getName)."""
        with self._lock:
            name = self._id_to_name.get(uid)
        if name is None:
            raise NoSuchUniqueId(self.kind.value, self.uid_to_bytes(uid))
        return name

    def has_name(self, name: str) -> bool:
        with self._lock:
            return name in self._name_to_id

    def get_or_create_id(self, name: str) -> int:
        """Assign a new UID if missing (UniqueId.getOrCreateIdAsync :865)."""
        with self._lock:
            uid = self._name_to_id.get(name)
            if uid is not None:
                self.cache_hits += 1
                return uid
            validate_uid_name(self.kind.value, name)
            if self._id_filter is not None and not self._id_filter.allow_uid_assignment(
                    name, self.kind):
                raise FailedToAssignUniqueIdException(
                    "UID assignment denied by filter for " + name)
            if self.random_ids:
                # RandomUniqueId.java: random assignment with retry on collision.
                for _ in range(10):
                    candidate = random.randint(1, self.max_possible_id)
                    if candidate not in self._id_to_name:
                        uid = candidate
                        break
                else:
                    raise FailedToAssignUniqueIdException(
                        "Failed to find a free random UID for " + name)
            else:
                if self._max_id >= self.max_possible_id:
                    raise FailedToAssignUniqueIdException(
                        "All Unique IDs for %s on %d bytes are already assigned!"
                        % (self.kind.value, self.width))
                self._max_id += 1
                uid = self._max_id
            self._name_to_id[name] = uid
            self._id_to_name[uid] = name
            self.assigned += 1
        # Outside the lock: realtime-UID meta hook (UniqueIdAllocator's
        # UIDMeta.storeNew callback under tsd.core.meta.enable_realtime_uid).
        if self.on_create is not None:
            self.on_create(name, uid)
        return uid

    # -- admin (UniqueId.suggest :971, rename :1095, deleteAsync :1212) --

    def suggest(self, prefix: str, max_results: int = MAX_SUGGESTIONS) -> list[str]:
        if max_results <= 0:
            max_results = MAX_SUGGESTIONS
        with self._lock:
            names = sorted(n for n in self._name_to_id if n.startswith(prefix))
        return names[:max_results]

    def rename(self, old_name: str, new_name: str) -> None:
        with self._lock:
            if new_name in self._name_to_id:
                raise ValueError(
                    "An UID with name %s for %s already exists"
                    % (new_name, self.kind.value))
            uid = self._name_to_id.pop(old_name, None)
            if uid is None:
                raise NoSuchUniqueName(self.kind.value, old_name)
            validate_uid_name(self.kind.value, new_name)
            self._name_to_id[new_name] = uid
            self._id_to_name[uid] = new_name

    def delete(self, name: str) -> int:
        with self._lock:
            uid = self._name_to_id.pop(name, None)
            if uid is None:
                raise NoSuchUniqueName(self.kind.value, name)
            self._id_to_name.pop(uid, None)
            return uid

    # -- codec helpers --

    def uid_to_bytes(self, uid: int) -> bytes:
        return uid.to_bytes(self.width, "big")

    def bytes_to_uid(self, raw: bytes) -> int:
        return int.from_bytes(raw, "big")

    def uid_to_hex(self, uid: int) -> str:
        return self.uid_to_bytes(uid).hex().upper()

    def hex_to_uid(self, hexstr: str) -> int:
        return int(hexstr, 16)

    # -- introspection --

    def __len__(self) -> int:
        with self._lock:
            return len(self._name_to_id)

    def names(self) -> Iterable[str]:
        with self._lock:
            return list(self._name_to_id)

    def snapshot(self) -> dict[str, int]:
        with self._lock:
            return dict(self._name_to_id)

    def restore(self, mapping: dict[str, int]) -> None:
        with self._lock:
            self._name_to_id = dict(mapping)
            self._id_to_name = {v: k for k, v in self._name_to_id.items()}
            self._max_id = max(self._id_to_name, default=0)
