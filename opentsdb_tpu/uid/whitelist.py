"""Regex whitelist/blacklist UID filter.

Reference behavior: /root/reference/src/uid/UniqueIdWhitelistFilter.java —
comma-separated regex lists per UID type from tsd.uidfilter.whitelist /
tsd.uidfilter.blacklist-style keys (metric_patterns etc.); a UID may be
assigned only when it matches a whitelist pattern (if any are configured)
and no blacklist pattern.
"""

from __future__ import annotations

import re

from opentsdb_tpu.plugins.spi import UniqueIdFilterPlugin

_KEYS = {
    "metric": ("tsd.uidfilter.metric_whitelist",
               "tsd.uidfilter.metric_blacklist"),
    "tagk": ("tsd.uidfilter.tagk_whitelist", "tsd.uidfilter.tagk_blacklist"),
    "tagv": ("tsd.uidfilter.tagv_whitelist", "tsd.uidfilter.tagv_blacklist"),
}


class UniqueIdWhitelistFilter(UniqueIdFilterPlugin):
    def __init__(self):
        self.whitelists: dict[str, list[re.Pattern]] = {}
        self.blacklists: dict[str, list[re.Pattern]] = {}

    def initialize(self, tsdb) -> None:
        for kind, (wkey, bkey) in _KEYS.items():
            self.whitelists[kind] = self._compile(tsdb.config, wkey)
            self.blacklists[kind] = self._compile(tsdb.config, bkey)

    @staticmethod
    def _compile(config, key: str) -> list[re.Pattern]:
        raw = config.get_string(key) if config.has_property(key) else ""
        return [re.compile(p.strip()) for p in raw.split(",") if p.strip()]

    def allow_uid_assignment(self, name: str, kind) -> bool:
        kind_name = getattr(kind, "value", str(kind)).lower()
        for pattern in self.blacklists.get(kind_name, ()):
            if pattern.search(name):
                return False
        whitelist = self.whitelists.get(kind_name, ())
        if whitelist:
            return any(p.search(name) for p in whitelist)
        return True
