from opentsdb_tpu.utils.config import Config
from opentsdb_tpu.utils import datetime_util as DateTime


def format_ascii_point(metric: str, ts_ms: int, value,
                       tags: dict[str, str]) -> str:
    """Import-compatible datapoint line `metric ts value k=v ...` — the one
    format shared by `tsdb query`, `tsdb scan --importfmt`, /q?ascii, and
    the TextImporter input grammar."""
    tag_str = " ".join("%s=%s" % kv for kv in sorted(tags.items()))
    return "%s %d %s%s" % (metric, ts_ms // 1000, value,
                           (" " + tag_str) if tag_str else "")


__all__ = ["Config", "DateTime", "format_ascii_point"]
