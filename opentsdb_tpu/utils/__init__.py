from opentsdb_tpu.utils.config import Config
from opentsdb_tpu.utils import datetime_util as DateTime

__all__ = ["Config", "DateTime"]
