"""Flat `tsd.*` properties configuration with typed getters.

Reference behavior: /root/reference/src/utils/Config.java (:53, setDefaults :560)
— a properties file of tsd.* keys with hardcoded defaults, typed accessors, and
hot access from every layer.  TPU additions live under the `tsd.tpu.*` prefix.
"""

from __future__ import annotations

import json
import os
from typing import Any

# Defaults mirror Config.setDefaults (Config.java:560-659) plus TPU-native keys.
DEFAULTS: dict[str, str] = {
    "tsd.mode": "rw",
    "tsd.no_diediedie": "false",
    "tsd.network.bind": "0.0.0.0",
    # multi-host mesh (parallel/distributed.py): coordinator "host:port"
    # of process 0 enables jax.distributed; all three must be set
    "tsd.network.distributed.coordinator": "",
    "tsd.network.distributed.num_processes": "0",
    "tsd.network.distributed.process_id": "",
    # request-driven cluster serving (tsd/cluster.py): other TSDs whose
    # stores this one fans /api/query out to (SaltScanner role)
    "tsd.network.cluster.peers": "",
    # overall per-peer-fetch budget, shared across every retry attempt
    "tsd.network.cluster.timeout_ms": "15000",
    # peer-failure stance after retries/breakers: "error" fails the
    # query (the reference's scanner-error stance); "allow" answers 200
    # with the surviving peers' data + exec_stats partialResults /
    # clusterPeersFailed annotations
    "tsd.network.cluster.partial_results": "error",
    # retry/backoff for peer raw-series fetches (utils/retry.py).
    # attempt_timeout 0 = each attempt may use the full remaining
    # budget, so a slow-but-healthy peer keeps the window it had before
    # retries existed; fast failures (refused, reset, garbage) leave
    # most of the budget for their retries
    "tsd.network.cluster.retry.max_attempts": "3",
    "tsd.network.cluster.retry.attempt_timeout_ms": "0",
    # per-peer circuit breaker: open after N consecutive fetch failures
    # (0 disables), half-open probe after the cooldown; state surfaces
    # via /api/stats (cluster.breaker.*)
    "tsd.network.cluster.breaker.threshold": "5",
    "tsd.network.cluster.breaker.cooldown_ms": "5000",
    # fault injection (utils/faults.py): inline JSON spec list or @path.
    # A testing/chaos surface — NEVER arm in production.
    "tsd.faults.config": "",
    "tsd.network.port": "",
    "tsd.network.worker_threads": "",
    "tsd.network.async_io": "true",
    "tsd.network.tcp_no_delay": "true",
    "tsd.network.keep_alive": "true",
    "tsd.network.reuse_address": "true",
    "tsd.core.authentication.enable": "false",
    "tsd.core.authentication.plugin": "",
    "tsd.core.auto_create_metrics": "false",
    "tsd.core.auto_create_tagks": "true",
    "tsd.core.auto_create_tagvs": "true",
    "tsd.core.connections.limit": "0",
    "tsd.core.enable_api": "true",
    "tsd.core.enable_ui": "true",
    "tsd.core.histograms.config": "",
    "tsd.core.meta.enable_realtime_ts": "false",
    "tsd.core.meta.enable_realtime_uid": "false",
    "tsd.core.meta.enable_tsuid_incrementing": "false",
    "tsd.core.meta.enable_tsuid_tracking": "false",
    "tsd.core.meta.cache.enable": "false",
    "tsd.core.meta.cache.plugin": "",
    "tsd.core.plugin_path": "",
    "tsd.core.response.async": "true",
    "tsd.core.socket.timeout": "0",
    "tsd.core.tree.enable_processing": "false",
    "tsd.core.preload_uid_cache": "false",
    "tsd.core.preload_uid_cache.max_entries": "300000",
    "tsd.core.storage_exception_handler.enable": "false",
    "tsd.core.storage_exception_handler.plugin": "",
    "tsd.core.uid.random_metrics": "false",
    "tsd.core.bulk.allow_out_of_order_timestamps": "false",
    "tsd.core.timezone": "UTC",
    "tsd.query.filter.expansion_limit": "4096",
    "tsd.query.skip_unresolved_tagvs": "false",
    "tsd.query.allow_simultaneous_duplicates": "true",
    "tsd.query.enable_fuzzy_filter": "true",
    "tsd.query.limits.bytes.default": "0",
    "tsd.query.limits.bytes.allow_override": "false",
    "tsd.query.limits.data_points.default": "0",
    "tsd.query.limits.data_points.allow_override": "false",
    "tsd.query.limits.overrides.config": "",
    "tsd.query.limits.overrides.interval": "60000",
    # TPU-native: /api/query mesh serving (the salt-scanner fan-out analog).
    # min_series gates the mesh to batches wide enough to amortize the
    # collective latency; below it the single-dispatch grouped path serves.
    "tsd.query.mesh.enable": "true",
    "tsd.query.mesh.min_series": "8",
    # Small-query fast lane: below this many scanned points a query's
    # dispatch runs the SAME jitted pipeline on the host CPU platform —
    # the accelerator dispatch floor (tunnel RTT + launch + transfer)
    # dwarfs the compute at this scale (VERDICT r3 weak #2).  0 disables.
    "tsd.query.host_lane.max_points": "2000000",
    # TPU-native: streaming (chunked) execution for beyond-memory queries.
    # Queries selecting more than point_threshold datapoints stream through
    # the device in chunk_points-sized slices instead of materializing one
    # [S, N] batch in host memory (SaltScanner's overlapped-scan analog).
    "tsd.query.streaming.point_threshold": "8000000",
    "tsd.query.streaming.chunk_points": "4000000",
    # rank-based downsample fns stream via the mergeable quantile summary
    # (approximate, rank error ~chunks/(2K)); false = materialize instead,
    # subject to the scan budgets
    "tsd.query.streaming.sketch_percentiles": "true",
    # auto-protect (VERDICT r3 #7): when one (series, window) cell would
    # absorb more than this many chunk merges (window span >> chunk span,
    # e.g. "0all" over a huge range, worst-case rank drift ~merges/128),
    # the planner routes to the exact materialized path — which the scan
    # budgets then admit or 413 — instead of silently drifting.  0 trusts
    # the sketch unconditionally.
    "tsd.query.streaming.sketch_max_merges": "4",
    # refuse queries whose streaming accumulator grid (S x W x lanes)
    # would exceed this many MB of device memory (0 = unlimited); the
    # 413 points the operator at a coarser interval or a shorter range
    "tsd.query.streaming.state_mb": "6144",
    # TPU-native: device-resident series cache (the BlockCache analog) —
    # hot metrics' columns pinned in HBM; repeat queries assemble their
    # batch on-device with zero host->device data traffic.  Size is a
    # byte budget (LRU); metrics beyond build_max_points are never cached
    # (the streaming path owns beyond-memory scans).
    "tsd.query.device_cache.enable": "true",
    "tsd.query.device_cache.mb": "4096",
    "tsd.query.device_cache.build_max_points": "200000000",
    "tsd.query.device_cache.batch_mb": "6144",
    # Hot-path kernel strategies (chip-A/B'd by bench_prefix.py; the
    # measurement session records winners in BENCH_WINNERS.json).  Empty
    # keeps the module defaults / TSDB_*_MODE env; every form carries
    # shape guards that demote it off losing shapes regardless.
    # empty = module default ("auto": the ops/costmodel.py shape chooser)
    "tsd.query.kernel.scan_mode": "",          # auto|flat|blocked|subblock|subblock2
    "tsd.query.kernel.search_mode": "",        # auto|scan|compare_all|hier
    "tsd.query.kernel.extreme_mode": "",       # auto|scan|segment|subblock
    "tsd.query.kernel.group_reduce_mode": "",  # auto|segment|matmul|sorted
    # Demote dense (accelerator-winner) search forms to the binary scan
    # on CPU execution — the planner's small-query host lane included
    # (measured 18x slower there under the chip-crowned modes).  Empty
    # keeps the module default (on); "false" opts out.
    "tsd.query.kernel.platform_guard": "",
    # Streamed chunks take the segment form when W > ratio * N (or the
    # TSDB_STREAM_SEGMENT_RATIO env); empty keeps the module default.
    "tsd.query.kernel.stream_segment_ratio": "",
    "tsd.query.multi_get.enable": "false",
    "tsd.query.multi_get.limit": "131072",
    "tsd.query.multi_get.batch_size": "1024",
    "tsd.query.multi_get.concurrent": "20",
    "tsd.query.multi_get.get_all_salts": "false",
    "tsd.query.timeout": "0",
    "tsd.rpc.plugins": "",
    "tsd.rpc.telnet.return_errors": "true",
    "tsd.rollups.enable": "false",
    "tsd.rollups.config": "",
    "tsd.rollups.tag_raw": "false",
    "tsd.rollups.agg_tag_key": "_aggregate",
    "tsd.rollups.raw_agg_tag_value": "RAW",
    "tsd.rollups.block_derived": "true",
    "tsd.rollups.split_query.enable": "false",
    "tsd.rtpublisher.enable": "false",
    "tsd.rtpublisher.plugin": "",
    "tsd.search.enable": "false",
    "tsd.search.plugin": "",
    "tsd.stats.canonical": "false",
    "tsd.startup.enable": "false",
    "tsd.startup.plugin": "",
    "tsd.storage.fix_duplicates": "false",
    "tsd.storage.flush_interval": "1000",
    "tsd.storage.data_table": "tsdb",
    "tsd.storage.uid_table": "tsdb-uid",
    "tsd.storage.tree_table": "tsdb-tree",
    "tsd.storage.meta_table": "tsdb-meta",
    "tsd.storage.enable_appends": "false",
    "tsd.storage.repair_appends": "false",
    "tsd.storage.enable_compaction": "true",
    "tsd.storage.compaction.flush_interval": "10",
    "tsd.storage.compaction.min_flush_threshold": "100",
    "tsd.storage.compaction.max_concurrent_flushes": "10000",
    "tsd.storage.compaction.flush_speed": "2",
    # TPU-native durability cadences (maintenance thread; 0 = disabled).
    "tsd.storage.wal_sync_interval": "0",
    # opt-in per-append WAL fsync: every journaled record hits the disk
    # barrier before the write acks (crash-consistent at ingest cost;
    # the default leans on the wal_sync_interval cadence instead)
    "tsd.storage.wal.fsync": "false",
    "tsd.storage.snapshot_interval": "0",
    # Compressed binary snapshots via the native chunk engine (native/);
    # falls back to npz automatically when the library can't build.
    "tsd.storage.native_snapshot": "true",
    "tsd.storage.salt.width": "0",
    "tsd.storage.salt.buckets": "20",
    "tsd.storage.uid.width.metric": "3",
    "tsd.storage.uid.width.tagk": "3",
    "tsd.storage.uid.width.tagv": "3",
    "tsd.storage.max_tags": "8",
    "tsd.storage.directory": "",
    "tsd.timeseriesfilter.enable": "false",
    "tsd.timeseriesfilter.plugin": "",
    "tsd.uid.use_mode": "false",
    "tsd.uid.lru.enable": "false",
    "tsd.uid.lru.name.size": "5000000",
    "tsd.uid.lru.id.size": "5000000",
    "tsd.uidfilter.enable": "false",
    "tsd.uidfilter.plugin": "",
    "tsd.core.stats_with_port": "false",
    "tsd.http.show_stack_trace": "true",
    "tsd.http.query.allow_delete": "false",
    "tsd.http.header_tag": "",
    "tsd.http.request.enable_chunked": "true",
    "tsd.http.request.max_chunk": "1048576",
    "tsd.http.request.cors_domains": "",
    "tsd.http.request.cors_headers": (
        "Authorization, Content-Type, Accept, Origin, User-Agent, DNT, "
        "Cache-Control, X-Mx-ReqToken, Keep-Alive, X-Requested-With, "
        "If-Modified-Since"),
    "tsd.http.cachedir": "",
    "tsd.http.staticroot": "",
    # --- TPU-native knobs (no reference equivalent) ---
    "tsd.tpu.enable": "true",
    "tsd.tpu.mesh.shards": "0",            # 0 = use all visible devices
    "tsd.tpu.batch.max_series": "4096",
    "tsd.tpu.batch.pad_pow2": "true",
    "tsd.tpu.precision.x64": "true",
}

_SECRET_MARKERS = ("pass", "key", "secret", "token")


class Config:
    """Typed accessor over a flat key->string map, file- and dict-loadable."""

    def __init__(self, properties: dict[str, Any] | None = None,
                 config_file: str | None = None, auto_load: bool = False):
        self._map: dict[str, str] = dict(DEFAULTS)
        self.config_location: str | None = None
        if auto_load and config_file is None:
            for candidate in ("./opentsdb.conf", "/etc/opentsdb/opentsdb.conf"):
                if os.path.isfile(candidate):
                    config_file = candidate
                    break
        if config_file:
            self.load_file(config_file)
        if properties:
            for k, v in properties.items():
                self._map[k] = self._stringify(v)

    @staticmethod
    def _stringify(value: Any) -> str:
        if isinstance(value, bool):
            return "true" if value else "false"
        return str(value)

    def load_file(self, path: str) -> None:
        with open(path, "r") as fh:
            for line in fh:
                line = line.strip()
                if not line or line.startswith("#") or line.startswith("!"):
                    continue
                if "=" not in line:
                    continue
                key, _, value = line.partition("=")
                self._map[key.strip()] = value.strip()
        self.config_location = path

    # -- typed getters (Config.java getString/getInt/getBoolean...) --

    def has_property(self, key: str) -> bool:
        return key in self._map

    def get_string(self, key: str) -> str:
        if key not in self._map:
            raise KeyError(key)
        return self._map[key]

    def get_int(self, key: str) -> int:
        return int(self.get_string(key))

    def get_float(self, key: str) -> float:
        return float(self.get_string(key))

    def get_bool(self, key: str) -> bool:
        value = self.get_string(key).strip().lower()
        return value in ("1", "true", "yes")

    def get_directory_name(self, key: str) -> str:
        path = self.get_string(key)
        if path and not path.endswith(os.sep):
            path += os.sep
        return path

    def override_config(self, key: str, value: Any) -> None:
        self._map[key] = self._stringify(value)

    def as_map(self, obfuscate: bool = True) -> dict[str, str]:
        """Full config dump for /api/config; secrets hidden like the reference."""
        out = {}
        for key, value in sorted(self._map.items()):
            if obfuscate and any(m in key.lower() for m in _SECRET_MARKERS):
                out[key] = "********"
            else:
                out[key] = value
        return out

    def dump_json(self) -> str:
        return json.dumps(self.as_map(), indent=2)

    # -- convenience flags used on hot paths --

    @property
    def auto_metric(self) -> bool:
        return self.get_bool("tsd.core.auto_create_metrics")

    @property
    def enable_compactions(self) -> bool:
        return self.get_bool("tsd.storage.enable_compaction")

    @property
    def fix_duplicates(self) -> bool:
        return self.get_bool("tsd.storage.fix_duplicates")

    @property
    def salt_width(self) -> int:
        return self.get_int("tsd.storage.salt.width")

    @property
    def salt_buckets(self) -> int:
        return self.get_int("tsd.storage.salt.buckets")
