"""Flat `tsd.*` properties configuration with typed getters and a schema.

Reference behavior: /root/reference/src/utils/Config.java (:53, setDefaults
:560) — a properties file of tsd.* keys with hardcoded defaults, typed
accessors, and hot access from every layer.  TPU additions live under the
`tsd.tpu.*` prefix.

Every key the codebase reads is declared in ``CONFIG_SCHEMA`` (key ->
type, default, doc); ``DEFAULTS`` is derived from it.  The tsdblint
config analyzer (tools/lint/config_schema.py) holds every ``tsd.*``
literal in the package to this registry — unknown keys, typed-getter
mismatches, and dead entries all fail tier-1 — and
``generate_config_doc()`` renders docs/configuration.md from it, so the
reference doc cannot drift from the code.

Keys marked ``compat=True`` are accepted from reference opentsdb.conf
files but not (yet) read by this codebase; they are excluded from the
dead-key check and flagged in the generated doc.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass
from typing import Any


@dataclass(frozen=True)
class ConfigEntry:
    """One declared key: accessor type, default (always a string — the
    properties file is untyped), one-line doc, and the compat flag."""
    type: str           # "str" | "int" | "float" | "bool" | "dir"
    default: str
    doc: str
    compat: bool = False


def _e(type: str, default: Any, doc: str, compat: bool = False
       ) -> ConfigEntry:
    if isinstance(default, bool):
        default = "true" if default else "false"
    return ConfigEntry(type, str(default), doc, compat)


CONFIG_SCHEMA: dict[str, ConfigEntry] = {
    # -- daemon -------------------------------------------------------- #
    "tsd.mode": _e("str", "rw",
                   "Operation mode: rw, ro (reads only) or wo (writes "
                   "only); gates which RPC routes mount."),
    "tsd.no_diediedie": _e("bool", False,
                           "Disable the telnet/HTTP diediedie shutdown "
                           "command."),
    "tsd.network.bind": _e("str", "0.0.0.0",
                           "Address the TSD listens on."),
    "tsd.network.port": _e("int", "",
                           "TCP port to serve on (telnet + HTTP on one "
                           "socket); empty defers to the CLI --port."),
    "tsd.network.keep_alive_timeout": _e(
        "int", "300",
        "Idle seconds before an open connection is dropped."),
    "tsd.network.drain_timeout_ms": _e(
        "int", "30000",
        "Graceful-shutdown budget for in-flight responder work; at "
        "expiry every in-flight request's cancellation token is "
        "force-flipped so cooperative handlers unwind, then teardown "
        "proceeds regardless after a short grace."),
    "tsd.network.worker_threads": _e(
        "int", "", "Responder thread count (reference compat; the "
        "daemon takes --worker-threads).", compat=True),
    "tsd.network.async_io": _e("bool", True,
                               "Reference compat; I/O is always async "
                               "here.", compat=True),
    "tsd.network.tcp_no_delay": _e("bool", True,
                                   "Reference compat socket flag.",
                                   compat=True),
    "tsd.network.keep_alive": _e("bool", True,
                                 "Reference compat socket flag.",
                                 compat=True),
    "tsd.network.reuse_address": _e("bool", True,
                                    "Reference compat socket flag.",
                                    compat=True),
    # -- multi-host mesh (parallel/distributed.py) --------------------- #
    "tsd.network.distributed.coordinator": _e(
        "str", "", "Coordinator host:port of process 0; setting it (plus "
        "num_processes/process_id) enables jax.distributed."),
    "tsd.network.distributed.num_processes": _e(
        "int", "0", "Process count of the distributed mesh."),
    "tsd.network.distributed.process_id": _e(
        "int", "", "This process's rank in the distributed mesh."),
    # -- request-driven cluster serving (tsd/cluster.py) --------------- #
    "tsd.network.cluster.peers": _e(
        "str", "", "Comma-separated host:port of the OTHER TSDs whose "
        "stores /api/query fans out to (empty = single-host serving)."),
    "tsd.network.cluster.timeout_ms": _e(
        "int", "15000", "Overall per-peer-fetch budget, shared across "
        "every retry attempt."),
    "tsd.network.cluster.partial_results": _e(
        "str", "error", "Peer-failure stance after retries/breakers: "
        "'error' fails the query; 'allow' answers 200 with surviving "
        "peers' data plus partialResults annotations."),
    "tsd.network.cluster.retry.max_attempts": _e(
        "int", "3", "Attempts per peer raw-series fetch "
        "(utils/retry.py capped exponential backoff)."),
    "tsd.network.cluster.retry.attempt_timeout_ms": _e(
        "int", "0", "Per-attempt deadline; 0 = each attempt may use the "
        "full remaining budget."),
    "tsd.network.cluster.breaker.threshold": _e(
        "int", "5", "Consecutive fetch failures that open a peer's "
        "circuit breaker (0 disables breakers)."),
    "tsd.network.cluster.breaker.cooldown_ms": _e(
        "int", "5000", "Open -> half-open probe delay; breaker state "
        "surfaces via /api/stats (cluster.breaker.*)."),
    # -- sharded ownership + replication (tsd/replication.py) ---------- #
    "tsd.network.cluster.self": _e(
        "str", "", "host:port identity of THIS node on the shard ring "
        "(how peers reach it).  Required when shard.enable is true."),
    "tsd.network.cluster.shard.enable": _e(
        "bool", False, "Consistent-hash series ownership across the "
        "cluster: each (metric, tags) series gets an owner + replica "
        "set, ingest routes to the owner, and queries fan out only to "
        "the owning shards' healthy members (docs/replication.md)."),
    "tsd.network.cluster.shard.count": _e(
        "int", "64", "Logical shards the series key space hashes into; "
        "the unit of ownership, failover, and anti-entropy comparison."),
    "tsd.network.cluster.shard.virtual_nodes": _e(
        "int", "32", "Virtual nodes per peer on the consistent-hash "
        "ring — evens shard placement and bounds rebalance movement to "
        "~1/n of the shards when a peer joins or leaves."),
    "tsd.network.cluster.shard.replicas": _e(
        "int", "2", "Replication factor: copies of each shard "
        "(owner included).  1 = unreplicated single-copy serving (the "
        "pre-replication behavior)."),
    "tsd.replication.max_inflight_mb": _e(
        "int", "64", "Byte bound on concurrently-processing "
        "replication ship/tail bodies.  Replication traffic is exempt "
        "from the query admission gate; this is its own backpressure "
        "(excess requests answer 503 and the sender falls back to the "
        "pull cadence)."),
    "tsd.replication.pull_interval_ms": _e(
        "int", "1000", "Replica catch-up cadence: how often each node "
        "pulls peers' WAL tails (/api/replication/tail) to fill gaps "
        "the synchronous ship path missed."),
    "tsd.replication.ship_timeout_ms": _e(
        "int", "5000", "Per-replica budget for the synchronous WAL "
        "ship on the ingest ack path; a replica that cannot answer "
        "within it is served by the pull cadence instead."),
    "tsd.replication.tail_batch_mb": _e(
        "int", "4", "Payload bound per /api/replication/tail page; a "
        "catching-up replica iterates pages until it reaches the "
        "owner's last sequence number."),
    # -- fault injection (utils/faults.py) ----------------------------- #
    "tsd.faults.config": _e(
        "str", "", "Fault-injection spec: inline JSON list or @path. "
        "A testing/chaos surface — NEVER arm in production.  Specs are "
        "validated against the registered hook sites at startup."),
    # -- runtime sanitizer (tools/sanitize, armed by tsd_main) --------- #
    "tsd.sanitizer.enable": _e(
        "bool", False, "Arm the tsdbsan runtime sanitizer (instrumented "
        "locks, write interception, deadlock watchdog) at daemon "
        "startup.  A testing/chaos surface — adds per-write overhead; "
        "never arm in production."),
    "tsd.sanitizer.lockset.enable": _e(
        "bool", True, "Lockset race detector: verify guarded-by "
        "annotations at runtime and run Eraser-style lockset "
        "intersection on unannotated shared attributes."),
    "tsd.sanitizer.deadlock.enable": _e(
        "bool", True, "Deadlock watcher: runtime lock-order graph, "
        "inversion detection, and the live wait-for-cycle watchdog."),
    "tsd.sanitizer.deadlock.watchdog_ms": _e(
        "int", "200", "Wait-for-cycle watchdog scan period in ms "
        "(0 disables the background thread; order-graph recording "
        "stays on)."),
    "tsd.sanitizer.jax.enable": _e(
        "bool", False, "JAX compile/sync accounting in the daemon "
        "(compile events per kernel; steady-phase gating is driven by "
        "the test harness, not the daemon)."),
    "tsd.sanitizer.report.path": _e(
        "str", "", "Write the sanitizer findings report here at "
        "daemon shutdown (JSON, or SARIF when the path ends in "
        ".sarif).  Empty = no report artifact."),
    # -- observability (opentsdb_tpu/obs/, docs/observability.md) ------ #
    "tsd.trace.enable": _e(
        "bool", True, "Trace query serving: a span tree per request "
        "(scan/pipeline stages, cluster fan-out with retry/breaker "
        "annotations) surfaced inline via showStats and in the "
        "/api/stats/query ring."),
    "tsd.trace.device_time": _e(
        "bool", True, "Record per-stage device time on traced requests "
        "by syncing on stage outputs at stage boundaries "
        "(block_until_ready; a sanctioned sync site).  False keeps "
        "spans wall-time-only and dispatches fully asynchronous."),
    "tsd.stats.interval": _e(
        "int", "0", "Seconds between self-report passes writing the "
        "daemon's own tsd.* metrics into its local store through the "
        "normal ingest path (0 = disabled).  The TSD becomes queryable "
        "about itself via ordinary /api/query."),
    # -- flight recorder + diagnostics (obs/flightrec.py) --------------- #
    "tsd.diag.enable": _e(
        "bool", True, "Arm the always-on flight recorder: a bounded "
        "ring of structured diagnostic events (admission verdicts, "
        "cache/rollup consults, spills, autotune flips, breaker "
        "transitions, deadline expiries, recompiles) served at "
        "/api/diag and dumped at shutdown.  Also gates /api/diag/slow."),
    "tsd.diag.ring_size": _e(
        "int", "4096", "Flight-recorder ring capacity in events; "
        "overflow drops the oldest."),
    "tsd.diag.dump_path": _e(
        "str", "", "Write the flight-recorder black box (ring + slow "
        "captures, JSON) here at shutdown/SIGTERM.  Empty = no dump "
        "artifact."),
    # -- latency attribution (obs/latattr.py) --------------------------- #
    "tsd.latattr.enable": _e(
        "bool", True, "Always-on latency attribution: the RPC layer "
        "stamps every request at fixed phases (parse, admission wait, "
        "plan, batch rendezvous, dispatch, device wait, serialize, "
        "flush) and folds the deltas into bounded streaming histograms "
        "keyed by (route, plan fingerprint, clamped tenant), served at "
        "/api/diag/latency.  Independent of tracing — answers 'where "
        "did the milliseconds go' with tsd.trace.enable off."),
    "tsd.latattr.max_profiles": _e(
        "int", "256", "Bound on distinct (route, fingerprint, tenant) "
        "latency-attribution profiles held in memory; requests beyond "
        "it collapse into a single overflow profile (counted by "
        "tsd.latattr.profile_overflow) so cardinality storms cannot "
        "grow the table."),
    "tsd.diag.slow_ms": _e(
        "int", "0", "Absolute slow-query capture threshold in ms: a "
        "query at least this slow retains its span tree + "
        "flight-recorder slice at /api/diag/slow without showStats.  "
        "0 disables the absolute arm."),
    "tsd.diag.slow_quantile": _e(
        "float", "0.99", "Rolling-quantile slow-capture arm: capture "
        "queries above this quantile of the recorder's own latency "
        "histogram (active once enough samples accrue).  0 disables."),
    "tsd.diag.slow_keep": _e(
        "int", "32", "Bounded slow-query store capacity; overflow "
        "drops the oldest capture."),
    "tsd.diag.exemplars": _e(
        "bool", False, "Emit OpenMetrics-style exemplar COMMENT lines "
        "(trace ids per histogram bucket) on /api/stats/prometheus, "
        "linking tail-latency buckets to flight-recorder traces.  The "
        "text format stays 0.0.4-parseable."),
    "tsd.diag.tenants": _e(
        "str", "", "Comma-separated registered tenant names for the "
        "X-TSDB-Tenant header.  Registered tenants keep their name as "
        "a metric label; everything else hashes into "
        "tsd.diag.tenant_buckets buckets (cardinality clamp)."),
    "tsd.diag.tenant_buckets": _e(
        "int", "16", "Hash buckets for unregistered tenant header "
        "values (0 collapses them all to 'other')."),
    # -- query explain (query/explain.py, docs/query_explain.md) -------- #
    "tsd.explain.enable": _e(
        "bool", True, "Mount /api/query/explain: the no-dispatch "
        "what-if engine returning the complete routing decision tree "
        "(admission preview, rollup/agg-cache/device-cache consults, "
        "grid-budget/tiling verdict, per-axis costmodel pricing) plus "
        "the stable plan fingerprint executed queries stamp into "
        "flight-recorder plan events."),
    "tsd.explain.include_candidates": _e(
        "bool", True, "Include the per-candidate predicted-ms tables "
        "in explain's costmodel decision reports.  False keeps only "
        "the chosen mode + provenance (smaller payloads for "
        "dashboard-driven polling)."),
    # -- health engine (obs/health.py) ---------------------------------- #
    "tsd.health.enable": _e(
        "bool", True, "Evaluate the declared health invariants "
        "(shed burn, steady-state recompiles, cache hit collapse, "
        "costmodel drift, spill saturation, breaker flap) into "
        "per-subsystem ok/degraded/failing verdicts at "
        "/api/diag/health and tsd.health.* gauges."),
    "tsd.health.interval": _e(
        "int", "10", "Seconds between health-engine passes on the "
        "maintenance cadence (each pass judges the window since the "
        "previous one)."),
    "tsd.health.shed_rate": _e(
        "float", "0.5", "Admission sheds per second over the window "
        "above which the admission subsystem reads degraded "
        "(failing at 4x)."),
    "tsd.health.recompile_warmup": _e(
        "int", "120", "Seconds after startup before the steady-state "
        "recompile invariant arms (first-touch compiles are "
        "legitimate)."),
    "tsd.health.recompile_limit": _e(
        "int", "0", "XLA compilations tolerated per window once "
        "warmed up; beyond it the compile subsystem reads degraded "
        "(failing past limit+4)."),
    "tsd.health.cache_hit_floor": _e(
        "float", "0.05", "Aggregate-cache hit fraction under which a "
        "busy window (>= 16 consults) reads degraded — the hit-rate-"
        "collapse invariant."),
    "tsd.health.costmodel_drift": _e(
        "float", "40", "Predicted-vs-actual device-ms ratio (either "
        "direction) above which the costmodel subsystem reads "
        "degraded (failing at 4x); volume-gated."),
    "tsd.health.spill_saturation": _e(
        "float", "0.9", "Spill-pool resident fraction of the combined "
        "host+disk budget above which the spill subsystem reads "
        "degraded (failing at 100%)."),
    "tsd.health.breaker_flap": _e(
        "int", "3", "Circuit-breaker open transitions per window "
        "above which the cluster subsystem reads degraded (failing "
        "at 2x); any breaker currently open is at least degraded."),
    "tsd.health.tenant_share_ratio": _e(
        "float", "10", "Cross-tenant starvation bound: among tenants "
        "with meaningful window demand, the max/min admitted-share "
        "ratio above which the tenant subsystem reads degraded "
        "(failing when a demanding tenant was admitted NOTHING while "
        "others were served)."),
    "tsd.health.replication_lag": _e(
        "int", "500", "Replication-lag burn bound: growth of the "
        "worst replica's unacknowledged WAL backlog (records) per "
        "window above which the replication subsystem reads degraded "
        "(failing at 4x); any under-replicated shard is at least "
        "degraded."),
    "tsd.health.phase_share": _e(
        "float", "0.5", "Phase-share burn budget: the serialize "
        "phase's share of the window's total attributed request time "
        "(obs/latattr.py) above which the latency subsystem reads "
        "degraded (failing at 2x).  Serialize is pure host-side "
        "overhead — the continuous production form of tsdbsan's "
        "serialize pin."),
    "tsd.health.diag_drop_rate": _e(
        "float", "50", "Evidence-loss bound: flight-recorder ring "
        "overflow drops per second over the window above which the "
        "diag subsystem reads degraded (failing at 4x) — a steadily "
        "overflowing ring means the next incident's history is "
        "already gone."),
    # -- costmodel autotune (ops/calibrate.py, docs/costmodel.md) ------ #
    "tsd.costmodel.autotune.enable": _e(
        "bool", False, "Online costmodel calibration: fit the kernel-"
        "strategy per-unit constants from the live predicted-vs-actual "
        "segment ring (obs/jaxprof.py) on the maintenance cadence and "
        "install them as a live override layer, so choose_* converges "
        "to what this hardware measures.  Requires traced serving with "
        "device timing (tsd.trace.enable + tsd.trace.device_time)."),
    "tsd.costmodel.autotune.interval": _e(
        "int", "30", "Seconds between calibration fits (and the length "
        "of an epsilon-exploration interval)."),
    "tsd.costmodel.autotune.min_samples": _e(
        "int", "64", "Fittable ring entries required before a fit runs "
        "— below this the window is too noisy to trust."),
    "tsd.costmodel.autotune.hysteresis": _e(
        "float", "0.15", "Sticky-argmin band: a challenger mode must "
        "predict this fraction cheaper than a shape bucket's incumbent "
        "before the strategy choice (and its jit caches) flips.  0 "
        "restores the pure argmin."),
    "tsd.costmodel.autotune.epsilon": _e(
        "float", "0", "Probability per calibration pass of forcing one "
        "losing-but-feasible mode for one interval so the fitter "
        "observes actuals for strategies the argmin never picks.  Off "
        "by default: exploration dispatches deliberately-slower "
        "kernels."),
    "tsd.costmodel.autotune.max_step": _e(
        "float", "4", "Bound on how far one fit may move a per-unit "
        "constant (multiplier clipped into [1/max_step, max_step]); "
        "convergence stays geometric and one wild batch is bounded."),
    "tsd.costmodel.autotune.persist": _e(
        "bool", True, "Merge the live-fitted constants into the "
        "calibration file at shutdown so calibration survives "
        "restarts."),
    "tsd.costmodel.autotune.calibration_file": _e(
        "str", "", "Calibration file path for both the file override "
        "layer and shutdown persistence; empty = BENCH_CALIBRATION."
        "json at the repo root."),
    # -- core ---------------------------------------------------------- #
    "tsd.core.authentication.enable": _e(
        "bool", False, "Require telnet/HTTP authentication."),
    "tsd.core.authentication.plugin": _e(
        "str", "", "Authentication plugin class path."),
    "tsd.core.auto_create_metrics": _e(
        "bool", False, "Assign UIDs to unseen metric names on ingest "
        "instead of rejecting the point."),
    "tsd.core.auto_create_tagks": _e(
        "bool", True, "Assign UIDs to unseen tag keys on ingest."),
    "tsd.core.auto_create_tagvs": _e(
        "bool", True, "Assign UIDs to unseen tag values on ingest."),
    "tsd.core.connections.limit": _e(
        "int", "0", "Max concurrent open connections (0 = unlimited)."),
    "tsd.core.enable_api": _e("bool", True, "Mount the /api routes."),
    "tsd.core.enable_ui": _e("bool", True,
                             "Mount the built-in UI routes."),
    "tsd.core.histograms.config": _e(
        "str", "", "Histogram codec config: inline JSON or @path."),
    "tsd.core.meta.enable_realtime_ts": _e(
        "bool", False, "Track TSMeta objects in real time."),
    "tsd.core.meta.enable_realtime_uid": _e(
        "bool", False, "Track UIDMeta objects in real time."),
    "tsd.core.meta.enable_tsuid_incrementing": _e(
        "bool", False, "Increment a counter per TSUID on ingest."),
    "tsd.core.meta.enable_tsuid_tracking": _e(
        "bool", False, "Track last-write per TSUID on ingest."),
    "tsd.core.meta.cache.enable": _e(
        "bool", False, "Reference compat meta-cache toggle.",
        compat=True),
    "tsd.core.meta.cache.plugin": _e(
        "str", "", "Reference compat meta-cache plugin.", compat=True),
    "tsd.core.plugin_path": _e(
        "dir", "", "Directory added to the import path for plugin "
        "discovery."),
    "tsd.core.response.async": _e(
        "bool", True, "Reference compat; responses are always async.",
        compat=True),
    "tsd.core.socket.timeout": _e(
        "int", "0", "Reference compat socket timeout.", compat=True),
    "tsd.core.tree.enable_processing": _e(
        "bool", False, "Run tree rules against incoming TSMeta."),
    "tsd.core.preload_uid_cache": _e(
        "bool", False, "Reference compat UID-cache preload.",
        compat=True),
    "tsd.core.preload_uid_cache.max_entries": _e(
        "int", "300000", "Reference compat UID-cache preload bound.",
        compat=True),
    "tsd.core.storage_exception_handler.enable": _e(
        "bool", False, "Enable the failed-write spillway plugin."),
    "tsd.core.storage_exception_handler.plugin": _e(
        "str", "", "Storage exception handler plugin class path."),
    "tsd.core.uid.random_metrics": _e(
        "bool", False, "Assign metric UIDs randomly instead of "
        "sequentially."),
    "tsd.core.bulk.allow_out_of_order_timestamps": _e(
        "bool", False, "Reference compat bulk-import flag.",
        compat=True),
    "tsd.core.timezone": _e(
        "str", "UTC", "Reference compat default timezone (queries carry "
        "their own tz).", compat=True),
    "tsd.core.stats_with_port": _e(
        "bool", False, "Reference compat: tag stats with the TSD port.",
        compat=True),
    # -- query --------------------------------------------------------- #
    "tsd.query.filter.expansion_limit": _e(
        "int", "4096", "Reference compat filter-expansion bound.",
        compat=True),
    "tsd.query.skip_unresolved_tagvs": _e(
        "bool", False, "Reference compat unresolved-tagv stance.",
        compat=True),
    "tsd.query.allow_simultaneous_duplicates": _e(
        "bool", True, "Allow identical queries to run concurrently "
        "instead of rejecting the second."),
    "tsd.query.enable_fuzzy_filter": _e(
        "bool", True, "Reference compat fuzzy-row-filter toggle.",
        compat=True),
    "tsd.query.limits.bytes.default": _e(
        "int", "0", "Per-query scanned-bytes budget (0 = unlimited); "
        "exceeding answers 413."),
    "tsd.query.limits.bytes.allow_override": _e(
        "bool", False, "Reference compat per-query override toggle.",
        compat=True),
    "tsd.query.limits.data_points.default": _e(
        "int", "0", "Per-query scanned-datapoints budget (0 = "
        "unlimited)."),
    "tsd.query.limits.data_points.allow_override": _e(
        "bool", False, "Reference compat per-query override toggle.",
        compat=True),
    "tsd.query.limits.overrides.config": _e(
        "str", "", "Per-metric budget overrides: inline JSON or @path."),
    "tsd.query.limits.overrides.interval": _e(
        "int", "60000", "Override-config reload interval (ms)."),
    "tsd.query.mesh.enable": _e(
        "bool", True, "Serve wide /api/query batches via the sharded "
        "device mesh (the salt-scanner fan-out analog)."),
    "tsd.query.mesh.min_series": _e(
        "int", "8", "Min series per batch before the mesh path engages "
        "(amortizes collective latency)."),
    "tsd.query.host_lane.max_points": _e(
        "int", "2000000", "Below this many scanned points the jitted "
        "pipeline runs on the host CPU platform — the accelerator "
        "dispatch floor dwarfs the compute at this scale.  0 disables."),
    "tsd.query.streaming.point_threshold": _e(
        "int", "8000000", "Queries past this many datapoints stream "
        "through the device in chunks instead of materializing one "
        "[S, N] batch."),
    "tsd.query.streaming.chunk_points": _e(
        "int", "4000000", "Streaming chunk size in points."),
    "tsd.query.streaming.sketch_percentiles": _e(
        "bool", True, "Rank-based downsample fns stream via the "
        "mergeable quantile sketch (approximate); false materializes "
        "subject to the scan budgets."),
    "tsd.query.streaming.sketch_max_merges": _e(
        "int", "4", "Max chunk merges per (series, window) cell before "
        "the planner routes to the exact materialized path (0 trusts "
        "the sketch unconditionally)."),
    "tsd.query.streaming.state_mb": _e(
        "int", "6144", "Refuse queries whose streaming accumulator grid "
        "would exceed this many MB of device memory (0 = unlimited)."),
    "tsd.query.device_cache.enable": _e(
        "bool", True, "Pin hot metrics' columns in device HBM (the "
        "BlockCache analog); repeat queries assemble batches on-device."),
    "tsd.query.device_cache.mb": _e(
        "int", "4096", "Device cache byte budget (LRU eviction)."),
    "tsd.query.device_cache.build_max_points": _e(
        "int", "200000000", "Metrics beyond this many points are never "
        "cached (the streaming path owns beyond-memory scans)."),
    "tsd.query.device_cache.batch_mb": _e(
        "int", "6144", "Decline cached-batch gathers whose padded "
        "[S, N] expansion exceeds this bound."),
    "tsd.query.spill.enable": _e(
        "bool", True, "Serve group-by plans whose [series, windows] "
        "state exceeds tsd.query.streaming.state_mb via series-tiled "
        "streaming with partial-aggregate spill (docs/tiling.md) "
        "instead of refusing with a 413."),
    "tsd.query.spill.host_mb": _e(
        "int", "1024", "Host-RAM ring budget for spilled partial "
        "grids; overflow demotes the oldest entries to disk."),
    "tsd.query.spill.disk_mb": _e(
        "int", "16384", "Disk-overflow budget for spilled partial "
        "grids (0 disables the disk tier; plans whose partials exceed "
        "host+disk refuse)."),
    "tsd.query.spill.dir": _e(
        "str", "", "Directory for disk-tier spill files (empty: a "
        "private tempdir, removed at shutdown)."),
    "tsd.query.spill.max_tiles": _e(
        "int", "1024", "Refuse tiled plans needing more series tiles "
        "than this (0 = unlimited) — a runaway-shape backstop."),
    "tsd.query.cache.enable": _e(
        "bool", True, "Cache per-(series, window) partial aggregates "
        "of fixed-interval downsample plans in aligned blocks and "
        "rewrite overlapping queries to reuse them, dispatching only "
        "the uncovered delta ranges (docs/caching.md)."),
    "tsd.query.cache.mb": _e(
        "int", "256", "Host-tier byte budget for cached aggregate "
        "blocks (LRU eviction)."),
    "tsd.query.cache.device_mb": _e(
        "int", "64", "Device/HBM-tier byte budget for hot aggregate "
        "blocks (0 disables the device mirrors)."),
    "tsd.query.cache.block_windows": _e(
        "int", "32", "Windows per cached block (rounded up to a power "
        "of two; blocks align to the absolute window grid so "
        "overlapping queries share them).  Smaller blocks waste fewer "
        "edge windows per query, larger ones cost fewer dispatches "
        "to populate."),
    "tsd.query.cache.min_repeats": _e(
        "int", "2", "Plan-family occurrences before a cold plan is "
        "worth materializing (1 = populate on first sight)."),
    "tsd.query.cache.promote_hits": _e(
        "int", "2", "Block hits before a host-tier block earns a "
        "device/HBM mirror."),
    "tsd.query.cache.amortize_horizon": _e(
        "int", "32", "Cold-populate admission: the populate overhead "
        "(rewrite minus monolithic predicted cost) must be "
        "recoverable within this many repeat queries' per-hit "
        "savings; plans whose per-hit saving is non-positive "
        "(dispatch-floor regime) never cache."),
    "tsd.query.cache.dispatch_overhead_us": _e(
        "int", "150", "Per-dispatch overhead (microseconds) the "
        "rewrite-vs-recompute costmodel decision charges each "
        "dispatch either side issues."),
    "tsd.query.kernel.scan_mode": _e(
        "str", "", "Prefix-scan strategy: auto|flat|blocked|subblock|"
        "subblock2 (empty keeps the module default / TSDB_SCAN_MODE "
        "env)."),
    "tsd.query.kernel.search_mode": _e(
        "str", "", "Edge-search strategy: auto|scan|compare_all|hier."),
    "tsd.query.kernel.extreme_mode": _e(
        "str", "", "min/max downsample strategy: "
        "auto|scan|segment|subblock."),
    "tsd.query.kernel.group_reduce_mode": _e(
        "str", "", "Group-reduce strategy: auto|segment|matmul|sorted."),
    "tsd.query.kernel.platform_guard": _e(
        "bool", "", "Demote dense search forms to the binary scan on "
        "CPU execution (empty keeps the module default: on)."),
    "tsd.query.kernel.stream_segment_ratio": _e(
        "float", "", "Streamed chunks take the segment form when "
        "W > ratio * N (empty keeps the module default)."),
    "tsd.query.multi_get.enable": _e(
        "bool", False, "Reference compat multigets toggle.", compat=True),
    "tsd.query.multi_get.limit": _e(
        "int", "131072", "Reference compat multigets bound.",
        compat=True),
    "tsd.query.multi_get.batch_size": _e(
        "int", "1024", "Reference compat multigets batch size.",
        compat=True),
    "tsd.query.multi_get.concurrent": _e(
        "int", "20", "Reference compat multigets concurrency.",
        compat=True),
    "tsd.query.multi_get.get_all_salts": _e(
        "bool", False, "Reference compat multigets salt stance.",
        compat=True),
    "tsd.query.timeout": _e(
        "int", "0", "Per-query wall-clock timeout in ms (0 = none).  "
        "Minted ONCE per request (min with the client's "
        "X-TSDB-Deadline-Ms header) and threaded end-to-end: planner "
        "sub-queries, cluster retries, and fan-out peers all run "
        "under the one remainder."),
    # -- admission control (tsd/admission.py, docs/admission.md) ------- #
    "tsd.query.admission.enable": _e(
        "bool", True,
        "Gate device-dispatching queries (/api/query, /q) behind "
        "bounded concurrency permits + priority wait queues; excess "
        "load sheds 503 + Retry-After instead of stalling the "
        "responder pool."),
    "tsd.query.admission.permits": _e(
        "int", "8",
        "Queries allowed to dispatch device work concurrently; "
        "arrivals beyond this wait in the admission queue."),
    "tsd.query.admission.queue_limit": _e(
        "int", "64",
        "Bound on queued queries across priority classes; a full "
        "queue sheds new arrivals with 503 + Retry-After.  With "
        "tsd.query.tenant.fair_share on, the bound applies PER "
        "clamped tenant (a storming tenant saturates its own backlog "
        "without shedding the rest); off, it is the global total."),
    "tsd.query.admission.max_wait_ms": _e(
        "int", "5000",
        "Longest a query may wait for a permit before being shed "
        "(0 = wait bounded only by the request deadline)."),
    # -- fused multi-query dispatch (query/batcher.py,
    #    docs/batching.md) ---------------------------------------------- #
    "tsd.query.batch.enable": _e(
        "bool", True,
        "Coalesce concurrent dispatch-bound queries (plan_decision "
        "path 'batched') into one stacked [Q, S, N] device kernel "
        "with host-side unpack — the per-dispatch floor is paid once "
        "per bucket instead of once per query.  Uncontended queries "
        "dispatch solo with zero hold."),
    "tsd.query.batch.hold_ms": _e(
        "int", "2",
        "Longest a bucket leader holds the coalesce window open for "
        "joiners.  Applied only while the admission gate shows other "
        "queries in flight — an idle daemon never pays coalesce "
        "latency."),
    "tsd.query.batch.max_q": _e(
        "int", "16",
        "Member queries per stacked dispatch; a full bucket seals and "
        "dispatches immediately."),
    "tsd.query.batch.max_mb": _e(
        "int", "64",
        "Byte bound on one bucket's stacked operands (members' padded "
        "[S, N] batches); a bucket at the bound seals and dispatches "
        "immediately."),
    "tsd.query.batch.amortize_factor": _e(
        "float", "4.0",
        "Coalesce-vs-dispatch-now line: a plan routes through the "
        "batcher when its costmodel-predicted compute plus stack/"
        "unpack overhead stays within this factor x the fitted "
        "stacked-dispatch floor (COST_TERMS stacked_dispatch/"
        "stacked_cell).  Compute-bound plans dispatch now."),
    # -- per-tenant fair share (tsd/admission.py) ----------------------- #
    "tsd.query.tenant.fair_share": _e(
        "bool", True,
        "Drain the admission queues by weighted deficit round robin "
        "across clamped tenants (X-TSDB-Tenant via tsd.diag.tenants) "
        "inside each priority class, so one tenant's dashboard storm "
        "cannot starve the rest.  Off: every query shares one FIFO "
        "identity (the PR 8 behavior)."),
    "tsd.query.tenant.weights": _e(
        "str", "",
        "Per-tenant DRR weights as 'tenant:weight,...' (default "
        "weight 1).  A tenant with weight 2 drains twice the "
        "predicted-cost share per round."),
    "tsd.query.tenant.quantum_ms": _e(
        "int", "50",
        "Deficit-round-robin quantum: predicted-cost milliseconds "
        "credited to each backlogged tenant per virtual drain round, "
        "scaled by its weight."),
    "tsd.query.tenant.max_inflight": _e(
        "int", "0",
        "Cap on admission permits any one tenant may hold "
        "concurrently (0 = no per-tenant cap; the global permit "
        "bound still applies)."),
    "tsd.query.degrade": _e(
        "str", "error",
        "Stance when a query's predicted cost cannot fit its "
        "remaining deadline: 'error' sheds with 503; 'allow' runs the "
        "degradation ladder first (coarsen the downsample interval, "
        "then truncate the range toward the present) and answers 200 "
        "with the partialResults annotation."),
    # -- rpc / rollups / plugins --------------------------------------- #
    "tsd.rpc.plugins": _e(
        "str", "", "Reference compat RPC plugin list.", compat=True),
    "tsd.rpc.telnet.return_errors": _e(
        "bool", True, "Reference compat telnet error stance.",
        compat=True),
    "tsd.rollup.enable": _e(
        "bool", False, "Enable rollup lanes: maintenance-built "
        "multi-resolution pre-aggregation serving any fixed-interval "
        "query whose interval is an integer multiple of a lane "
        "exactly from mergeable sum/count/min/max partials "
        "(docs/rollup.md)."),
    "tsd.rollup.intervals": _e(
        "str", "1m,1h,1d", "Comma-separated lane granularities the "
        "maintenance thread may materialize; the coarsest lane "
        "dividing a query's interval serves it."),
    "tsd.rollup.mb": _e(
        "int", "256", "Byte budget for materialized lane blocks "
        "(Storyboard-style precompute-under-budget: candidates are "
        "selected by costmodel-priced saving per byte; LRU eviction "
        "enforces the budget at insert)."),
    "tsd.rollup.block_windows": _e(
        "int", "64", "Lane cells per materialized block (rounded up "
        "to a power of two; blocks align to the absolute lane "
        "grid)."),
    "tsd.rollup.interval": _e(
        "int", "5", "Seconds between rollup-lane maintenance passes "
        "(demand selection + block builds; 0 disables the cadence — "
        "lanes then only build via explicit refresh() calls)."),
    "tsd.rollup.refresh_blocks": _e(
        "int", "32", "Maximum lane blocks (re)built per maintenance "
        "pass — bounds the per-tick build work."),
    "tsd.rollup.delay_ms": _e(
        "int", "0", "Skip building lane blocks whose range ends "
        "within this many ms of now (the actively-written head would "
        "be invalidated by the next ingest anyway; 0 builds "
        "everything)."),
    "tsd.rollups.enable": _e("bool", False,
                             "Enable rollup/pre-aggregate ingest and "
                             "query serving."),
    "tsd.rollups.config": _e(
        "str", "", "Rollup interval table: inline JSON or @path."),
    "tsd.rollups.tag_raw": _e(
        "bool", False, "Tag raw datapoints with the agg tag on ingest."),
    "tsd.rollups.agg_tag_key": _e(
        "str", "_aggregate", "Tag key marking pre-aggregated series."),
    "tsd.rollups.raw_agg_tag_value": _e(
        "str", "RAW", "Agg-tag value marking raw series."),
    "tsd.rollups.block_derived": _e(
        "bool", True, "Reject queries for derived aggregates with no "
        "stored lane."),
    "tsd.rollups.split_query.enable": _e(
        "bool", False, "Serve query head from rollups and tail from raw "
        "(SplitRollupQuery)."),
    "tsd.rtpublisher.enable": _e(
        "bool", False, "Publish ingested points to a real-time plugin."),
    "tsd.rtpublisher.plugin": _e(
        "str", "", "Real-time publisher plugin class path."),
    "tsd.search.enable": _e("bool", False,
                            "Index meta/annotations into a search "
                            "plugin."),
    "tsd.search.plugin": _e("str", "", "Search plugin class path."),
    "tsd.stats.canonical": _e(
        "bool", False, "Reference compat canonical-stats naming.",
        compat=True),
    "tsd.startup.enable": _e("bool", False, "Run a startup plugin."),
    "tsd.startup.plugin": _e("str", "", "Startup plugin class path."),
    # -- storage ------------------------------------------------------- #
    "tsd.storage.fix_duplicates": _e(
        "bool", False, "Resolve duplicate timestamps at read (last "
        "write wins) instead of raising."),
    "tsd.storage.flush_interval": _e(
        "int", "1000", "Reference compat HBase flush interval.",
        compat=True),
    "tsd.storage.data_table": _e(
        "str", "tsdb", "Reference compat table name.", compat=True),
    "tsd.storage.uid_table": _e(
        "str", "tsdb-uid", "Reference compat table name.", compat=True),
    "tsd.storage.tree_table": _e(
        "str", "tsdb-tree", "Reference compat table name.", compat=True),
    "tsd.storage.meta_table": _e(
        "str", "tsdb-meta", "Reference compat table name.", compat=True),
    "tsd.storage.enable_appends": _e(
        "bool", False, "Reference compat append-write mode.",
        compat=True),
    "tsd.storage.repair_appends": _e(
        "bool", False, "Reference compat append repair mode.",
        compat=True),
    "tsd.storage.enable_compaction": _e(
        "bool", True, "Background-compact dirty series rows."),
    "tsd.storage.compaction.flush_interval": _e(
        "int", "10", "Seconds between compaction flush passes."),
    "tsd.storage.compaction.min_flush_threshold": _e(
        "int", "100", "Backlog size that triggers an early flush pass."),
    "tsd.storage.compaction.max_concurrent_flushes": _e(
        "int", "10000", "Max series flushed per pass."),
    "tsd.storage.compaction.flush_speed": _e(
        "int", "2", "Backlog-pressure multiplier on the per-pass flush "
        "slice."),
    "tsd.storage.wal_sync_interval": _e(
        "int", "0", "Seconds between WAL fsync passes (0 = disabled; "
        "line buffering still survives process crashes)."),
    "tsd.storage.wal.segment_mb": _e(
        "int", "64", "WAL segment rotation size; segments are named by "
        "their first sequence number so a replica can catch up from an "
        "arbitrary offset without the owner rescanning one unbounded "
        "file."),
    "tsd.storage.wal.fsync": _e(
        "bool", False, "fsync the WAL per journaled record: "
        "crash-consistent at ingest cost (default rides the "
        "wal_sync_interval cadence)."),
    "tsd.storage.snapshot_interval": _e(
        "int", "0", "Seconds between full state snapshots (0 = "
        "disabled)."),
    "tsd.storage.native_snapshot": _e(
        "bool", True, "Snapshot series via the compressed native chunk "
        "engine; falls back to npz when the library can't build."),
    "tsd.storage.salt.width": _e(
        "int", "0", "Row-key salt width (reference parity; affects "
        "TSUID shape)."),
    "tsd.storage.salt.buckets": _e(
        "int", "20", "Salt bucket count."),
    "tsd.storage.uid.width.metric": _e(
        "int", "3", "Metric UID byte width."),
    "tsd.storage.uid.width.tagk": _e(
        "int", "3", "Tag-key UID byte width."),
    "tsd.storage.uid.width.tagv": _e(
        "int", "3", "Tag-value UID byte width."),
    "tsd.storage.max_tags": _e(
        "int", "8", "Reference compat max tags per point (enforced as a "
        "constant here).", compat=True),
    "tsd.storage.directory": _e(
        "dir", "", "Directory for snapshots + the WAL; empty disables "
        "persistence."),
    # -- uid / filters ------------------------------------------------- #
    "tsd.timeseriesfilter.enable": _e(
        "bool", False, "Enable the per-point write filter plugin."),
    "tsd.timeseriesfilter.plugin": _e(
        "str", "", "Write filter plugin class path."),
    "tsd.uid.use_mode": _e(
        "bool", False, "Reference compat UID mode flag.", compat=True),
    "tsd.uid.lru.enable": _e(
        "bool", False, "Reference compat UID LRU cache toggle.",
        compat=True),
    "tsd.uid.lru.name.size": _e(
        "int", "5000000", "Reference compat UID LRU bound.", compat=True),
    "tsd.uid.lru.id.size": _e(
        "int", "5000000", "Reference compat UID LRU bound.", compat=True),
    "tsd.uidfilter.enable": _e(
        "bool", False, "Enable the UID-assignment filter plugin."),
    "tsd.uidfilter.plugin": _e(
        "str", "", "UID filter plugin class path."),
    "tsd.uidfilter.metric_whitelist": _e(
        "str", "", "Comma-separated regexes a new metric name must "
        "match (UniqueIdWhitelistFilter)."),
    "tsd.uidfilter.metric_blacklist": _e(
        "str", "", "Comma-separated regexes that reject a new metric "
        "name."),
    "tsd.uidfilter.tagk_whitelist": _e(
        "str", "", "Whitelist regexes for new tag keys."),
    "tsd.uidfilter.tagk_blacklist": _e(
        "str", "", "Blacklist regexes for new tag keys."),
    "tsd.uidfilter.tagv_whitelist": _e(
        "str", "", "Whitelist regexes for new tag values."),
    "tsd.uidfilter.tagv_blacklist": _e(
        "str", "", "Blacklist regexes for new tag values."),
    # -- http ---------------------------------------------------------- #
    "tsd.http.show_stack_trace": _e(
        "bool", True, "Include the stack trace in error envelopes."),
    "tsd.http.query.allow_delete": _e(
        "bool", False, "Allow DELETE /api/query (and the delete query "
        "flag) to drop matched datapoints."),
    "tsd.http.header_tag": _e(
        "str", "", "Reference compat header-to-tag mapping.",
        compat=True),
    "tsd.http.request.enable_chunked": _e(
        "bool", True, "Reference compat chunked-request toggle.",
        compat=True),
    "tsd.http.request.max_chunk": _e(
        "int", "1048576", "Reference compat chunk size bound.",
        compat=True),
    "tsd.http.request.cors_domains": _e(
        "str", "", "Comma-separated origins allowed CORS access "
        "(* allows any)."),
    "tsd.http.request.cors_headers": _e(
        "str", ("Authorization, Content-Type, Accept, Origin, "
                "User-Agent, DNT, Cache-Control, X-Mx-ReqToken, "
                "Keep-Alive, X-Requested-With, If-Modified-Since"),
        "Headers returned in Access-Control-Allow-Headers."),
    "tsd.http.cachedir": _e(
        "dir", "", "Graph/cache scratch directory."),
    "tsd.http.staticroot": _e(
        "dir", "", "Static UI file root."),
    # -- TPU-native knobs (no reference equivalent) -------------------- #
    "tsd.tpu.enable": _e(
        "bool", True, "Reserved master toggle for accelerator serving.",
        compat=True),
    "tsd.tpu.mesh.shards": _e(
        "int", "0", "Device-mesh shard count (0 = all visible devices).",
        compat=True),
    "tsd.tpu.batch.max_series": _e(
        "int", "4096", "Reserved batch-width bound.", compat=True),
    "tsd.tpu.batch.pad_pow2": _e(
        "bool", True, "Reserved pow2-padding toggle.", compat=True),
    "tsd.tpu.precision.x64": _e(
        "bool", True, "Require 64-bit JAX arithmetic (Java double/long "
        "parity; int64 ms timestamps).  True (default): TSDB "
        "construction re-enables jax_enable_x64 if something turned it "
        "off.  False: x64 is left alone and the downsample planners "
        "refuse int64 window math while it is off rather than silently "
        "truncate ms timestamps."),
}

# Defaults mirror Config.setDefaults (Config.java:560-659) plus TPU-native
# keys; derived from the schema so the two can never diverge.
DEFAULTS: dict[str, str] = {k: e.default for k, e in CONFIG_SCHEMA.items()}

_SECRET_MARKERS = ("pass", "key", "secret", "token")


def generate_config_doc() -> str:
    """Render docs/configuration.md from CONFIG_SCHEMA (one table per
    top-level prefix).  tests/test_lint_clean.py pins the committed file
    to this output."""
    groups: dict[str, list[tuple[str, ConfigEntry]]] = {}
    for key, entry in sorted(CONFIG_SCHEMA.items()):
        prefix = ".".join(key.split(".")[:2])
        groups.setdefault(prefix, []).append((key, entry))
    lines = [
        "# Configuration reference",
        "",
        "<!-- GENERATED FILE — do not edit by hand.",
        "     Regenerate with: python tools/lint/run.py --update-doc",
        "     Source of truth: opentsdb_tpu/utils/config.py "
        "CONFIG_SCHEMA. -->",
        "",
        "All keys live in a flat Java-properties file "
        "(`./opentsdb.conf` or `/etc/opentsdb/opentsdb.conf`, or any "
        "path passed to `Config`).  Types are enforced by tsdblint "
        "against the accessor used at every read site.  Keys marked "
        "*compat* are accepted from reference OpenTSDB config files but "
        "not read by this codebase yet.",
        "",
    ]
    for prefix in sorted(groups):
        lines.append("## `%s.*`" % prefix)
        lines.append("")
        lines.append("| key | type | default | description |")
        lines.append("|---|---|---|---|")
        for key, entry in groups[prefix]:
            default = entry.default if len(entry.default) <= 40 \
                else entry.default[:37] + "..."
            doc = entry.doc + (" *(compat)*" if entry.compat else "")
            lines.append("| `%s` | %s | `%s` | %s |"
                         % (key, entry.type,
                            default.replace("|", "\\|") or " ",
                            doc.replace("|", "\\|")))
        lines.append("")
    return "\n".join(lines)


class Config:
    """Typed accessor over a flat key->string map, file- and dict-loadable."""

    def __init__(self, properties: dict[str, Any] | None = None,
                 config_file: str | None = None, auto_load: bool = False):
        self._map: dict[str, str] = dict(DEFAULTS)
        self.config_location: str | None = None
        if auto_load and config_file is None:
            for candidate in ("./opentsdb.conf", "/etc/opentsdb/opentsdb.conf"):
                if os.path.isfile(candidate):
                    config_file = candidate
                    break
        if config_file:
            self.load_file(config_file)
        if properties:
            for k, v in properties.items():
                self._map[k] = self._stringify(v)

    @staticmethod
    def _stringify(value: Any) -> str:
        if isinstance(value, bool):
            return "true" if value else "false"
        return str(value)

    def load_file(self, path: str) -> None:
        with open(path, "r") as fh:
            for line in fh:
                line = line.strip()
                if not line or line.startswith("#") or line.startswith("!"):
                    continue
                if "=" not in line:
                    continue
                key, _, value = line.partition("=")
                self._map[key.strip()] = value.strip()
        self.config_location = path

    # -- typed getters (Config.java getString/getInt/getBoolean...) --

    def has_property(self, key: str) -> bool:
        return key in self._map

    def get_string(self, key: str) -> str:
        if key not in self._map:
            raise KeyError(key)
        return self._map[key]

    def get_int(self, key: str) -> int:
        return int(self.get_string(key))

    def get_float(self, key: str) -> float:
        return float(self.get_string(key))

    def get_bool(self, key: str) -> bool:
        value = self.get_string(key).strip().lower()
        return value in ("1", "true", "yes")

    def get_directory_name(self, key: str) -> str:
        path = self.get_string(key)
        if path and not path.endswith(os.sep):
            path += os.sep
        return path

    def override_config(self, key: str, value: Any) -> None:
        self._map[key] = self._stringify(value)

    def as_map(self, obfuscate: bool = True) -> dict[str, str]:
        """Full config dump for /api/config; secrets hidden like the reference."""
        out = {}
        for key, value in sorted(self._map.items()):
            if obfuscate and any(m in key.lower() for m in _SECRET_MARKERS):
                out[key] = "********"
            else:
                out[key] = value
        return out

    def dump_json(self) -> str:
        return json.dumps(self.as_map(), indent=2)

    # -- convenience flags used on hot paths --

    @property
    def auto_metric(self) -> bool:
        return self.get_bool("tsd.core.auto_create_metrics")

    @property
    def enable_compactions(self) -> bool:
        return self.get_bool("tsd.storage.enable_compaction")

    @property
    def fix_duplicates(self) -> bool:
        return self.get_bool("tsd.storage.fix_duplicates")

    @property
    def salt_width(self) -> int:
        return self.get_int("tsd.storage.salt.width")

    @property
    def salt_buckets(self) -> int:
        return self.get_int("tsd.storage.salt.buckets")
