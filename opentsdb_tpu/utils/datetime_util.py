"""Date/time parsing helpers reproducing OpenTSDB's query time grammar.

Reference behavior: /root/reference/src/utils/DateTime.java
  - parseDateTimeString (:76): relative ("1h-ago"), absolute ("yyyy/MM/dd[-HH:mm[:ss]]"),
    unix seconds / milliseconds / dotted "<sec>.<ms>" forms, "now", bare "<n>ms".
  - parseDuration (:187): ms/s/m/h/d/w/n(30d)/y(365d) suffixes -> milliseconds.
  - previousInterval (:421): calendar-aligned interval starts honoring timezones.
"""

from __future__ import annotations

import calendar as _calendar
import datetime as _dt
import re
import time as _time
from zoneinfo import ZoneInfo, available_timezones

UTC_ID = "UTC"

# zoneinfo lookups are pure: an entry never changes once built
# cache: tz-lookup invalidated-by: none
_TZ_CACHE: dict[str, ZoneInfo] = {}
# cache: tz-lookup invalidated-by: none
_AVAILABLE: set[str] | None = None


def timezone(name: str | None) -> ZoneInfo:
    """Look up a timezone, raising on unknown names (unlike the JDK's GMT trap)."""
    global _AVAILABLE
    if name is None or name == "":
        name = UTC_ID
    tz = _TZ_CACHE.get(name)
    if tz is None:
        if _AVAILABLE is None:
            _AVAILABLE = available_timezones()
        if name not in _AVAILABLE:
            raise ValueError("Invalid timezone name: " + name)
        tz = ZoneInfo(name)
        _TZ_CACHE[name] = tz
    return tz


# Duration unit -> seconds multiplier (DateTime.java:216-226).
_DURATION_MULTIPLIERS = {
    "s": 1,
    "m": 60,
    "h": 3600,
    "d": 3600 * 24,
    "w": 3600 * 24 * 7,
    "n": 3600 * 24 * 30,   # month, averaged
    "y": 3600 * 24 * 365,  # year, ignoring leap years like the reference
}

_LONG_MAX = 2**63 - 1


def parse_duration(duration: str) -> int:
    """Parse "10m"/"3h"/"500ms" into milliseconds (DateTime.parseDuration :187)."""
    if not duration:
        raise ValueError("Cannot parse null or empty duration")
    unit = 0
    while unit < len(duration) and duration[unit].isdigit():
        unit += 1
    if unit >= len(duration):
        raise ValueError("Invalid duration, must have an integer and unit: " + duration)
    if unit == 0:
        raise ValueError("Invalid duration (number): " + duration)
    interval = int(duration[:unit])
    if interval <= 0:
        raise ValueError("Zero or negative duration: " + duration)
    suffix = duration.lower()[-1]
    if suffix == "s" and len(duration) >= 2 and duration[-2].lower() == "m":
        return interval  # milliseconds
    mult = _DURATION_MULTIPLIERS.get(suffix)
    if mult is None:
        raise ValueError("Invalid duration (suffix): " + duration)
    result = interval * mult * 1000
    if result > _LONG_MAX:
        raise ValueError("Duration must be < Long.MAX_VALUE ms: " + duration)
    return result


def get_duration_units(duration: str) -> str:
    """Return the unit suffix of a duration string (DateTime.getDurationUnits :241)."""
    if not duration:
        raise ValueError("Duration cannot be null or empty")
    unit = 0
    while unit < len(duration) and duration[unit].isdigit():
        unit += 1
    units = duration[unit:].lower()
    if units in ("ms", "s", "m", "h", "d", "w", "n", "y"):
        return units
    raise ValueError("Invalid units in the duration: " + units)


def get_duration_interval(duration: str) -> int:
    """Return the numeric prefix of a duration string (DateTime.getDurationInterval :268)."""
    if not duration:
        raise ValueError("Duration cannot be null or empty")
    if "." in duration:
        raise ValueError("Floating point intervals are not supported")
    unit = 0
    while unit < len(duration) and duration[unit].isdigit():
        unit += 1
    if unit == 0:
        raise ValueError("Invalid duration (number): " + duration)
    interval = int(duration[:unit])
    if interval <= 0:
        raise ValueError("Zero or negative duration: " + duration)
    return interval


def is_relative_date(value: str) -> bool:
    return value.lower().endswith("-ago")


_DOTTED_MS_RE = re.compile(r"^[0-9]{10}\.[0-9]{1,3}$")
_BARE_MS_RE = re.compile(r"^[0-9]+ms$")


def parse_datetime_string(datetime_str: str | None, tz: str | None = None,
                          now_ms: int | None = None) -> int:
    """Parse a query time string into epoch milliseconds.

    Mirrors DateTime.parseDateTimeString (:76): returns -1 for empty input;
    supports "now", "<dur>-ago", slash-dated absolute strings, unix seconds
    (<= 10 digits -> x1000), unix ms, and "<sec>.<ms>".
    """
    if datetime_str is None or datetime_str == "":
        return -1
    if _BARE_MS_RE.match(datetime_str):
        return int(datetime_str[:-2])
    lower = datetime_str.lower()
    if lower == "now":
        return now_ms if now_ms is not None else int(_time.time() * 1000)
    if lower.endswith("-ago"):
        interval = parse_duration(datetime_str[:-4])
        base = now_ms if now_ms is not None else int(_time.time() * 1000)
        return base - interval
    if "/" in datetime_str or ":" in datetime_str:
        fmt: str
        n = len(datetime_str)
        if n == 10:
            fmt = "%Y/%m/%d"
        elif n == 16:
            fmt = "%Y/%m/%d-%H:%M" if "-" in datetime_str else "%Y/%m/%d %H:%M"
        elif n == 19:
            fmt = "%Y/%m/%d-%H:%M:%S" if "-" in datetime_str else "%Y/%m/%d %H:%M:%S"
        else:
            raise ValueError("Invalid absolute date: " + datetime_str)
        try:
            naive = _dt.datetime.strptime(datetime_str, fmt)
        except ValueError as e:
            raise ValueError("Invalid date: %s. %s" % (datetime_str, e))
        aware = naive.replace(tzinfo=timezone(tz))
        return int(aware.timestamp() * 1000)
    # Numeric forms.
    contains_dot = "." in datetime_str
    if contains_dot:
        if not _DOTTED_MS_RE.match(datetime_str):
            raise ValueError(
                "Invalid time: " + datetime_str + ". Millisecond timestamps must "
                "be in the format <seconds>.<ms> where the milliseconds are "
                "limited to 3 digits")
        value = int(datetime_str.replace(".", ""))
    else:
        try:
            value = int(datetime_str)
        except ValueError as e:
            raise ValueError("Invalid time: %s. %s" % (datetime_str, e))
    if value < 0:
        raise ValueError("Invalid time: " + datetime_str +
                         ". Negative timestamps are not supported.")
    if len(datetime_str) <= 10:
        value *= 1000
    return value


# Calendar units for downsampling, keyed by duration suffix
# (DateTime.unitsToCalendarType equivalent).
_CAL_UNITS = ("ms", "s", "m", "h", "d", "w", "n", "y")


def previous_interval(ts_ms: int, interval: int, unit: str,
                      tz: str | ZoneInfo | None = None) -> int:
    """Snap ts_ms down to the start of its calendar-aligned interval.

    Mirrors DateTime.previousInterval (:421): pick a base boundary — the top
    of the parent unit when the interval divides it, otherwise the top of the
    next-larger unit (e.g. 45m tiles from midnight, 23s from the top of the
    hour) — then step forward by the interval until passing ts and back off
    one step.  Weeks start on Sunday (java.util.Calendar default) and step as
    7*interval days; months/years always tile from the top of the year.
    """
    if ts_ms < 0:
        raise ValueError("Timestamp cannot be less than zero")
    if interval < 1:
        raise ValueError("Interval must be greater than zero")
    if unit not in _CAL_UNITS:
        raise ValueError("Invalid unit: " + unit)
    zone = tz if isinstance(tz, ZoneInfo) else timezone(tz)
    when = _dt.datetime.fromtimestamp(ts_ms / 1000.0, zone)

    def _start_of(trunc_unit: str) -> _dt.datetime:
        if trunc_unit == "s":
            return when.replace(microsecond=0)
        if trunc_unit == "m":
            return when.replace(second=0, microsecond=0)
        if trunc_unit == "h":
            return when.replace(minute=0, second=0, microsecond=0)
        if trunc_unit == "d":
            return when.replace(hour=0, minute=0, second=0, microsecond=0)
        if trunc_unit == "n":
            return when.replace(day=1, hour=0, minute=0, second=0,
                                microsecond=0)
        # "y"
        return when.replace(month=1, day=1, hour=0, minute=0, second=0,
                            microsecond=0)

    step_unit = unit
    step_interval = interval
    if unit == "ms":
        base = _start_of("s") if 1000 % interval == 0 else _start_of("m")
    elif unit == "s":
        base = _start_of("m") if 60 % interval == 0 else _start_of("h")
    elif unit == "m":
        base = _start_of("h") if 60 % interval == 0 else _start_of("d")
    elif unit == "h":
        base = _start_of("d") if 24 % interval == 0 else _start_of("n")
    elif unit == "d":
        base = _start_of("n") if interval == 1 else _start_of("y")
    elif unit == "w":
        day = _start_of("d") if interval <= 2 else _start_of("y")
        # Snap back to the first day of the week (Sunday).
        days_since_sunday = (day.weekday() + 1) % 7
        base = day - _dt.timedelta(days=days_since_sunday)
        step_unit = "d"
        step_interval = 7 * interval
    else:  # "n" / "y"
        base = _start_of("y")

    base_ms = int(base.timestamp() * 1000)
    if base_ms == ts_ms:
        return base_ms
    prev = base_ms
    current = base_ms
    while current <= ts_ms:
        prev = current
        current = add_calendar_interval(current, step_interval, step_unit, zone)
    return prev


def add_calendar_interval(start_ms: int, interval: int, unit: str,
                          tz: str | ZoneInfo | None = None) -> int:
    """Advance a calendar interval start by one interval (Calendar.add semantics).

    Weeks advance as 7*interval days (Downsampler.java:338-341).
    Month arithmetic clamps the day-of-month like java.util.Calendar.
    """
    zone = tz if isinstance(tz, ZoneInfo) else timezone(tz)
    when = _dt.datetime.fromtimestamp(start_ms / 1000.0, zone)
    if unit == "ms":
        out = when + _dt.timedelta(milliseconds=interval)
    elif unit == "s":
        out = when + _dt.timedelta(seconds=interval)
    elif unit == "m":
        out = when + _dt.timedelta(minutes=interval)
    elif unit == "h":
        out = when + _dt.timedelta(hours=interval)
    elif unit == "d":
        out = when + _dt.timedelta(days=interval)
    elif unit == "w":
        out = when + _dt.timedelta(days=7 * interval)
    elif unit == "n":
        month_index = when.month - 1 + interval
        year = when.year + month_index // 12
        month = month_index % 12 + 1
        day = min(when.day, _calendar.monthrange(year, month)[1])
        out = when.replace(year=year, month=month, day=day)
    elif unit == "y":
        year = when.year + interval
        day = min(when.day, _calendar.monthrange(year, when.month)[1])
        out = when.replace(year=year, day=day)
    else:
        raise ValueError("Invalid unit: " + unit)
    return int(out.timestamp() * 1000)


def calendar_window_edges(start_ms: int, end_ms: int, interval: int, unit: str,
                          tz: str | None = None) -> list[int]:
    """Precompute calendar window start edges covering [start_ms, end_ms].

    Host-side helper for the TPU downsample kernels: calendar math cannot run
    inside jit, so edges are materialized here and turned into segment IDs on
    device (SURVEY.md §7 hard part (d)).
    """
    zone = timezone(tz)
    edges = [previous_interval(start_ms, interval, unit, zone)]
    while edges[-1] <= end_ms:
        edges.append(add_calendar_interval(edges[-1], interval, unit, zone))
    return edges


def current_time_millis() -> int:
    return int(_time.time() * 1000)
