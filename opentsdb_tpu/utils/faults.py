"""Fault injection: config-gated hook points on the failure-prone edges.

Every fault-tolerance behavior in this codebase (peer retries, circuit
breakers, degraded partial results, WAL crash recovery) ships with a
deterministic failure test — which requires a way to MAKE the failure
happen on demand.  Production code calls the module-level hooks at its
hazard sites; with no faults armed each hook is a single attribute read
(``_active`` False) so the request path pays nothing.

Sites currently instrumented:

  cluster.peer_fetch    before a peer HTTP fetch (tsd/cluster.py) —
                        ``peer`` in the context
  cluster.peer_body     the decoded peer response body, pre-parse
  wal.append            before a WAL journal write (storage/persist.py)
  wal.fsync             before a WAL fsync
  admission.acquire     before the admission gate's accounting
                        (tsd/admission.py) — ``route`` in the context
  rpc.slow_handler      inside a held admission permit, before query
                        execution (tsd/rpcs.py, tsd/graph.py) — a
                        latency fault here wedges the admission queue
                        deliberately (chaos_soak --overload)
  spill.write           before each spill-pool disk-tier file write
                        (storage/spill.py) — the disk-full shape
                        chaos_soak --spill heals through

Fault kinds:

  latency     {"kind": "latency", "ms": 500}           sleep, then pass
  refuse      {"kind": "refuse"}                        ConnectionRefusedError
  error       {"kind": "error", "message": "..."}       OSError
  disconnect  {"kind": "disconnect"}                    ConnectionResetError
              (at a body site: the body truncates mid-stream first, the
              mid-response-disconnect shape)
  garbage     {"kind": "garbage"}                        body replaced with
              bytes that are not JSON (body sites only)

Matching/arming:

  {"site": "cluster.peer_fetch", "kind": "refuse",
   "match": {"peer": "127.0.0.1:4243"},   # optional ctx equality filter
   "times": 2}                            # optional: fire N times then
                                          # disarm (omitted = every call)

Specs install programmatically (``install([...])`` — what the tests and
tools/chaos_soak.py use) or from config: ``tsd.faults.config`` holds
inline JSON (a list of specs) or ``@/path/to/specs.json``, read once by
``install_from_config`` at TSDB construction.  Injection is a testing
surface; the config gate exists so a REAL spawned daemon (crash/chaos
soaks) can run with faults armed — never arm it in production.
"""

from __future__ import annotations

import json
import logging
import threading
import time

LOG = logging.getLogger(__name__)

CONFIG_KEY = "tsd.faults.config"

# The registered hook sites and the context keys their call sites pass.
# Specs are validated against this at install time: a typo'd site or
# match key would otherwise arm NOTHING and silently defeat the chaos
# harness (the fault "passes" because it never fires).
KNOWN_SITES: dict[str, frozenset] = {
    "cluster.peer_fetch": frozenset({"peer"}),
    "cluster.peer_body": frozenset({"peer"}),
    "wal.append": frozenset(),
    "wal.fsync": frozenset(),
    # admission-control hazard sites (tsd/admission.py, tsd/rpcs.py):
    # `admission.acquire` fires before the gate's accounting (a
    # latency fault delays every arrival; refuse sheds at the door);
    # `rpc.slow_handler` fires INSIDE a held permit (a latency fault
    # wedges the queue deliberately — the chaos_soak --overload lever)
    "admission.acquire": frozenset({"route"}),
    "rpc.slow_handler": frozenset({"route"}),
    # before each spill-pool disk-tier file write (storage/spill.py) —
    # an "error" fault here is the disk-full shape chaos_soak --spill
    # heals through
    "spill.write": frozenset(),
    # replication hazard sites (tsd/replication.py): `replication.ship`
    # fires owner-side before the synchronous WAL ship to a replica
    # (a refuse/error there forces the pull cadence to fill the gap);
    # `replication.tail` fires puller-side before a catch-up tail GET
    # (a latency/refuse there delays rejoin convergence) — both carry
    # ``peer`` so split-brain-shaped failures target one link
    "replication.ship": frozenset({"peer"}),
    "replication.tail": frozenset({"peer"}),
}
# Body-corruption kinds only make sense at mangle() sites.
BODY_SITES = frozenset({"cluster.peer_body"})
CHECK_KINDS = frozenset({"latency", "refuse", "error", "disconnect"})
BODY_KINDS = frozenset({"garbage", "disconnect"})


class FaultError(OSError):
    """Raised by the generic "error" fault kind."""


class FaultSpecError(ValueError):
    """An invalid fault spec: unknown site/kind/match key.  Raised at
    install (daemon startup for config-armed specs) — loudly, because a
    fault that silently never fires is a chaos test that tests nothing."""


def validate_spec(spec: dict) -> None:
    if not isinstance(spec, dict):
        raise FaultSpecError("fault spec must be an object: %r" % (spec,))
    site = spec.get("site")
    kind = spec.get("kind")
    if site not in KNOWN_SITES:
        raise FaultSpecError(
            "unknown fault site %r (known: %s)"
            % (site, ", ".join(sorted(KNOWN_SITES))))
    allowed = CHECK_KINDS | (BODY_KINDS if site in BODY_SITES
                             else frozenset())
    if kind not in allowed:
        raise FaultSpecError(
            "fault kind %r is not valid at site %r (allowed: %s)"
            % (kind, site, ", ".join(sorted(allowed))))
    match = spec.get("match") or {}
    unknown = set(match) - KNOWN_SITES[site]
    if unknown:
        raise FaultSpecError(
            "match key(s) %s are never passed at site %r (context keys: "
            "%s)" % (sorted(unknown), site,
                     ", ".join(sorted(KNOWN_SITES[site])) or "none"))
    times = spec.get("times")
    if times is not None and (not isinstance(times, int) or times <= 0):
        raise FaultSpecError("'times' must be a positive int: %r" % times)


class _Fault:
    def __init__(self, spec: dict):
        self.site = spec["site"]
        self.kind = spec["kind"]
        self.spec = dict(spec)
        self.match = spec.get("match") or {}
        self.times = spec.get("times")      # None = unlimited
        self.fired = 0

    def applies(self, ctx: dict) -> bool:
        if self.times is not None and self.fired >= self.times:
            return False
        return all(ctx.get(k) == v for k, v in self.match.items())


class FaultInjector:
    """The registry.  One process-wide instance (``FAULTS``) — hook
    sites are module-level calls, and the soak tools arm faults before
    the daemon under test constructs its TSDB."""

    def __init__(self):
        self._lock = threading.Lock()
        # guarded-by: _lock
        self._faults: list[_Fault] = []
        self._active = False  # guarded-by: _lock (fast-path read lockless)
        self._installed_configs: set[str] = set()  # guarded-by: _lock
        self.injected = 0  # guarded-by: _lock

    # -- arming --

    def install(self, specs: list[dict]) -> None:
        """Arm specs; every spec validates against KNOWN_SITES first so
        a typo'd hook name fails the install instead of silently arming
        a fault that never fires (FaultSpecError)."""
        for s in specs:
            validate_spec(s)
        with self._lock:
            self._faults.extend(_Fault(s) for s in specs)
            self._active = bool(self._faults)
        if specs:
            LOG.warning("fault injection ARMED: %d spec(s) — %s",
                        len(specs),
                        ", ".join("%s/%s" % (s["site"], s["kind"])
                                  for s in specs))

    def clear(self) -> None:
        with self._lock:
            self._faults.clear()
            self._installed_configs.clear()
            self._active = False

    def install_from_config(self, config) -> None:
        """Read ``tsd.faults.config`` (inline JSON list or ``@path``).

        Idempotent per spec string: every TSDB construction in the
        process calls this, and a second TSDB on the same config must
        not double-arm the specs (a "times": 1 fault firing twice)."""
        raw = (config.get_string(CONFIG_KEY)
               if config.has_property(CONFIG_KEY) else "") or ""
        raw = raw.strip()
        if not raw:
            return
        with self._lock:
            if raw in self._installed_configs:
                return
            self._installed_configs.add(raw)
        # any failure below un-marks the spec string: an @path whose
        # file is fixed (or a corrected spec reinstalled after a
        # FaultSpecError) must be able to arm on a later construction,
        # not be silently remembered as "already installed"
        installed = False
        try:
            try:
                # ValueError covers JSONDecodeError AND the
                # UnicodeDecodeError a non-UTF-8 file raises; parsing
                # cannot raise FaultSpecError (that comes from
                # install() below), so the broad catch is safe
                if raw.startswith("@"):
                    with open(raw[1:]) as fh:
                        specs = json.load(fh)
                else:
                    specs = json.loads(raw)
            except (OSError, ValueError) as e:
                LOG.error("ignoring unreadable %s: %s", CONFIG_KEY, e)
                return
            if isinstance(specs, dict):
                specs = [specs]
            self.install(specs)      # FaultSpecError on a typo'd spec
            installed = True
        finally:
            if not installed:
                with self._lock:
                    self._installed_configs.discard(raw)

    # -- hook points --

    def _take(self, site: str, kinds: tuple, ctx: dict) -> _Fault | None:
        with self._lock:
            for f in self._faults:
                if f.site == site and f.kind in kinds and f.applies(ctx):
                    f.fired += 1
                    self.injected += 1
                    return f
        return None

    def check(self, site: str, **ctx) -> None:
        """Call at a hazard site; may sleep and/or raise the armed
        failure.  No-op (one attribute read) when nothing is armed."""
        if not self._active:
            return
        f = self._take(site, ("latency", "refuse", "error", "disconnect"),
                       ctx)
        if f is None:
            return
        if f.kind == "latency":
            # a latency fault EXISTS to stall the request path on
            # purpose (chaos harness only; never armed in production)
            # blocking: bounded-by the armed spec's own ms budget
            time.sleep(f.spec.get("ms", 100) / 1e3)
            return
        LOG.info("injecting %s at %s (%s)", f.kind, site, ctx)
        if f.kind == "refuse":
            raise ConnectionRefusedError(
                "injected connection refusal at %s" % site)
        if f.kind == "disconnect":
            raise ConnectionResetError(
                "injected disconnect at %s" % site)
        raise FaultError(f.spec.get("message",
                                    "injected fault at %s" % site))

    def mangle(self, site: str, data: bytes, **ctx) -> bytes:
        """Body-corruption hook: pass the payload through; an armed
        fault replaces it with garbage or truncates it mid-stream (the
        "disconnect" shape: half a body, then the peer goes away)."""
        if not self._active:
            return data
        f = self._take(site, ("garbage", "disconnect"), ctx)
        if f is None:
            return data
        LOG.info("injecting %s at %s (%s)", f.kind, site, ctx)
        if f.kind == "garbage":
            return b"\x00garbage{{{not json"
        raise ConnectionResetError(
            "injected mid-body disconnect at %s after %d bytes"
            % (site, len(data) // 2))


FAULTS = FaultInjector()

# module-level aliases: hazard sites call faults.check(...)/faults.mangle
check = FAULTS.check
mangle = FAULTS.mangle
install = FAULTS.install
clear = FAULTS.clear
install_from_config = FAULTS.install_from_config
