"""Capped exponential backoff with jitter, per-attempt deadlines, and an
overall time budget.

The role asynchbase's internal retry machinery played for the reference
(HBaseClient retries RegionServer RPCs through NSRE/flap windows so the
TSD above it never sees a transient): our rebuild replaced asynchbase
with direct HTTP fan-out (tsd/cluster.py) and dropped that layer — this
module restores it as a reusable utility.

Semantics:

  * up to ``max_attempts`` calls of ``fn(attempt_timeout_s)``;
  * each attempt gets a deadline: the configured per-attempt cap (or,
    unset, the whole budget — a slow-but-healthy first attempt keeps
    the full window it had before retries existed; retries then run on
    whatever remains, which fast failures like a refused connection
    leave nearly intact) bounded by the remaining overall budget;
  * between attempts: capped exponential backoff with full jitter
    (delay = uniform(0, min(cap, base * mult**n))) — the AWS-style
    decorrelation that keeps a retry thundering herd from
    re-synchronizing on a recovering peer;
  * a retry is only scheduled while budget remains for both the sleep
    AND a meaningful next attempt (``min_attempt_s``); otherwise the
    last error raises immediately.

``clock``/``sleep``/``rand`` are injectable so the fault-injection tests
drive every branch deterministically (tests/test_fault_tolerance.py).
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass
from typing import Callable, Tuple, Type


@dataclass(frozen=True)
class RetryPolicy:
    """How a retried call behaves.  ``budget_s`` is the overall wall
    budget across every attempt and backoff sleep (for cluster fetches:
    ``tsd.network.cluster.timeout_ms``)."""

    max_attempts: int = 3
    budget_s: float = 15.0
    attempt_timeout_s: float = 0.0   # 0 = the full budget per attempt
    base_delay_s: float = 0.05
    max_delay_s: float = 2.0
    multiplier: float = 2.0
    min_attempt_s: float = 0.05      # don't bother with a sliver attempt

    def per_attempt_s(self) -> float:
        if self.attempt_timeout_s > 0:
            return self.attempt_timeout_s
        return self.budget_s


def _cancellable_sleep(delay: float, deadline) -> None:
    """The default backoff sleep: wakeable by the request Deadline's
    cancellation token.  A bare ``time.sleep(delay)`` here meant a
    disconnected client's fan-out retries slept out their full backoff
    while holding an admission permit; parking on the token instead
    releases within one tick of ``cancel()`` and re-raises through
    ``Deadline.check`` (503/413) so no further attempt is scheduled.
    With no deadline anywhere (library callers), plain sleep."""
    if deadline is None:
        from opentsdb_tpu.query.limits import active_deadline
        deadline = active_deadline()
    if deadline is None:
        # this arm runs only with NO deadline anywhere (library caller
        # outside any request): there is no token this sleep could watch
        # blocking: bounded-by the backoff delay itself (deadline-free path)
        time.sleep(delay)
        return
    deadline.wait_cancelled(delay)
    deadline.check()


def call_with_retries(fn: Callable[[float], object],
                      policy: RetryPolicy,
                      retry_on: Tuple[Type[BaseException], ...]
                      = (Exception,),
                      no_retry_on: Tuple[Type[BaseException], ...] = (),
                      on_retry: Callable[[int, BaseException], None]
                      | None = None,
                      clock: Callable[[], float] = time.monotonic,
                      sleep: Callable[[float], None] | None = None,
                      rand: Callable[[], float] = random.random,
                      deadline=None):
    """Run ``fn(attempt_timeout_s)`` under ``policy``; returns its value
    or raises the last error once attempts/budget are exhausted.
    ``no_retry_on`` wins over ``retry_on``: a deterministic failure
    (e.g. the server rejected the request as malformed) propagates
    immediately — retrying the same request buys the same answer.
    ``on_retry(attempt_number, exc)`` fires before each backoff sleep
    (telemetry hook — cluster.py counts these into /api/stats).

    ``deadline`` (a query.limits.Deadline) makes the backoff sleeps
    cancellation-aware; pass it EXPLICITLY from pool threads — the
    ambient TLS deadline lives on the responder thread, not on the
    fan-out executor's workers.  Omitted, the ambient one (if any) is
    picked up at sleep time.  An injected ``sleep`` wins outright (the
    fault-injection tests drive the loop deterministically)."""
    if sleep is None:
        sleep = lambda d: _cancellable_sleep(d, deadline)  # noqa: E731
    start = clock()
    last_exc: BaseException | None = None
    for attempt in range(1, policy.max_attempts + 1):
        remaining = policy.budget_s - (clock() - start)
        if remaining <= 0:
            break
        try:
            return fn(min(policy.per_attempt_s(), remaining))
        except retry_on as e:      # noqa: PERF203 — the retry loop
            if no_retry_on and isinstance(e, no_retry_on):
                raise
            last_exc = e
            if attempt >= policy.max_attempts:
                break
            delay = min(policy.max_delay_s,
                        policy.base_delay_s
                        * policy.multiplier ** (attempt - 1)) * rand()
            remaining = policy.budget_s - (clock() - start)
            if remaining - delay < policy.min_attempt_s:
                break              # no budget left for a real retry
            if on_retry is not None:
                on_retry(attempt, e)
            if delay > 0:
                sleep(delay)
    if last_exc is None:
        raise TimeoutError(
            "retry budget %.3fs exhausted before the first attempt"
            % policy.budget_s)
    raise last_exc
