"""Test harness: force an 8-device virtual CPU platform before JAX loads.

Mirrors the reference's test stance (SURVEY.md §4): deterministic in-memory
storage + golden-value numeric tests, with multi-chip sharding validated on a
virtual device mesh (the driver separately dry-runs the real multi-chip path).
"""

import os

# Force the CPU platform even when the ambient environment points JAX at a
# real accelerator (JAX_PLATFORMS=axon + sitecustomize pre-imports jax, so a
# plain env setdefault is too late).  The accelerator tunnel is exclusive;
# tests must never contend for it.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
# Persistent compile cache: kernel tests compile many small shapes; cache
# them across pytest runs so the suite stays fast.
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_pytest_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")

import sys  # noqa: E402

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

# TSDBSAN=1 arms the runtime sanitizer (tools/sanitize) for the whole
# session: instrumented locks + write interception + deadlock watchdog.
# The plugin fails the session on error-level findings.
if os.environ.get("TSDBSAN", "") == "1":
    pytest_plugins = ["tools.sanitize.plugin"]

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402

# The platform guard demotes the dense (accelerator-winner) search forms
# to the binary search whenever execution lands on CPU — which is every
# test in this suite.  Disable it suite-wide so CPU CI keeps exercising
# the dense kernels' correctness; tests of the guard itself re-enable it
# locally (tests/test_prefix_downsample.py::TestPlatformModeGuard).
from opentsdb_tpu.ops import downsample as _ds  # noqa: E402

_ds.set_platform_mode_guard(False)


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: excluded from the tier-1 gate (-m 'not slow'); the "
        "standing CI soak runs these")


@pytest.fixture
def rng():
    return np.random.default_rng(42)
