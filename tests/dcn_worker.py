"""One process of the REAL 2-process DCN integration test (VERDICT r3 #5).

Launched by test_distributed.py::TestTwoProcessDCN with
  python dcn_worker.py <coordinator> <num_processes> <process_id>
Each process owns 4 virtual CPU devices; `maybe_init_distributed` joins
them into one 8-device JAX runtime (the compute-mesh analog of the
reference's RegionServer+ZooKeeper substrate, TSDB.java:235-253).  The
worker runs the production sharded query pipeline over the global mesh
and asserts bit-equality with the single-host answer; any assertion
failure exits nonzero and fails the wrapper test.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
os.environ.setdefault("JAX_ENABLE_X64", "1")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from opentsdb_tpu.parallel.distributed import (  # noqa: E402
    host_major_devices, maybe_init_distributed)
from opentsdb_tpu.utils.config import Config  # noqa: E402


def main() -> None:
    coordinator, num, pid = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
    conf = Config({
        "tsd.network.distributed.coordinator": coordinator,
        "tsd.network.distributed.num_processes": str(num),
        "tsd.network.distributed.process_id": str(pid),
    })
    assert maybe_init_distributed(conf) is True
    assert jax.process_count() == num, jax.process_count()
    devs = host_major_devices()
    assert len(devs) == 4 * num, devs
    # host-major contract: each host's devices contiguous on the series
    # axis, so dense combines stay intra-host
    keys = [(d.process_index, d.id) for d in devs]
    assert keys == sorted(keys), keys
    assert [d.process_index for d in devs] == \
        sorted([d.process_index for d in devs]), keys

    # deterministic batch, identical in every process
    from opentsdb_tpu.ops.downsample import FixedWindows, pad_pow2
    from opentsdb_tpu.ops.pipeline import (DownsampleStep, PipelineSpec,
                                           run_group_pipeline)
    from opentsdb_tpu.parallel.mesh import make_mesh
    from opentsdb_tpu.parallel.sharded import (shard_rows,
                                               sharded_query_pipeline)

    s, n, g = 16, 256, 4
    start = 1_356_998_400_000
    rng = np.random.default_rng(99)
    ts = start + np.sort(rng.integers(0, 3_600_000, (s, n)), axis=1)
    ts = np.asarray(ts, np.int64)
    val = rng.normal(50.0, 15.0, (s, n))
    mask = rng.random((s, n)) < 0.9
    gid = np.arange(s, dtype=np.int64) % g

    fixed = FixedWindows.for_range(start, start + 3_600_000, 60_000)
    window_spec, wargs = fixed.split()
    g_pad = pad_pow2(g)
    spec = PipelineSpec(
        aggregator="sum",
        downsample=DownsampleStep("avg", window_spec, "none", 0.0))

    # single-host reference on this process's local devices
    ref_ts, ref_val, ref_mask = run_group_pipeline(
        spec, ts, val, mask, gid, g_pad, wargs)
    ref_ts, ref_val, ref_mask = (np.asarray(ref_ts), np.asarray(ref_val),
                                 np.asarray(ref_mask))

    # global mesh across BOTH processes; same production entry points
    mesh = make_mesh(devices=host_major_devices())
    assert mesh.devices.size == 4 * num
    fn = sharded_query_pipeline(mesh, spec, g_pad)
    d_ts, d_val, d_mask, d_gid = shard_rows(mesh, ts, val, mask, gid,
                                            pad_gid_value=g_pad)
    out_ts, out_val, out_mask = fn(d_ts, d_val, d_mask, d_gid, wargs)
    out_ts, out_val, out_mask = (np.asarray(out_ts), np.asarray(out_val),
                                 np.asarray(out_mask))

    assert np.array_equal(out_ts, ref_ts)
    assert np.array_equal(out_mask, ref_mask)
    live = ref_mask[:g]
    np.testing.assert_allclose(out_val[:g][live], ref_val[:g][live],
                               rtol=1e-12)

    # a second aggregator exercises the gather-to-owner (ordered) branch
    # across DCN
    spec2 = PipelineSpec(
        aggregator="p90",
        downsample=DownsampleStep("avg", window_spec, "none", 0.0))
    ref2 = np.asarray(run_group_pipeline(
        spec2, ts, val, mask, gid, g_pad, wargs)[1])
    fn2 = sharded_query_pipeline(mesh, spec2, g_pad)
    out2 = np.asarray(fn2(d_ts, d_val, d_mask, d_gid, wargs)[1])
    np.testing.assert_allclose(out2[:g][live], ref2[:g][live], rtol=1e-12)

    print("DCN_WORKER_OK process=%d devices=%d" % (pid, len(devs)),
          flush=True)


if __name__ == "__main__":
    main()
