"""Deterministic failure machinery for the fault-tolerance tests.

`FaultyPeer` is a real TCP server speaking just enough HTTP to stand in
for a cluster peer's /api/query: every fan-out fetch hits an actual
socket, and the fault mode decides what the wire does — answer
correctly, hang, cut the connection mid-body, or return bytes that are
not JSON.  Failures are injected by the SERVER side, so the client
stack under test (urllib + retry + breaker in tsd/cluster.py) sees the
genuine network error shapes, not monkeypatched stand-ins.

No sleeps-as-synchronization anywhere: "timeout" holds the socket open
until the client's own deadline fires, and breaker cooldowns are driven
by rewinding `opened_at` (see force_cooldown_elapsed) instead of
waiting wall-clock time.
"""

from __future__ import annotations

import json
import socket
import threading

# fault modes a FaultyPeer can serve
OK = "ok"                   # 200 + canned payload
TIMEOUT = "timeout"         # accept, read, never answer
PARTITION = "partition"     # accept the connect, never even READ the
                            # request, hold the socket — the network-
                            # partition shape: the peer looks alive at
                            # the TCP layer but nothing moves (split-
                            # brain-shaped failures for the replication
                            # ship/tail tests)
DISCONNECT = "disconnect"   # 200 headers, half the body, RST
GARBAGE = "garbage"         # 200 + bytes that are not JSON
ERROR_500 = "error500"      # well-formed 500 (transient: retried)
ERROR_400 = "error400"      # well-formed 400 (deterministic: not retried)
SLOW_BODY = "slow_body"     # 200 headers then the body dribbled slowly —
                            # a "healthy" peer that cannot finish inside
                            # the caller's deadline (deadline-propagation
                            # tests time the abort against the remainder)


def refused_port() -> int:
    """A port with nothing listening: connecting gets ECONNREFUSED
    deterministically (bound then immediately released, so the OS
    won't reassign it to another listener within the test)."""
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class FaultyPeer:
    """A fake peer TSD on a live socket with a switchable fault mode.

    ``peer.mode = TIMEOUT`` flips behavior between requests;
    ``peer.script = [GARBAGE, OK]`` serves one mode per request then
    falls back to ``mode`` (deterministic transient-then-recover);
    ``peer.requests`` counts connections that delivered a full request
    (the breaker fast-fail tests assert this does NOT grow)."""

    def __init__(self, payload: list[dict] | None = None):
        self.payload = payload if payload is not None else []
        self.mode = OK
        self.script: list[str] = []
        self.requests = 0
        # lower-cased header dict of every request that arrived, in
        # order (the deadline-propagation tests assert the coordinator
        # forwarded X-TSDB-Deadline-Ms with its remainder)
        self.seen_headers: list[dict] = []
        # seconds per 1-byte body chunk in SLOW_BODY mode
        self.slow_body_step_s = 0.2
        self._lock = threading.Lock()
        self._hung: list[socket.socket] = []
        self._closing = False
        self._sock = socket.socket()
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("127.0.0.1", 0))
        self._sock.listen(16)
        self.port = self._sock.getsockname()[1]
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    @property
    def address(self) -> str:
        return "127.0.0.1:%d" % self.port

    def close(self) -> None:
        self._closing = True
        try:
            self._sock.close()
        except OSError:
            pass
        with self._lock:
            for c in self._hung:        # release clients stuck in TIMEOUT
                try:
                    c.close()
                except OSError:
                    pass
            self._hung.clear()
        self._thread.join(5)

    # -- server internals --

    def _serve(self) -> None:
        while not self._closing:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._handle, args=(conn,),
                             daemon=True).start()

    def _read_request(self, conn: socket.socket) -> bytes | None:
        conn.settimeout(10)
        data = b""
        while b"\r\n\r\n" not in data:
            chunk = conn.recv(65536)
            if not chunk:
                return None
            data += chunk
        head, _, rest = data.partition(b"\r\n\r\n")
        length = 0
        headers: dict = {}
        for line in head.split(b"\r\n"):
            if b":" in line:
                k, v = line.split(b":", 1)
                headers[k.strip().lower().decode("latin-1")] = \
                    v.strip().decode("latin-1")
            if line.lower().startswith(b"content-length:"):
                length = int(line.split(b":", 1)[1])
        with self._lock:
            self.seen_headers.append(headers)
        while len(rest) < length:
            chunk = conn.recv(65536)
            if not chunk:
                return None
            rest += chunk
        return rest[:length]

    def _handle(self, conn: socket.socket) -> None:
        try:
            with self._lock:
                upcoming = self.script[0] if self.script else self.mode
                if upcoming == PARTITION:
                    # the partition holds the socket BEFORE any byte is
                    # read: the client's connect succeeds, its request
                    # bytes vanish into the kernel buffer, and nothing
                    # ever answers — `requests` does NOT grow (no full
                    # request was delivered)
                    if self.script:
                        self.script.pop(0)
                    self._hung.append(conn)
                    return              # close() releases it
            if self._read_request(conn) is None:
                return
            with self._lock:
                mode = self.script.pop(0) if self.script else self.mode
                self.requests += 1
            if mode == TIMEOUT:
                # hold the connection open, never answer: the client's
                # own per-attempt deadline is what fires
                with self._lock:
                    self._hung.append(conn)
                return                  # close() releases it
            if mode == ERROR_500:
                conn.sendall(b"HTTP/1.1 500 Internal Server Error\r\n"
                             b"Content-Length: 9\r\n\r\nkaboom :(")
            elif mode == ERROR_400:
                conn.sendall(b"HTTP/1.1 400 Bad Request\r\n"
                             b"Content-Length: 8\r\n\r\nrejected")
            elif mode == GARBAGE:
                body = b"\x7f{{{this is not json"
                conn.sendall(b"HTTP/1.1 200 OK\r\n"
                             b"Content-Type: application/json\r\n"
                             b"Content-Length: %d\r\n\r\n%s"
                             % (len(body), body))
            elif mode == SLOW_BODY:
                import time
                body = json.dumps(self.payload).encode()
                conn.sendall(b"HTTP/1.1 200 OK\r\n"
                             b"Content-Type: application/json\r\n"
                             b"Content-Length: %d\r\n\r\n" % len(body))
                # dribble one byte per step: the response never
                # finishes inside a tight deadline, but the socket
                # stays live — only the CLIENT's clamped timeout (the
                # forwarded remainder) can end this fetch
                for i in range(len(body)):
                    conn.sendall(body[i:i + 1])
                    time.sleep(self.slow_body_step_s)
            elif mode == DISCONNECT:
                body = json.dumps(self.payload).encode()
                # advertise the full length, ship half, cut the line
                # hard (RST via SO_LINGER 0) — the mid-response
                # disconnect a crashing peer produces
                conn.sendall(b"HTTP/1.1 200 OK\r\n"
                             b"Content-Type: application/json\r\n"
                             b"Content-Length: %d\r\n\r\n" % len(body))
                conn.sendall(body[:max(len(body) // 2, 1)])
                conn.setsockopt(
                    socket.SOL_SOCKET, socket.SO_LINGER,
                    b"\x01\x00\x00\x00\x00\x00\x00\x00")
            else:
                body = json.dumps(self.payload).encode()
                conn.sendall(b"HTTP/1.1 200 OK\r\n"
                             b"Content-Type: application/json\r\n"
                             b"Content-Length: %d\r\n\r\n%s"
                             % (len(body), body))
            conn.close()
        except OSError:
            try:
                conn.close()
            except OSError:
                pass


def series_payload(metric: str, tags: dict, dps: dict) -> list[dict]:
    """One raw series in the shape a peer's fan-out response carries."""
    return [{"metric": metric, "tags": tags,
             "aggregateTags": [], "dps": dps}]


def force_cooldown_elapsed(breaker) -> None:
    """Rewind an OPEN breaker's clock so its next allow() is the
    half-open probe — cooldown transitions without wall-clock sleeps.
    `opened_at` is guarded-by `_lock`; the responder pool may be
    fetching (and the breaker transitioning) concurrently, so the
    rewind takes the lock like every other writer — tsdbsan flagged
    the previous lockless form (san-unguarded-mutation)."""
    with breaker._lock:
        breaker.opened_at -= breaker.cooldown_s + 1e-3
