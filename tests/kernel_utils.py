"""Helpers to build padded [series, time] batches for kernel tests."""

import numpy as np

PAD_TS = np.iinfo(np.int64).max


def batch(series):
    """series: list of (ts_list, val_list). Returns ts[S,N], val[S,N], mask[S,N]."""
    n = max((len(ts) for ts, _ in series), default=1)
    n = max(n, 1)
    s = len(series)
    ts = np.full((s, n), PAD_TS, dtype=np.int64)
    val = np.zeros((s, n), dtype=np.float64)
    mask = np.zeros((s, n), dtype=bool)
    for i, (t, v) in enumerate(series):
        k = len(t)
        ts[i, :k] = t
        val[i, :k] = v
        mask[i, :k] = True
    return ts, val, mask


def collect(ts, val, mask):
    """Extract (ts, value) pairs where mask, as plain Python lists."""
    ts = np.asarray(ts)
    val = np.asarray(val)
    mask = np.asarray(mask)
    return [(int(t), float(v)) for t, v, m in zip(ts.ravel(), val.ravel(),
                                                  mask.ravel()) if m]
