"""Batched-dispatch obligation true negatives: the sanctioned shapes
the real batcher/planner use (query/batcher.py, the planner's batched
branch) — the member span finished on every path, bucket state
mutated only under the batcher lock, and fixed-vocabulary outcome
labels.  Parsed, never imported."""

import threading

REGISTRY = None  # stub: the analyzer matches the receiver NAME


def batched_span_finished_on_every_path(obs_trace, batcher, plan):
    span = obs_trace.begin("pipeline")
    try:
        if not batcher.enabled:
            return None
        return batcher.submit(plan)
    finally:
        obs_trace.end(span)


class BucketStateLocked:
    """The real bucket discipline: every members/nbytes mutation under
    the one batcher lock (the leader's seal snapshot included)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.members = []  # guarded-by: _lock
        self.nbytes = 0    # guarded-by: _lock

    def add(self, member, size):
        with self._lock:
            self.members.append(member)
            self.nbytes += size

    def seal(self):
        with self._lock:
            live = list(self.members)
            self.members = []
            self.nbytes = 0
        return live


def batch_counts_fixed_outcomes(stacked):
    outcome = "stacked" if stacked else "solo"
    REGISTRY.counter("tsd.fixture.count").labels(route=outcome).inc()
