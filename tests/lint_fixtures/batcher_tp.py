"""Batched-dispatch obligation true positives (ISSUE 14): the shapes
the fused multi-query dispatcher must NOT take — a member's pipeline
span leaked when the rendezvous bails early, bucket state mutated
without the batcher lock despite its annotation, and a batch-outcome
metric minted from a raw string.  Parsed, never imported."""

import threading

REGISTRY = None  # stub: the analyzer matches the receiver NAME


def batched_span_leaks_on_declined_submit(obs_trace, batcher, plan):
    """The planner's batched branch begins the pipeline span before
    the rendezvous; declining WITHOUT ending it leaks the span."""
    span = obs_trace.begin("pipeline")
    if not batcher.enabled:
        return None  # EXPECT: resource-leak-return
    out = batcher.submit(plan)
    obs_trace.end(span)
    return out


class BucketStateUnlocked:
    """Batcher bucket bookkeeping is guarded-by the batcher lock; a
    lock-free member append races the leader's seal."""

    def __init__(self):
        self._lock = threading.Lock()
        self.members = []  # guarded-by: _lock
        self.nbytes = 0    # guarded-by: _lock

    def add(self, member, size):
        self.nbytes = self.nbytes + size  # EXPECT: lock-unguarded-mutation
        with self._lock:
            self.members.append(member)


def batch_outcome_from_member_count(q):
    """Outcome labels come from a fixed vocabulary ('stacked'/'solo'),
    never a computed value — cardinality discipline."""
    REGISTRY.counter("tsd.fixture." + str(q)).inc()  # EXPECT: metrics-dynamic-name
