"""True negatives for deadline_discipline / hold_lock_while_blocking:
every blocking site derives its bound from a sanctioned source — the
deadline's remainder, a timeout-named config key, a min() clamp, a
settimeout'd socket, a reviewed `# blocking: bounded-by` waiver — or
sits off the request paths entirely (the background puller).
"""

import socket
import threading
import time
import urllib.request
from queue import Queue


class BoundedHandler:
    def __init__(self, config):
        self._lock = threading.Lock()
        self._cond = threading.Condition()
        self._work = Queue()
        # guarded-by: _lock
        self.served = 0
        self.timeout_s = config.get_int("tsd.good.timeout_ms") / 1e3

    def execute_http(self, peer, deadline):
        self._fetch(peer, deadline)
        self._probe(peer, deadline)
        self._drain(deadline)
        self._record()

    def _fetch(self, peer, deadline):
        # deadline-derived, clamped to the config bound: both sanctioned
        timeout_s = min(self.timeout_s,
                        max(deadline.remaining_ms() / 1e3, 0.05))
        return urllib.request.urlopen(peer, timeout=timeout_s)

    def _probe(self, peer, deadline):
        sock = socket.create_connection((peer, 4242), self.timeout_s)
        sock.settimeout(deadline.remaining_ms() / 1e3)
        sock.sendall(b"ping")
        sock.close()

    def _drain(self, deadline):
        if self._lock.acquire(timeout=self.timeout_s):
            self._lock.release()
        self._work.get(block=False)
        self._work.put("tick", timeout=0.5)
        # the sanctioned request-path sleep: parks on the cancellation
        # token instead of time.sleep
        deadline.wait_cancelled(self.timeout_s)
        # a reviewed waiver the analyzer cannot see through
        # blocking: bounded-by the chaos harness's own armed ms budget
        time.sleep(0.01)
        t = threading.Thread(target=self._record)
        t.start()
        t.join(self.timeout_s)

    def _record(self):
        with self._lock:
            self.served += 1
            # Condition.wait releases the lock while waiting — exempt
            # from hold-lock-while-blocking; its timeout keeps it off
            # blocking-unbounded
            self._cond.wait(0.5)

def background_pull(peer):
    """Not reachable from any request entry: the puller cadence owns
    its own schedule, so a plain config-free bound is acceptable here
    and the analyzer must not flag it."""
    return urllib.request.urlopen(peer)
