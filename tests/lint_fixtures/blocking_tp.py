"""True positives for the v4 pair: deadline_discipline
(blocking-unbounded / blocking-sleep) and hold_lock_while_blocking.

`execute_http` is the entry the analyzer keys on by naming convention;
every helper below is reachable from it, so each marked site must fire
exactly the named rule.  The `_fetch_race` shape pins the program-point
property: the FIRST urlopen sits before the min() clamp (an early
return crosses it unclamped) and reports, while the second — after the
clamp — stays clean.
"""

import socket
import subprocess
import threading
import time
import urllib.request
from queue import Queue


class WedgeHandler:
    def __init__(self):
        self._lock = threading.Lock()
        self._work = Queue()
        # guarded-by: _lock
        self.served = 0

    def execute_http(self, peer, fast, timeout_s):
        self._probe(peer)
        self._drain()
        self._spawn_and_wait()
        body = self._fetch_race(peer, fast, timeout_s)
        self._audit(peer)
        return body

    def _probe(self, peer):
        sock = socket.create_connection((peer, 4242))  # EXPECT: blocking-unbounded
        sock.sendall(b"ping")  # EXPECT: blocking-unbounded
        time.sleep(0.05)  # EXPECT: blocking-sleep
        sock.close()

    def _drain(self):
        self._lock.acquire()  # EXPECT: blocking-unbounded
        self._lock.release()
        self._work.get()  # EXPECT: blocking-unbounded
        subprocess.run(["sync"])  # EXPECT: blocking-unbounded

    def _spawn_and_wait(self):
        t = threading.Thread(target=self._drain)
        t.start()
        t.join()  # EXPECT: blocking-unbounded

    def _fetch_race(self, peer, fast, timeout_s):
        if fast:
            # the pre-clamp program point: timeout_s is still the
            # caller's unvetted value here
            return urllib.request.urlopen(peer, timeout=timeout_s)  # EXPECT: blocking-unbounded
        timeout_s = min(timeout_s, 2.0)
        return urllib.request.urlopen(peer, timeout=timeout_s)

    def _audit(self, peer):
        with self._lock:
            self.served += 1
            urllib.request.urlopen(peer, timeout=2.0)  # EXPECT: hold-lock-while-blocking
