"""cache_coherence true negatives: the sanctioned forms stay silent.

Pins: the single-entry-point invalidation idiom (transitive credit),
the clear-loop token form, direct per-site clears, the `__init__`
pre-publication exemption, `invalidated-by: none` with a genuinely
immutable read-set, and backing-store fills/drops staying exempt.
"""

import functools

import jax

_MODE = "auto"
_EXTRA = 1.0


def _kernel(x):
    return x * _EXTRA if _MODE == "auto" else x


_jitted_kernel = jax.jit(_kernel)


@functools.lru_cache(maxsize=4)
def cached_thing(n):
    return (_MODE, n)


def _clear_all():
    """The single invalidation entry point (the _clear_dependent_caches
    shape, including the clear-loop token form)."""
    for fn in (_jitted_kernel,):
        fn.clear_cache()
    cached_thing.cache_clear()


def set_mode(mode):
    # routed through the entry point: transitively credited
    global _MODE
    _MODE = mode
    _clear_all()


def set_extra(v):
    # direct per-site clears are just as coherent
    global _EXTRA
    _EXTRA = v
    _jitted_kernel.clear_cache()
    cached_thing.cache_clear()


class Owner:
    def __init__(self):
        # pre-publication construction: exempt by design
        global _MODE
        _MODE = "owner"


# append-only memo over pure inputs: nothing to invalidate
# cache: lookup invalidated-by: none
_LOOKUP = {}


def lookup(k):
    v = _LOOKUP.get(k)
    if v is None:
        v = k + 1
        _LOOKUP[k] = v
    return v


# manual cache with a real invalidator; fills and drops of the backing
# store are the cache's own business, not read-set mutations
_CFG_SRC = "file"
# cache: state invalidated-by: drop_state
_STATE = None


def get_state():
    global _STATE
    if _STATE is None:
        _STATE = {"src": _CFG_SRC}
    return _STATE


def drop_state():
    global _STATE
    _STATE = None


def set_cfg_src(v):
    global _CFG_SRC
    _CFG_SRC = v
    drop_state()
