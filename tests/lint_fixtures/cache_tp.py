"""cache_coherence true positives: every line marked EXPECT must be
caught by exactly that rule.

Each scenario uses its own dependency global so the expected findings
stay independent (a shared mode global would cross-obligate every
cache here).
"""

import functools

# -- 1. mutation that never reaches the cache's invalidator ----------- #

_PLAN_MODE = "auto"


@functools.lru_cache(maxsize=8)
def cached_plan(n):
    return (_PLAN_MODE, n)


def set_plan_mode_no_clear(mode):
    global _PLAN_MODE
    _PLAN_MODE = mode  # EXPECT: cache-stale-mutation


# -- 2. early return crossing an undischarged obligation -------------- #

_LAYOUT = "rowmajor"


@functools.lru_cache(maxsize=8)
def cached_layout(n):
    return (_LAYOUT, n)


def set_layout(mode, dry_run=False):
    global _LAYOUT
    _LAYOUT = mode  # EXPECT: cache-stale-mutation
    if dry_run:
        return
    cached_layout.cache_clear()


# -- 3. gutted invalidator: registered but no longer drops ------------ #

_TBL_SRC = "default"
# cache: table invalidated-by: rebuild_table
_TABLE = None


def table():
    global _TABLE
    if _TABLE is None:
        _TABLE = {"src": _TBL_SRC}
    return _TABLE


def rebuild_table():  # EXPECT: cache-invalidator-gutted
    # the drop (`_TABLE = None`) was "cleaned up"; callers that route
    # through this entry point now invalidate nothing
    return table()


def set_tbl_src(v):
    global _TBL_SRC
    _TBL_SRC = v
    rebuild_table()


# -- 4. declared-immutable cache fed by mutable state ----------------- #

_FROZEN_SRC = 1
# cache: frozen invalidated-by: none
_FROZEN = {}


def frozen_lookup(k):
    v = _FROZEN.get(k)
    if v is None:
        v = _FROZEN_SRC + k
        _FROZEN[k] = v
    return v


def bump_frozen_src():
    global _FROZEN_SRC
    _FROZEN_SRC += 1  # EXPECT: cache-stale-mutation


# -- 5. memo idiom with no declaration -------------------------------- #

_MEMO: dict = {}  # EXPECT: cache-undeclared


def memo_get(k):
    v = _MEMO.get(k)
    if v is None:
        v = k * 2
        _MEMO[k] = v
    return v


# -- 6. annotation pointing at nothing -------------------------------- #

_ORPHAN = {}  # cache: orphan invalidated-by: no_such_function  # EXPECT: cache-bad-annotation


def orphan_get(k):
    return _ORPHAN.get(k)
