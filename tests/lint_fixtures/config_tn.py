"""True-negative fixtures for the config_schema analyzer: declared keys
read through the right getters — ZERO findings against the miniature
schema (tsd.good.flag bool / tsd.good.count int / tsd.good.name str).
Parsed, never imported.
"""

import logging

# a dotted logger name is not a config key (call arguments are exempt
# from the module-constant idiom)
LOG = logging.getLogger("tsd.fixture")

WELL_KNOWN = "tsd.good.name"


def read(config):
    flag = config.get_bool("tsd.good.flag")
    count = config.get_int("tsd.good.count")
    # get_string is the raw accessor, legal on any declared key
    raw = config.get_string("tsd.good.count")
    name = config.get_string(WELL_KNOWN)
    present = config.has_property("tsd.good.flag")
    return flag, count, raw, name, present
