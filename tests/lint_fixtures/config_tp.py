"""True-positive fixtures for the config_schema analyzer.

The unit tests run these against an injected miniature schema:

    tsd.good.flag   -> bool
    tsd.good.count  -> int
    tsd.good.name   -> str

`# EXPECT: <rule>` markers pin the (line, rule) pairs.  Parsed, never
imported.
"""

# a typo'd module-level key constant (the CONFIG_KEY idiom)
TYPOED_KEY = "tsd.good.flga"                 # EXPECT: config-unknown-key

KEY_TABLE = {
    "metric": ("tsd.good.name",
               "tsd.good.nmae"),             # EXPECT: config-unknown-key
}


def read(config):
    if config.get_bool("tsd.good.falg"):     # EXPECT: config-unknown-key
        pass
    n = config.get_bool("tsd.good.count")    # EXPECT: config-type-mismatch
    s = config.get_int("tsd.good.name")      # EXPECT: config-type-mismatch
    return n, s
