"""Effect & purity true negatives for tools/lint/effects.py: every
pattern here is the sanctioned form of an effects_tp.py hazard and must
stay silent under all fifteen analyzers.  Parsed, never imported."""

import threading


class GatedLanes:
    def __init__(self):
        self._lock = threading.Lock()
        self._demand = {}   # guarded-by: _lock
        self._plans = {}    # guarded-by: _lock

    # effects: observe-gated(observe)
    def plan(self, key, observe):
        with self._lock:
            if observe:
                self._demand[key] = self._demand.get(key, 0) + 1
            return self._plans.get(key)

    # effects: observe-gated(observe)
    def plan_early(self, key, observe):
        # early-out domination: everything after the `if not observe`
        # return runs only in the observing arm
        if not observe:
            return self._peek(key)
        self._note(key, observe)
        return self._peek(key)

    def _note(self, key, observe):
        # helper's own gate maps through the call argument above
        if observe:
            with self._lock:
                self._demand[key] = self._demand.get(key, 0) + 1

    def _peek(self, key):
        with self._lock:
            return self._plans.get(key)


class BoundsCache:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = {}   # guarded-by: _lock

    # effects: reads-only
    def peek(self, key):
        with self._lock:
            return self._items.get(key)

    # effects: reads-only
    def dry_consult(self, lanes, key):
        # literal False at the call site drops the callee's
        # observe-gated effects: the dry-run arm really is read-only
        return lanes.plan(key, False)


# effects: pure
def lane_width(start, end, cadence):
    return max(1, (end - start) // cadence)


class Buf:
    def __init__(self):
        self._lock = threading.Lock()
        self._vals = []      # guarded-by: _lock
        self._dirty = False  # guarded-by: _lock

    # value-preserving re-canonicalization: writes confined to the
    # function's own class are the verified claim, not an exemption
    # effects: canonicalize
    def _normalize(self):
        with self._lock:
            self._vals.sort()
            self._dirty = False

    # effects: reads-only
    def bounds(self):
        self._normalize()
        with self._lock:
            if not self._vals:
                return None
            return (self._vals[0], self._vals[-1])
