"""Effect & purity true positives for tools/lint/effects.py.

One case per rule: an observe-gated accounting write leaked out of its
gate, a counter bump inside a `pure` function, a mutator call inside a
`reads-only` method, a malformed annotation, and the explain/permit
entry subtrees reaching a device dispatch and an admission permit
(the entry qnames are wired in by the test's effects-bucket override).
Parsed, never imported.
"""

import threading

import jax.numpy as jnp


class LeakyLanes:
    def __init__(self):
        self._lock = threading.Lock()
        self._demand = {}   # guarded-by: _lock
        self._plans = {}    # guarded-by: _lock

    # the demand observation moved OUT of the `if observe:` arm — the
    # exact regression effect-observe-leak exists to catch
    # effects: observe-gated(observe)
    def plan(self, key, observe):   # EXPECT: effect-observe-leak
        with self._lock:
            self._demand[key] = self._demand.get(key, 0) + 1
            return self._plans.get(key)

    # the grammar requires the gate parameter: observe-gated without
    # one is unenforceable and must be rejected, not guessed
    # effects: observe-gated    # EXPECT: effect-bad-annotation
    def plan_dry(self, key):
        return self._plans.get(key)


class _Reg:
    def gauge(self, name):
        return self

    def set(self, value):
        return None


REGISTRY = _Reg()


# a registry bump is accounting, not computation: `pure` forbids it
# effects: pure
def lane_cost(width):               # EXPECT: effect-violation
    REGISTRY.gauge("tsd.fixture.level").set(float(width))
    return width * 2


class PeekCache:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = {}   # guarded-by: _lock

    # pop() evicts — a consult that promises reads-only must not
    # restructure the cache it peeks at
    # effects: reads-only
    def peek(self, key):            # EXPECT: effect-violation
        with self._lock:
            return self._items.pop(key, None)


def explain_entry(query):
    return _score(query)


def _score(query):
    # device dispatch two edges under the explain entry: reachability
    # reports the SITE, not the entry
    return jnp.ones(3)              # EXPECT: dispatch-reachable


class FixturePermit:
    def acquire(self, cost):
        return True


def permit_entry(query):
    gate = FixturePermit()
    # .acquire on a non-lock receiver is an admission permit: the
    # explain surface must never consume serving capacity
    return gate.acquire(1.0)        # EXPECT: permit-reachable
