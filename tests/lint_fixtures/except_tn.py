"""True-negative fixtures for the exception_discipline analyzer: every
broad handler here visibly deals with the failure — ZERO findings.
Parsed, never imported.
"""

import logging

LOG = logging.getLogger("fixture")


class Handler:
    def __init__(self):
        self.errors = 0

    def logs(self, fn):
        try:
            return fn()
        except Exception:
            LOG.exception("fn failed")
            return None

    def counts(self, fn):
        try:
            return fn()
        except Exception:
            self.errors += 1
            return None

    def reraises(self, fn):
        try:
            return fn()
        except Exception:
            raise RuntimeError("wrapped")

    def narrow(self, fn):
        # narrow catches are outside the rule entirely
        try:
            return fn()
        except (ValueError, KeyError):
            return None

    def propagates_the_object(self, fn):
        try:
            return fn()
        except Exception as e:
            return {"error": str(e)}

    def suppressed(self, fn):
        try:
            return fn()
        except Exception:
            # fixture for the suppression path: silence is deliberate
            pass  # tsdblint: disable=except-swallow
