"""True-positive fixtures for the exception_discipline analyzer.
`# EXPECT: <rule>` markers pin the (line, rule) pairs.  Parsed, never
imported.
"""


def swallow_pass(fn):
    try:
        return fn()
    except Exception:                        # EXPECT: except-swallow
        pass


def swallow_bare(fn):
    try:
        return fn()
    except:                                  # EXPECT: except-swallow  # noqa: E722
        return None


def swallow_default(fn, registry):
    try:
        return fn()
    except (ValueError, Exception):          # EXPECT: except-swallow
        registry.clear()
        return {}
