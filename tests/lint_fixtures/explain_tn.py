"""Explain-endpoint obligation true negatives: the sanctioned shapes
the real handler uses (tsd/rpcs.py handle_explain) — the explain span
as a with-block that closes on success AND on the error path, and
outcome labels from a fixed vocabulary.  Parsed, never imported."""

REGISTRY = None  # stub: the analyzer matches the receiver NAME


def explain_with_block(obs_trace, engine, ts_query):
    """The handler's shape: stage() is a context manager — the span
    finishes even when the engine raises."""
    with obs_trace.stage("explain") as span:
        report = engine.explain_query(ts_query)
        obs_trace.annotate(span, sub_queries=len(report))
    return report


def explain_counts_fixed_outcomes(ok):
    outcome = "ok" if ok else "error"
    REGISTRY.counter("tsd.fixture.count").labels(
        route=outcome).inc()


def explain_span_hand_finished(obs_trace, engine, ts_query):
    """begin/end is also sanctioned when every path reaches end()."""
    span = obs_trace.begin("explain")
    try:
        return engine.explain_query(ts_query)
    finally:
        obs_trace.end(span)
