"""Explain-endpoint obligation true positives (ISSUE 13): the shapes
the /api/query/explain handler must NOT take — an explain span that
never finishes (the handler's one span obligation), an outcome metric
minted from a raw request string, and an explain error path that
drops the span on the floor.  Parsed, never imported."""

REGISTRY = None  # stub: the analyzer matches the receiver NAME


def explain_span_never_finished(obs_trace, engine, ts_query, reply):
    """A handler that begins the explain span and forgets it: the
    request trace would keep an open child forever."""
    span = obs_trace.begin("explain")  # EXPECT: resource-leak
    reply.send(engine.explain_query(ts_query))


def explain_span_leaks_on_disabled_return(obs_trace, engine, ts_query,
                                          enabled):
    span = obs_trace.begin("explain")
    if not enabled:
        return None  # EXPECT: resource-leak-return
    report = engine.explain_query(ts_query)
    obs_trace.end(span)
    return report


def explain_outcome_from_raw_request(route):
    """Outcome labels must come from a fixed vocabulary, never a
    client-chosen string — the tenant-clamp rule, applied to explain."""
    REGISTRY.counter("tsd.fixture." + route).inc()  # EXPECT: metrics-dynamic-name
