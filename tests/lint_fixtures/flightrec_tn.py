"""Flight-recorder lifecycle true negatives: the sanctioned shapes —
subscribe paired with a shutdown-reachable unsubscribe (the
obs/flightrec.py FlightRecorder lifecycle), and a dump that closes its
handle on every path.  Parsed, never imported."""


class GoodRecorder:
    """Install in start, uninstall in shutdown — the FlightRecorder
    shape (obs/flightrec.py)."""

    def __init__(self, capture):
        self.capture = capture

    def start(self):
        # global-install: unsubscribe paired-with: shutdown
        self.capture.subscribe(self._on_compile)

    def shutdown(self):
        self.capture.unsubscribe(self._on_compile)
        dump_with_close("events.json", [])

    def _on_compile(self, kernel):
        return kernel


def dump_with_close(path, events):
    """The sanctioned dump: a with-block closes the black box even
    when the JSON encode raises."""
    import json
    with open(path, "w") as fh:
        json.dump(list(events), fh)
