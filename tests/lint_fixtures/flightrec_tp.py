"""Flight-recorder lifecycle true positives (ISSUE 12): a recorder
whose compile-capture subscription pairing rotted, and a shutdown dump
that leaks its file handle.  Parsed, never imported."""


class BadRecorderPairingGutted:
    """The pairing function exists but was 'simplified' and no longer
    unsubscribes — the capture handler would outlive the recorder."""

    def __init__(self, capture):
        self.capture = capture
        # global-install: unsubscribe paired-with: shutdown  # EXPECT: install-missing-uninstall
        capture.subscribe(self._on_compile)

    def shutdown(self):
        self.capture = None

    def _on_compile(self, kernel):
        return kernel


class BadRecorderUnreachableUninstall:
    """The uninstall exists and works — but no shutdown/close/stop
    path ever reaches it, so the black box never detaches."""

    def __init__(self, capture):
        self.capture = capture
        # global-install: unsubscribe paired-with: detach  # EXPECT: install-unreachable-uninstall
        capture.subscribe(self._on_compile)

    def detach(self):
        self.capture.unsubscribe(self._on_compile)

    def _on_compile(self, kernel):
        return kernel


def dump_leaks_handle(path, events):
    """A shutdown dump that drops its handle: the black box file may
    be torn/unflushed exactly when it matters (SIGTERM)."""
    import json
    fh = open(path, "w")                     # EXPECT: resource-leak
    fh.write(json.dumps(list(events)))


def dump_leaks_on_early_return(path, events, enabled):
    import json
    fh = open(path, "w")
    if not enabled:
        return None                          # EXPECT: resource-leak-return
    fh.write(json.dumps(list(events)))
    fh.close()
    return path
