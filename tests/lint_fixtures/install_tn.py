"""Paired-install true negatives: the sanctioned lifecycles."""


class GoodDirect:
    """Install in __init__, uninstall in shutdown — the
    OnlineCalibrator shape."""

    def __init__(self, reg):
        self.reg = reg
        # global-install: remove_hook paired-with: shutdown
        reg.install_hook(self._on_event)

    def shutdown(self):
        self.reg.remove_hook(self._on_event)

    def _on_event(self, event):
        return event


class GoodIndirect:
    """The pairing function is a helper reached from a close path —
    reachability is transitive."""

    def __init__(self, reg):
        self.reg = reg
        # global-install: remove_hook paired-with: _teardown_hooks
        reg.install_hook(self._on_event)

    def _teardown_hooks(self):
        self.reg.remove_hook(self._on_event)

    def close(self):
        self._teardown_hooks()

    def _on_event(self, event):
        return event
