"""Paired-install true positives for cache_coherence's install rules."""


class BadMissingPair:
    """The pairing function does not exist at all."""

    def __init__(self, reg):
        self.reg = reg
        # global-install: remove_hook paired-with: no_such_close  # EXPECT: install-missing-uninstall
        reg.install_hook(self._on_event)

    def _on_event(self, event):
        return event


class BadNeverUninstalls:
    """`close` exists but was 'simplified' and no longer uninstalls."""

    def __init__(self, reg):
        self.reg = reg
        # global-install: remove_hook paired-with: close  # EXPECT: install-missing-uninstall
        reg.install_hook(self._on_event)

    def close(self):
        self.reg = None

    def _on_event(self, event):
        return event


class BadUnreachable:
    """The uninstall exists and works — but nothing on any
    shutdown/close/stop path ever calls it."""

    def __init__(self, reg):
        self.reg = reg
        # global-install: remove_hook paired-with: detach_hooks  # EXPECT: install-unreachable-uninstall
        reg.install_hook(self._on_event)

    def detach_hooks(self):
        self.reg.remove_hook(self._on_event)

    def _on_event(self, event):
        return event
