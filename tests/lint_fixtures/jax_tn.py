"""True-negative fixtures for the jax_hygiene analyzer: every pattern
here is legitimate and must produce ZERO findings.

Parsed, never imported.  The x64 guard for the jnp.int64 use below is
the module's own jax_enable_x64 update — the pattern the ops package
__init__ uses.
"""

from functools import lru_cache, partial

import jax
import jax.numpy as jnp

jax.config.update("jax_enable_x64", True)


def kernel(spec, ts, val):
    # branching on a STATIC argument is resolved at trace time
    if spec == "sum":
        out = jnp.sum(val)
    else:
        out = jnp.max(val)
    # shape/dtype/len are static metadata, not traced values
    if ts.dtype == jnp.int32 or ts.shape[0] > 4 or len(ts.shape) > 1:
        out = out + 1
    # membership on a traced-args dict with a constant key is static
    return jnp.where(val > 0, out, 0.0)


_jitted = jax.jit(kernel, static_argnums=(0,))


@partial(jax.jit, static_argnums=(1,))
def decorated(ts, width: int):
    # int() on static metadata is fine
    return ts.reshape(int(ts.shape[0] // width), width).astype(jnp.int64)


@lru_cache(maxsize=8)
def builder(n: int):
    # memoized builder: one jit wrapper per static n, the blessed
    # pattern for shape-keyed construction
    def gather(ts):
        return ts[:n]
    return jax.jit(gather)


def grid(wargs, ts):
    if "base" in wargs:       # constant-key membership: trace-static
        ts = ts + wargs["base"]
    return ts


_jitted_grid = jax.jit(grid)
