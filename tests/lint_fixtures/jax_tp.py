"""True-positive fixtures for the jax_hygiene analyzer.

Each hazardous line carries an `# EXPECT: <rule>` marker; the analyzer
unit tests assert exactly those (line, rule) pairs fire — no more, no
less.  This module is parsed, never imported.
"""

import jax
import jax.numpy as jnp
import numpy as np


def kernel(ts, val, threshold):
    if val > threshold:                      # EXPECT: jax-tracer-branch
        return ts
    peak = float(val)                        # EXPECT: jax-host-sync
    host = np.asarray(ts)                    # EXPECT: jax-host-sync
    first = ts[0].item()                     # EXPECT: jax-host-sync
    while val > 0:                           # EXPECT: jax-tracer-branch
        val = val - 1
    return peak + host.sum() + first


_jitted = jax.jit(kernel)


def helper(x):
    # reached transitively from the jitted root: x is traced here too
    return x.tolist()                        # EXPECT: jax-host-sync


def outer(ts, val, threshold):
    return helper(ts)


_jitted_outer = jax.jit(outer, static_argnums=(2,))


def per_call_wrapper(fn, ts):
    wrapped = jax.jit(fn)                    # EXPECT: jax-jit-per-call
    return wrapped(ts)


def widen(ts):
    # no x64 guard anywhere in this module or its package
    return ts.astype(jnp.int64)              # EXPECT: jax-int64-no-x64-guard
