"""True-negative fixtures for the resource_leak analyzer: every
acquisition here reaches cleanup or transfers ownership, and must stay
silent.  Parsed, never imported."""

import socket
from concurrent.futures import ThreadPoolExecutor


def with_managed(path):
    with open(path) as fh:
        return fh.readlines()


def try_finally(path):
    fh = open(path)
    try:
        return fh.readlines()
    finally:
        fh.close()


def acquired_inside_try(path, strict):
    # the finally protects acquisitions INSIDE the try body too —
    # including the early return crossing the live handle
    try:
        fh = open(path)
        if not strict:
            return None
        return fh.readlines()
    finally:
        fh.close()


def closed_before_return(path):
    fh = open(path)
    data = fh.readlines()
    fh.close()
    return data


def spill_file_finally(path, arr):
    from opentsdb_tpu.storage import spill
    fh = spill.open_spill_file(path)
    try:
        fh.write(arr.tobytes())
    finally:
        fh.close()


def spill_file_ownership_to_pool(path, table, key):
    # ownership transfer: the pool's files table unlinks it on free()
    from opentsdb_tpu.storage import spill
    fh = spill.open_spill_file(path)
    table[key] = fh
    return key


def ownership_returned(path):
    fh = open(path)
    return fh                           # the caller owns it now


class Holder:
    def __init__(self, path):
        self._fh = None
        self.attach(path)

    def attach(self, path):
        fh = open(path)
        self._fh = fh                   # object owns it; closed elsewhere

    def close(self):
        if self._fh is not None:
            self._fh.close()


def registered_elsewhere(path, registry):
    fh = open(path)
    registry.append(fh)                 # container owns it now


def pool_shut_down(jobs):
    pool = ThreadPoolExecutor(max_workers=4)
    try:
        for job in jobs:
            pool.submit(job)
    finally:
        pool.shutdown(wait=False)


def socket_closed(host, port):
    conn = socket.create_connection((host, port))
    conn.sendall(b"version\n")
    conn.close()
