"""True-positive fixtures for the resource_leak analyzer.  Parsed,
never imported.  The analyzer unit tests inject this file's path as the
leak scope."""

import socket
from concurrent.futures import ThreadPoolExecutor


def never_closed(path):
    fh = open(path)                          # EXPECT: resource-leak
    fh.readlines()


def early_return_leaks(path, strict):
    fh = open(path)
    if not strict:
        return None                          # EXPECT: resource-leak-return
    data = fh.readlines()
    fh.close()
    return data


def executor_never_shut_down(jobs):
    pool = ThreadPoolExecutor(max_workers=4)  # EXPECT: resource-leak
    for job in jobs:
        pool.submit(job)


def socket_dropped(host, port):
    conn = socket.create_connection((host, port))  # EXPECT: resource-leak
    conn.sendall(b"version\n")


def spill_file_dropped(path, arr):
    from opentsdb_tpu.storage.spill import open_spill_file
    fh = open_spill_file(path)                     # EXPECT: resource-leak
    fh.write(arr.tobytes())
