"""True-negative fixtures for the lock_discipline analyzer: disciplined
locking that must produce ZERO findings.  Parsed, never imported.
"""

import threading


class DisciplinedCounter:
    def __init__(self):
        self._lock = threading.Lock()
        # guarded-by: _lock
        self.hits = 0
        self.misses = 0  # guarded-by: _lock
        self.label = ""          # never mutated under the lock: not shared

    def record(self, hit: bool):
        with self._lock:
            if hit:
                self.hits += 1
            else:
                self.misses += 1

    def rename(self, label: str):
        self.label = label


class CallerHoldsConvention:
    def __init__(self):
        self._lock = threading.Lock()
        self.entries = {}  # guarded-by: _lock

    def put(self, k, v):
        with self._lock:
            self._evict_locked()
            self.entries[k] = v

    def _evict_locked(self):
        # *_locked methods run with the caller holding the lock
        while len(self.entries) > 8:
            self.entries.popitem()


class ReentrantSelfCall:
    def __init__(self):
        self._lock = threading.RLock()
        self.n = 0  # guarded-by: _lock

    def bump(self):
        with self._lock:
            self.n += 1

    def bump_twice(self):
        # RLock: re-acquiring on the same instance is reentrant, no cycle
        with self._lock:
            self.bump()
            self.n += 1


class NoLocksAtAll:
    """Single-threaded helper: no locks, no annotation obligations."""

    def __init__(self):
        self.count = 0

    def bump(self):
        self.count += 1
