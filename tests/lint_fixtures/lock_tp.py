"""True-positive fixtures for the lock_discipline analyzer.

`# EXPECT: <rule>` markers pin the (line, rule) pairs the unit tests
assert.  Parsed, never imported.
"""

import threading


class UnannotatedCounter:
    def __init__(self):
        self._lock = threading.Lock()
        self.hits = 0                        # EXPECT: lock-missing-annotation

    def record(self):
        with self._lock:
            self.hits += 1


class RacyCounter:
    def __init__(self):
        self._lock = threading.Lock()
        self.total = 0  # guarded-by: _lock

    def ok(self):
        with self._lock:
            self.total += 1

    def racy(self):
        self.total += 1                      # EXPECT: lock-unguarded-mutation


class BogusAnnotation:
    def __init__(self):
        self._lock = threading.Lock()
        self.x = 0  # guarded-by: _mutex     # EXPECT: lock-missing-annotation

    def bump(self):
        with self._lock:
            self.x += 1


class AlphaAB:
    def __init__(self, beta):
        self._lock_a = threading.Lock()
        self.beta: "BetaBA" = beta
        self.n = 0  # guarded-by: _lock_a

    def forward(self):
        with self._lock_a:
            self.n += 1
            self.beta.poke()

    def poke_a(self):
        with self._lock_a:
            self.n += 1


class BetaBA:
    def __init__(self, alpha):
        self._lock_b = threading.Lock()
        self.alpha: "AlphaAB" = alpha
        self.m = 0  # guarded-by: _lock_b

    def poke(self):
        with self._lock_b:
            self.m += 1

    def backward(self):
        with self._lock_b:
            self.alpha.poke_a()              # EXPECT: lock-order-cycle


class SelfDeadlock:
    def __init__(self):
        self._lock = threading.Lock()
        self.k = 0  # guarded-by: _lock

    def inner(self):
        with self._lock:
            self.k += 1

    def outer(self):
        with self._lock:
            self.inner()                     # EXPECT: lock-order-cycle
