"""metrics_schema true negatives: declared names, matching kinds and
labels, the %-template wildcard form, and the suppressed sanctioned
forwarder."""

REGISTRY = None  # stub: the analyzer matches the receiver NAME


def emit(collector, route, kind, walked):
    REGISTRY.counter("tsd.fixture.count",
                     "Requests by route").labels(route=route).inc()
    REGISTRY.gauge("tsd.fixture.level").set(3)
    REGISTRY.histogram("tsd.fixture.latency_ms").observe(1.5)
    collector.record("fixture.pushed", 2, "kind=%s" % kind)
    collector.record("fixture.level", 1)
    collector.record("%s.errors" % kind, 1, "type=storage")
    for name, value in walked:
        # sanctioned forwarder: names already declared + walked
        # tsdblint: disable=metrics-dynamic-name
        collector.record(name, value)
