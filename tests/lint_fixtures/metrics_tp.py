"""metrics_schema true positives (checked against the fixture schema
injected by tests/test_lint_analyzers.py, not the real
METRICS_SCHEMA)."""

REGISTRY = None  # stub: the analyzer matches the receiver NAME


def emit(collector, route):
    REGISTRY.counter("tsd.fixture.typo").inc()  # EXPECT: metrics-unknown-name
    REGISTRY.gauge("tsd.fixture.count").set(1)  # EXPECT: metrics-kind-collision
    REGISTRY.counter("tsd.fixture." + route).inc()  # EXPECT: metrics-dynamic-name
    REGISTRY.counter("tsd.fixture.count").labels(method=route).inc()  # EXPECT: metrics-unknown-label
    collector.record("fixture.unknown", 1)  # EXPECT: metrics-unknown-name
    collector.record("fixture.count", 1)  # EXPECT: metrics-kind-collision
    collector.record("fixture.pushed", 1, "peer=x")  # EXPECT: metrics-unknown-label
