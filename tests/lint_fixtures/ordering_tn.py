"""Ordering & failure-atomicity true negatives — correct orderings,
rolled-back transitions, and protected installs stay silent."""

import threading

# order: tn-write before tn-mark


class MarkedStore:
    def __init__(self):
        self._lock = threading.Lock()
        self._data = {}   # guarded-by: _lock
        self._marks = 0   # guarded-by: _lock

    def write(self, key, value):
        with self._lock:
            self._data[key] = value   # order-event: tn-write

    def mark(self):
        with self._lock:
            self._marks += 1          # order-event: tn-mark

    def put(self, key, value):
        self.write(key, value)
        self.mark()

    def remark(self):
        # sequences only the mark side: the contract binds functions
        # that order BOTH events, not every site that emits one
        self.mark()


class RolledBackSession:
    def __init__(self):
        self._lock = threading.Lock()
        self._state = "idle"   # guarded-by: _lock
        self._epoch = 0        # guarded-by: _lock

    def advance(self, loader):
        # fallible work hoisted before the first write: a raise here
        # leaves the transition untouched
        payload = loader.fetch()
        with self._lock:
            self._state = "loading"
            self._epoch += 1
        return payload

    def advance_guarded(self, loader):
        with self._lock:
            prev = self._state
            self._state = "loading"
            try:
                loader.push(self._epoch)
                self._epoch += 1
            except Exception:
                self._state = prev
                raise
        return prev

    def branch_local(self, fresh):
        # writes in opposite branches can never interleave on a path
        with self._lock:
            if fresh:
                self._state = "fresh"
            else:
                self._epoch += 1


class ProtectedPlugin:
    def __init__(self, reg, config):
        self.reg = reg
        # global-install: remove_hook paired-with: shutdown
        reg.install_hook(self._on_event)
        try:
            self.limit = config.parse_limit()
        except Exception:
            # a failed construction uninstalls before re-raising
            reg.remove_hook(self._on_event)
            raise

    def shutdown(self):
        self.reg.remove_hook(self._on_event)

    def _on_event(self, event):
        return event
