"""Ordering & failure-atomicity true positives for tools/lint/ordering.py.

One case per rule: a happens-before contract violated by a reordered
sequencer, a multi-write guarded transition torn by an interleaved
fallible call, and a global install armed before later fallible
__init__ work with no rollback.
"""

import threading

# order: fx-write before fx-mark


class MarkedStore:
    def __init__(self):
        self._lock = threading.Lock()
        self._data = {}   # guarded-by: _lock
        self._marks = 0   # guarded-by: _lock

    def write(self, key, value):
        with self._lock:
            self._data[key] = value   # order-event: fx-write

    def mark(self):
        with self._lock:
            self._marks += 1          # order-event: fx-mark

    def put(self, key, value):
        # readers chase the mark: publishing it before the write makes
        # them re-read and serve the PREVIOUS value as fresh
        self.mark()                   # EXPECT: order-violation
        self.write(key, value)


class TornSession:
    def __init__(self):
        self._lock = threading.Lock()
        self._state = "idle"   # guarded-by: _lock
        self._epoch = 0        # guarded-by: _lock

    def advance(self, loader):
        with self._lock:
            self._state = "loading"
            payload = loader.fetch()   # EXPECT: atomicity-torn-on-raise
            self._epoch += 1
        return payload


class LeakyPlugin:
    def __init__(self, reg, config):
        self.reg = reg
        # global-install: remove_hook paired-with: shutdown
        reg.install_hook(self._on_event)   # EXPECT: install-leak-on-raise
        self.limit = config.parse_limit()

    def shutdown(self):
        self.reg.remove_hook(self._on_event)

    def _on_event(self, event):
        return event
