"""True-negative fixtures for the shape_dtype analyzer: every pattern
here is the sanctioned form of a shape_tp.py hazard and must stay
silent.  Parsed, never imported."""

import jax.numpy as jnp

_x64_marker = True      # this fixture assumes jax_enable_x64, like ops/


# shape: ts[S, N] i64, val[S, N] f64, mask[S, N] bool -> [S, W] f64
def kernel(ts, val, mask):
    return val


# shape: a[S, N] f64, b[S, N] f64 -> [S, N] f64
def pairwise(a, b):
    return a + b


# shape: ts[S, N] i64 -> [S, N] i32
def declared_narrow(ts):
    # the 32-bit result is part of this function's contract: callers
    # passing i64 hit the declared-narrowing exemption, and the clip
    # below saturates instead of wrapping
    return jnp.clip(ts, -2**30, 2**30).astype(jnp.int32)


def clipped_narrowing(ts, val, mask):
    ids = kernel(ts, val, mask)
    bounded = jnp.clip(ts, 0, 2**30)
    offs = bounded.astype(jnp.int32)         # clipped first: fine
    return ids, offs


# shape: ts[S, N] i64, val[S, N] f64, mask[S, N] bool
def well_shaped_call(ts, val, mask):
    return kernel(ts, val, mask)             # ranks and dims line up


# shape: a[S, N] f64
def consistent_binding(a):
    doubled = a + a
    return pairwise(a, doubled)              # both [S, N]: fine


# shape: val[S, N] f64
def axis_in_range(val):
    return jnp.sum(val, axis=1)


# shape: mask[S, N] bool, hi[S, N] f64
def aligned_where(mask, hi):
    lo = jnp.zeros((4, 4), jnp.float64)
    scalar_branch = jnp.where(mask, hi, 0.0)   # weak python scalar: fine
    return jnp.where(mask, hi, lo), scalar_branch


# shape: x[S, N] i32 -> [S, N] i32
def takes_i32(x):
    return x


# shape: ts[S, N] i64
def narrowing_into_declared_param(ts):
    # passing i64 into a contract param declared i32 is the DECLARED
    # narrowing — the callee owns the clamp
    return takes_i32(ts)
