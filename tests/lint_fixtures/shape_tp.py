"""True-positive fixtures for the shape_dtype analyzer.

Each hazardous line carries an `# EXPECT: <rule>` marker.  Parsed, never
imported.  The `_x64_marker` identifier satisfies the jax_hygiene x64
heuristic so only the shape rules are exercised here.
"""

import jax.numpy as jnp

_x64_marker = True      # this fixture assumes jax_enable_x64, like ops/


# shape: ts[S, N] i64, val[S, N] f64, mask[S, N] bool -> [S, W] f64
def kernel(ts, val, mask):
    return val


# shape: a[S, N] f64, b[S, N] f64 -> [S, N] f64
def pairwise(a, b):
    return a + b


# shape: ts[S, N] i64 -> [S, N] i32
def declared_narrow(ts):
    return jnp.clip(ts, -2**30, 2**30).astype(jnp.int32)


# shape: ts[S, N] i64, val[S, N] f64, mask[S, N] bool
def unguarded_narrowing(ts, val, mask):
    ids = kernel(ts, val, mask)
    offs = ts.astype(jnp.int32)              # EXPECT: shape-dtype-narrowing
    demoted = jnp.asarray(val, jnp.float32)  # EXPECT: shape-dtype-narrowing
    return ids, offs, demoted


# shape: ts[S, N] i64, val[S, N] f64, mask[S, N] bool
def rank_mismatch(ts, val, mask):
    collapsed = jnp.sum(val, axis=1)
    return kernel(collapsed, val, mask)      # EXPECT: shape-contract-mismatch


# shape: a[S, N] f64
def transposed_operand(a):
    flipped = a.T
    return pairwise(a, flipped)              # EXPECT: shape-contract-mismatch


# shape: val[S, N] f64
def axis_out_of_range(val):
    return jnp.sum(val, axis=2)              # EXPECT: shape-axis-mismatch


# shape: mask[S, N] bool, hi[S, N] f64
def divergent_where(mask, hi):
    lo = jnp.zeros((4, 4), jnp.float32)
    return jnp.where(mask, hi, lo)           # EXPECT: shape-divergent-dtypes
