"""Span-leak true negatives: the sanctioned span lifecycles.

Pins the cluster fan-out's create-on-owner/finish-on-pool handoff
(ownership transfer via the submit argument), the begin/end pair, the
estimated-child hand-finish idiom, and try/finally."""


def handoff_to_pool(parent, pool, job):
    # created on the owning thread, finished by the pool thread: the
    # submit argument transfers ownership (tsd/cluster.py fan-out)
    span = parent.child("peer_fetch")
    fut = pool.submit(job, span)
    return fut


def begin_end_pair(obs_trace, work):
    sp = obs_trace.begin("pipeline")
    work()
    obs_trace.end(sp)


def estimated_child_hand_finish(parent, share):
    # finish() only fills wall_ms when still None — the explicit store
    # IS the finish (the planner's apportioned stage children)
    child = parent.child("downsample", estimated=True)
    child.device_ms = share
    child.wall_ms = child.device_ms


def finally_finishes(parent, work):
    sp = parent.child("scan")
    try:
        work()
    finally:
        sp.finish()
