"""Span-leak true positives (resource_leak's trace-span acquisition
kind): a Span started via obs/trace.py must reach finish/with/finally
or transfer ownership on every non-exceptional path."""


def stage_never_finished(obs_trace, work):
    sp = obs_trace.begin("pipeline")  # EXPECT: resource-leak
    work()


def early_return_leaks_span(obs_trace, work):
    sp = obs_trace.begin("pipeline")
    if work is None:
        return None  # EXPECT: resource-leak-return
    work()
    obs_trace.end(sp)
    return sp


def child_never_finished(parent, values):
    child = parent.child("aggregate")  # EXPECT: resource-leak
    total = 0
    for v in values:
        total += v
