"""True-negative fixtures for the taint analyzer: every route here is
sanitized the way query/limits.py intends — a budget charge, a limit
guard that raises, or a min() clamp — and must stay silent.  Parsed,
never imported."""

import numpy as np

MAX_BUCKETS = 4096


def alloc_helper(count):
    return np.zeros(count)


def charged_route(query, budget):
    n = int(query.get_query_string_param("n"))
    budget.charge(n)                   # the 413 contract runs FIRST
    buf = np.zeros(n)
    return alloc_helper(n), buf


def clamped_route(query):
    n = int(query.get_query_string_param("n"))
    n = min(n, MAX_BUCKETS)            # explicit clamp launders the size
    return np.zeros(n)


def guarded_route(query, limits):
    n = int(query.required_query_string_param("count"))
    if n > limits.get_data_points_limit("m"):
        raise ValueError("over budget")
    return alloc_helper(n)


def proportional_route(query):
    # len() of data the request already shipped is proportional, not
    # amplified — the analyzer deliberately treats it as clean
    parts = (query.get_query_string_param("csv") or "").split(",")
    return np.zeros(len(parts))


def untainted_route(config):
    n = config.get_int("tsd.good.count")  # operator-controlled, not a
    return np.zeros(n)                    # request field
