"""True-positive fixtures for the taint analyzer: request fields sizing
allocations with no limits sanitizer on the route.  Parsed, never
imported.  The analyzer unit tests inject this file's path as the sink
scope."""

import numpy as np


def pad_pow2(n, floor=8):
    out = floor
    while out < n:                 # control dependence: n sizes out
        out *= 2
    return out


def alloc_helper(count):
    return np.zeros(count)


def direct_sink(query):
    n = int(query.get_query_string_param("n"))
    buf = np.zeros(n)                        # EXPECT: taint-unsanitized-alloc
    rows = [None] * n                        # EXPECT: taint-unsanitized-alloc
    for _ in range(n):                       # EXPECT: taint-unsanitized-alloc
        rows.append(buf)
    return rows


def interprocedural_sink(query):
    n = int(query.required_query_string_param("count"))
    return alloc_helper(n)                   # EXPECT: taint-unsanitized-alloc


def while_amplified_sink(query):
    n = int(query.get_query_string_param("windows"))
    padded = pad_pow2(n)
    return np.empty(padded + 1)              # EXPECT: taint-unsanitized-alloc


def body_sink(query):
    body = query.json_body()
    k = int(body["buckets"])
    return np.full(k, 0.0)                   # EXPECT: taint-unsanitized-alloc


def min_of_two_tainted(query):
    a = int(query.get_query_string_param("a"))
    b = int(query.get_query_string_param("b"))
    n = min(a, b)      # both operands request-derived: bounds nothing
    return np.zeros(n)                       # EXPECT: taint-unsanitized-alloc
