"""Dummy plugin implementations exercised by the loader/SPI tests (the
test/plugin/Dummy* pattern from the reference suite)."""

from opentsdb_tpu.auth import AuthState, AuthStatus, Authentication
from opentsdb_tpu.plugins import (
    RTPublisher, StorageExceptionHandler, WriteableDataPointFilterPlugin)


class RecordingPublisher(RTPublisher):
    def __init__(self):
        self.points = []

    def publish_data_point(self, metric, timestamp, value, tags, tsuid):
        self.points.append((metric, timestamp, value))


class RecordingSEH(StorageExceptionHandler):
    def __init__(self):
        self.errors = []

    def handle_error(self, dp, exception):
        self.errors.append((dp, str(exception)))


class EvenOnlyFilter(WriteableDataPointFilterPlugin):
    def allow(self, metric, timestamp, value, tags):
        return int(value) % 2 == 0


class DenyAuth(Authentication):
    def authenticate_telnet(self, conn, command):
        if len(command) >= 3 and command[0] == "auth" and \
                command[2] == "secret":
            return AuthState(user=command[1], status=AuthStatus.SUCCESS)
        return AuthState(status=AuthStatus.UNAUTHORIZED)

    def authenticate_http(self, conn, request):
        if request.header("x-token") == "secret":
            return AuthState(user="u", status=AuthStatus.SUCCESS)
        return AuthState(status=AuthStatus.UNAUTHORIZED)
