# tsdbsan seeded fixture: TRUE NEGATIVES for the deadlock watcher.
# Sanctioned locking shapes that must come back CLEAN:
#
#   * a consistent two-lock order used repeatedly (Outer before Inner,
#     every time) — edges exist but no cycle;
#   * two instances of the SAME class acquired nested in a consistent
#     instance order — the canonical-order peer idiom; a same-label
#     edge only becomes an inversion when BOTH orders are observed;
#   * reentrant RLock re-acquired by its owner — not a self-deadlock.

import threading


class Outer:
    def __init__(self):
        self._lock = threading.Lock()


class Inner:
    def __init__(self):
        self._lock = threading.Lock()


class Peer:
    def __init__(self):
        self._plock = threading.RLock()


def run():
    outer = Outer()
    inner = Inner()
    for _ in range(3):
        with outer._lock:
            with inner._lock:
                pass
    # peers in one canonical order only
    first, second = Peer(), Peer()
    with first._plock:
        with second._plock:
            pass
    with first._plock:
        with second._plock:
            pass
    # reentrant self re-acquire is sanctioned
    with first._plock:
        with first._plock:
            pass
    return outer, inner, first, second
