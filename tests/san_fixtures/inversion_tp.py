# tsdbsan seeded-bug fixture: TRUE POSITIVE for the deadlock watcher's
# order-graph detector.
#
# The two `with` blocks below acquire (Left._lock, Right._lock) in BOTH
# orders — serialized, so nothing actually deadlocks this run, which is
# exactly the point: the inversion is a latent hazard the order graph
# catches without needing the fatal interleaving.  The finding lands on
# the acquire that closes the cycle.

import threading


class Left:
    def __init__(self):
        self._lock = threading.Lock()


class Right:
    def __init__(self):
        self._lock = threading.Lock()


def run():
    left = Left()
    right = Right()
    with left._lock:
        with right._lock:
            pass
    with right._lock:
        with left._lock:  # EXPECT: san-lock-order-inversion
            pass
    return left, right
