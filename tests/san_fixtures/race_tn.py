# tsdbsan seeded fixture: TRUE NEGATIVES for the lockset detector.
# Every pattern here is a sanctioned form and must come back CLEAN:
#
#   * annotated attribute always mutated under its declared lock;
#   * unannotated attribute written by several threads but ALWAYS under
#     the same lock (non-empty lockset — annotate it eventually, but it
#     is not racing);
#   * construct-then-hand-off: the worker thread becomes the sole
#     writer after __init__ — the classic Eraser false positive the
#     ownership-handoff state machine must stay silent on;
#   * a deliberately racy write carrying a justified
#     `# tsdblint: disable=` suppression — the shared suppression
#     syntax must clear sanitizer findings exactly as it clears lint's.

import threading


class DisciplinedCounter:
    def __init__(self):
        self._lock = threading.Lock()
        self.total = 0  # guarded-by: _lock
        self.approx = 0     # unannotated, but always written under _lock
        self.handoff = 0    # written only by the worker after __init__
        self.noisy = 0      # racy on purpose; suppressed below

    def bump(self):
        with self._lock:
            self.total += 1
            self.approx += 1

    def worker_only(self):
        self.handoff += 1

    def suppressed_racy(self):
        # fixture-only: proves tsdbsan honors the shared suppression form
        self.noisy += 1  # tsdblint: disable=san-lockset-race


def run():
    c = DisciplinedCounter()
    c.bump()
    t = threading.Thread(target=c.bump)
    t.start()
    t.join()
    # hand-off: only the worker writes `handoff` post-construction
    t2 = threading.Thread(target=c.worker_only)
    t2.start()
    t2.join()
    # suppressed race: main + worker + main again, no lock — would be a
    # san-lockset-race without the inline disable
    c.suppressed_racy()
    t3 = threading.Thread(target=c.suppressed_racy)
    t3.start()
    t3.join()
    c.suppressed_racy()
    return c
