# tsdbsan seeded-bug fixture: TRUE POSITIVES for the lockset detector.
#
# Driven by tests/test_sanitizer.py, which instruments this module,
# runs `run()`, and asserts the findings land EXACTLY on the
# `# EXPECT:` lines below (the lint fixture convention).
#
# Two seeded bugs:
#   * `guarded_total` carries a `# guarded-by:` annotation but
#     `unguarded_bump` mutates it without the lock — the runtime twin
#     of tsdblint's lock-unguarded-mutation, caught even though the
#     static analyzer was never shown this file.
#   * `free_total` has NO annotation and is written by two threads with
#     no common lock — Eraser lockset intersection goes empty after the
#     original writer returns post-handoff, which static lint cannot
#     see at all.

import threading


class RacyCounter:
    def __init__(self):
        self._lock = threading.Lock()
        self.guarded_total = 0  # guarded-by: _lock
        self.free_total = 0     # deliberately unannotated shared state

    def locked_bump(self):
        with self._lock:
            self.guarded_total += 1

    def unguarded_bump(self):
        self.guarded_total += 1  # EXPECT: san-unguarded-mutation

    def free_bump(self):
        self.free_total += 1  # EXPECT: san-lockset-race


def run():
    c = RacyCounter()
    c.locked_bump()
    # a second thread mutates the annotated attribute with no lock held
    t = threading.Thread(target=c.unguarded_bump)
    t.start()
    t.join()
    # Eraser: main writes, a worker writes (handoff — still silent),
    # then main writes AGAIN -> two shared-state writers, empty lockset
    c.free_bump()
    t2 = threading.Thread(target=c.free_bump)
    t2.start()
    t2.join()
    c.free_bump()
    return c
