# tsdbsan seeded fixture: TRUE NEGATIVE for the JAX compile sanitizer.
#
# The sanctioned builder shape: the jit wrapper is constructed once
# under functools.lru_cache (the fix pattern from parallel/sharded.py),
# so steady-state calls are pure cache hits — zero compiles, zero
# findings.

from functools import lru_cache

import jax


def _triple(v):
    return v * 3


@lru_cache(maxsize=None)
def _jitted_triple():
    return jax.jit(_triple)


def cached_kernel(x):
    return _jitted_triple()(x)


def run(x):
    return cached_kernel(x)
