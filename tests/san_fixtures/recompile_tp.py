# tsdbsan seeded-bug fixture: TRUE POSITIVE for the JAX compile
# sanitizer.
#
# `per_call_kernel` closes over a FRESH inner function and jits it on
# every invocation — the exact bug shape tsdblint's jax-jit-per-call
# rule catches statically (and PR 2 fixed in parallel/sharded.py, where
# each rollup pass built a fresh shard_map closure).  A fresh function
# object per call defeats every jit cache, so the kernel re-traces and
# recompiles in the steady phase; the sanitizer attributes the finding
# to the triggering call line.

import jax


def per_call_kernel(x):
    def _double(v):              # fresh closure -> fresh jit cache key
        return v * 2 + 1

    step = jax.jit(_double)
    return step(x)  # EXPECT: san-recompile-after-warmup


def run(x):
    return per_call_kernel(x)
