# tsdbsan seeded fixture: TRUE NEGATIVES shaped like the replication
# manager's DISCIPLINED shared state (tsd/replication.py).  Every
# pattern here is the sanctioned form the real manager uses and must
# come back CLEAN:
#
#   * annotated position/chain state always mutated under the manager
#     lock, from both the ship-ack path and the puller thread;
#   * an unannotated scratch attribute written by several threads but
#     ALWAYS under the same lock (non-empty lockset);
#   * the puller-thread handle mutated only before the thread starts
#     and after it joins (construct-then-hand-off shape).

import threading


class DisciplinedShipQueue:
    """The lock discipline ReplicationManager actually follows."""

    def __init__(self):
        self._lock = threading.Lock()
        self.peer_position = 0  # guarded-by: _lock
        self.chain = 0          # guarded-by: _lock
        self.inflight = 0       # unannotated, but always under _lock
        self.rounds = 0         # written only by the puller post-start

    def ack(self, seq):
        with self._lock:
            self.peer_position = max(self.peer_position, seq)
            self.chain = (self.chain * 31 + seq) & 0xFFFFFFFF
            self.inflight += 1

    def puller_round(self):
        self.rounds += 1
        self.ack(self.rounds)


def run():
    q = DisciplinedShipQueue()
    q.ack(1)
    # ship-ack from a worker thread, lock held inside ack()
    t = threading.Thread(target=q.ack, args=(2,))
    t.start()
    t.join()
    # hand-off: only the puller writes `rounds` post-construction
    t2 = threading.Thread(target=q.puller_round)
    t2.start()
    t2.join()
    return q
