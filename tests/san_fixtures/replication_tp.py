# tsdbsan seeded-bug fixture: TRUE POSITIVES shaped like the
# replication manager's shared state (tsd/replication.py).
#
# Driven by tests/test_sanitizer.py, which instruments this module,
# runs `run()`, and asserts the findings land EXACTLY on the
# `# EXPECT:` lines below (the lint fixture convention).
#
# Two seeded bugs, both the shapes replication threading invites:
#   * `peer_position` carries a `# guarded-by:` annotation (a ship
#     ack and a tail poll both move it), but the ack path below
#     mutates it without the lock — the exact race a synchronous
#     shipper + background puller would have without the manager's
#     `_lock`.
#   * `pending_seqs` is unannotated and mutated by the "ship" thread
#     and the "drain" caller with no common lock — Eraser lockset
#     intersection goes empty once both writers have run.

import threading


class ShipQueue:
    """A deliberately-racy miniature of the per-peer ship state."""

    def __init__(self):
        self._lock = threading.Lock()
        self.peer_position = 0  # guarded-by: _lock
        self.pending_seqs = 0   # deliberately unannotated shared state

    def ack_locked(self, seq):
        with self._lock:
            self.peer_position = max(self.peer_position, seq)

    def ack_racy(self, seq):
        self.peer_position = seq  # EXPECT: san-unguarded-mutation

    def stash(self):
        self.pending_seqs += 1  # EXPECT: san-lockset-race


def run():
    q = ShipQueue()
    q.ack_locked(1)
    # the "ship" thread acks without the lock the annotation demands
    t = threading.Thread(target=q.ack_racy, args=(2,))
    t.start()
    t.join()
    # Eraser: main stashes, a worker stashes (handoff — still silent),
    # then main stashes AGAIN -> two shared-state writers, empty lockset
    q.stash()
    t2 = threading.Thread(target=q.stash)
    t2.start()
    t2.join()
    q.stash()
    return q
