"""Overload resilience (ISSUE 8): the admission gate, the
request-scoped Deadline, and cooperative cancellation.

Covers the tentpole contracts deterministically:

  * the Deadline primitive (manual clock — no wall sleeps for expiry),
    the ambient per-thread activation, and QueryBudget deriving its
    clock + cancellation token from it;
  * AdmissionGate permits/queue/shed semantics, priority drain order,
    and queue-wait cancellation that releases WITHOUT dispatching;
  * CancellationHandle bind-before/after-cancel replay;
  * the degradation ladder (coarsen, then truncate);
  * end-to-end through RpcManager.handle_http: shed 503 + Retry-After,
    degraded 200 + partialResults, deadline minting from the header;
  * deadline PROPAGATION to fan-out peers: the coordinator forwards
    its remainder via x-tsdb-deadline-ms and a slow-body peer fetch
    aborts within it (this test FAILS without the clamp — the cluster
    budget alone is configured far beyond the asserted bound);
  * live-socket server behavior: a disconnected client's queued query
    releases without dispatching; TSDServer.stop force-cancels at
    tsd.network.drain_timeout_ms instead of blocking forever.

Runs under TSDBSAN=1 in the sanitized tier-1 subset
(tools/sanitize/run.py) — the gate's lock discipline is race-checked.
"""

import asyncio
import json
import socket
import threading
import time

import pytest

from opentsdb_tpu.core import TSDB
from opentsdb_tpu.models import TSQuery, parse_m_subquery
from opentsdb_tpu.obs.registry import REGISTRY
from opentsdb_tpu.query import limits
from opentsdb_tpu.query.limits import (
    Deadline, QueryBudget, QueryCancelledException, QueryException)
from opentsdb_tpu.tsd import admission
from opentsdb_tpu.tsd.admission import (
    AdmissionGate, CancellationHandle, ShedError)
from opentsdb_tpu.tsd.http import HttpRequest
from opentsdb_tpu.tsd.rpc_manager import RpcManager
from opentsdb_tpu.utils import faults
from opentsdb_tpu.utils.config import Config

BASE = 1_356_998_400


def counter_value(name: str, **labels) -> float:
    """Current value of one labeled registry counter cell (0 when the
    family or cell does not exist yet)."""
    key = tuple(sorted((k, str(v)) for k, v in labels.items()))
    for fam in REGISTRY.families():
        if fam.name == name:
            for label_key, cell in fam.children():
                if label_key == key:
                    return cell.get()
    return 0.0


class ManualClock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t


# --------------------------------------------------------------------- #
# Deadline                                                              #
# --------------------------------------------------------------------- #

class TestDeadline:
    def test_remaining_and_expiry(self):
        clock = ManualClock()
        d = Deadline(500, clock=clock)
        assert d.bounded and d.remaining_ms() == 500
        clock.t += 0.3
        assert d.remaining_ms() == pytest.approx(200)
        assert not d.expired()
        d.check()                              # still alive
        clock.t += 0.3
        assert d.expired()
        with pytest.raises(QueryException) as ei:
            d.check()
        assert ei.value.status == 413
        assert not isinstance(ei.value, QueryCancelledException)

    def test_unbounded(self):
        d = Deadline(0)
        assert not d.bounded
        assert d.remaining_ms() == float("inf")
        assert not d.expired()
        d.check()

    def test_cancel_idempotent_first_reason_wins(self):
        d = Deadline(0)
        assert d.cancel("client disconnected")
        assert not d.cancel("drain")           # second flip: no-op
        assert d.is_cancelled()
        assert d.cancel_reason == "client disconnected"
        with pytest.raises(QueryCancelledException) as ei:
            d.check()
        assert ei.value.status == 503
        assert "client disconnected" in str(ei.value)

    def test_cancelled_beats_expired(self):
        """A cancelled deadline reports 503 (server gave up) even once
        also past its wall budget — disconnect must not read as 413."""
        clock = ManualClock()
        d = Deadline(100, clock=clock)
        d.cancel("client disconnected")
        clock.t += 10
        with pytest.raises(QueryCancelledException):
            d.check()

    def test_wait_cancelled_already_cancelled_returns_at_once(self):
        d = Deadline(0)
        d.cancel("client disconnected")
        start = time.monotonic()
        assert d.wait_cancelled(10.0) is True
        assert time.monotonic() - start < 1.0

    def test_wait_cancelled_serves_the_timeout_when_nothing_happens(self):
        d = Deadline(0)                        # unbounded, never flipped
        start = time.monotonic()
        assert d.wait_cancelled(0.02) is False
        assert time.monotonic() - start >= 0.015

    def test_wait_cancelled_clamps_to_the_remaining_budget(self):
        """Parking for 10s on a deadline with 30ms left must return
        within the remainder, not the requested timeout."""
        d = Deadline(30)
        start = time.monotonic()
        assert d.wait_cancelled(10.0) is False
        assert time.monotonic() - start < 5.0

    def test_wait_cancelled_wakes_on_cancel_from_another_thread(self):
        """The cancellation-token contract the retry backoff and the
        cluster probe loop build on: cancel() from the responder thread
        releases a parked waiter within one tick, not after its full
        timeout."""
        d = Deadline(0)
        t = threading.Timer(0.05, lambda: d.cancel("client disconnected"))
        t.start()
        start = time.monotonic()
        assert d.wait_cancelled(10.0) is True
        assert time.monotonic() - start < 5.0
        t.join()


class TestAmbientDeadline:
    def test_activate_deactivate(self):
        assert limits.active_deadline() is None
        d = Deadline(100)
        limits.activate_deadline(d)
        try:
            assert limits.active_deadline() is d
        finally:
            limits.deactivate_deadline()
        assert limits.active_deadline() is None

    def test_per_thread_isolation(self):
        d = Deadline(100)
        limits.activate_deadline(d)
        seen = {}

        def other():
            seen["deadline"] = limits.active_deadline()

        try:
            t = threading.Thread(target=other)
            t.start()
            t.join(5)
        finally:
            limits.deactivate_deadline()
        assert seen["deadline"] is None


class TestQueryBudgetDerivation:
    def test_budget_shares_request_clock(self):
        """A QueryBudget derived from the request deadline must expire
        on the REQUEST's clock — not restart tsd.query.timeout at
        planner time (the pre-PR behavior this test pins out)."""
        clock = ManualClock()
        d = Deadline(1000, clock=clock)
        clock.t += 0.9                          # 900ms burnt pre-planner
        # timeout_ms=0: the budget's own wall check reads the REAL
        # monotonic clock — only the derived deadline (manual clock)
        # may expire this budget
        budget = QueryBudget(None, "m", 0, deadline=d)
        budget.check_deadline()                 # 100ms left: alive
        clock.t += 0.2
        with pytest.raises(QueryException):
            budget.check_deadline()

    def test_budget_observes_cancellation(self):
        d = Deadline(0)
        budget = QueryBudget(None, "m", 0, deadline=d)
        budget.check_deadline()
        d.cancel("client disconnected")
        with pytest.raises(QueryCancelledException):
            budget.check_deadline()

    def test_budget_without_deadline_unchanged(self):
        budget = QueryBudget(None, "m", 60_000)
        budget.check_deadline()                 # fresh clock, no raise


# --------------------------------------------------------------------- #
# CancellationHandle                                                    #
# --------------------------------------------------------------------- #

class TestCancellationHandle:
    def test_cancel_after_bind_flips(self):
        h = CancellationHandle()
        d = Deadline(0)
        h.bind(d)
        assert h.cancel("client disconnected")
        assert d.is_cancelled() and h.is_cancelled()

    def test_cancel_before_bind_replays(self):
        """The responder loop may detect the disconnect before
        rpc_manager minted the deadline: the flip must not be lost."""
        h = CancellationHandle()
        assert h.cancel("client disconnected")
        assert h.is_cancelled()
        d = Deadline(0)
        h.bind(d)
        assert d.is_cancelled()
        assert d.cancel_reason == "client disconnected"

    def test_second_cancel_is_noop(self):
        h = CancellationHandle()
        assert h.cancel("a")
        assert not h.cancel("b")
        d = Deadline(0)
        h.bind(d)
        assert d.cancel_reason == "a"


# --------------------------------------------------------------------- #
# AdmissionGate                                                         #
# --------------------------------------------------------------------- #

def _gate(**over) -> AdmissionGate:
    props = {"tsd.query.admission.enable": "true",
             "tsd.query.admission.permits": "2",
             "tsd.query.admission.queue_limit": "4",
             "tsd.query.admission.max_wait_ms": "5000"}
    props.update({k: str(v) for k, v in over.items()})
    return AdmissionGate(Config(props))


class TestAdmissionGate:
    def test_disabled_gate_is_noop(self):
        gate = _gate(**{"tsd.query.admission.enable": "false"})
        with gate.acquire(None, "interactive"):
            assert gate.in_flight == 0

    def test_permits_bound_concurrency(self):
        gate = _gate()
        a = gate.acquire(None, "interactive")
        b = gate.acquire(None, "interactive")
        assert gate.in_flight == 2
        admitted = threading.Event()

        def third():
            with gate.acquire(None, "interactive"):
                admitted.set()

        t = threading.Thread(target=third)
        t.start()
        assert not admitted.wait(0.3)           # queued behind the bound
        a.release()
        assert admitted.wait(5)
        t.join(5)
        b.release()
        assert gate.in_flight == 0

    def test_release_is_idempotent(self):
        gate = _gate()
        permit = gate.acquire(None, "interactive")
        permit.release()
        permit.release()
        assert gate.in_flight == 0

    def test_queue_full_sheds_503_with_retry_after(self):
        gate = _gate(**{"tsd.query.admission.permits": "1",
                        "tsd.query.admission.queue_limit": "0"})
        before = counter_value("tsd.query.admission.shed",
                               reason="queue_full")
        with gate.acquire(None, "interactive"):
            with pytest.raises(ShedError) as ei:
                gate.acquire(None, "interactive")
        assert ei.value.status == 503
        assert ei.value.retry_after_s >= 1
        assert counter_value("tsd.query.admission.shed",
                             reason="queue_full") == before + 1

    def test_max_wait_sheds(self):
        gate = _gate(**{"tsd.query.admission.permits": "1",
                        "tsd.query.admission.max_wait_ms": "120"})
        before = counter_value("tsd.query.admission.shed",
                               reason="max_wait")
        t0 = time.monotonic()
        with gate.acquire(None, "interactive"):
            with pytest.raises(ShedError):
                gate.acquire(None, "interactive")
        assert time.monotonic() - t0 < 5.0
        assert counter_value("tsd.query.admission.shed",
                             reason="max_wait") == before + 1

    def test_cancel_while_queued_releases_without_permit(self):
        gate = _gate(**{"tsd.query.admission.permits": "1"})
        d = Deadline(0)
        outcome = {}

        def queued():
            try:
                gate.acquire(d, "interactive")
            except QueryException as e:
                outcome["exc"] = e

        with gate.acquire(None, "interactive"):
            admitted_before = gate.admitted
            t = threading.Thread(target=queued)
            t.start()
            deadline = time.time() + 5
            while time.time() < deadline and not gate._depth_locked():
                time.sleep(0.01)
            assert gate._depth_locked() == 1
            d.cancel("client disconnected")
            t.join(5)
        assert isinstance(outcome["exc"], QueryCancelledException)
        assert gate.admitted == admitted_before  # never dispatched
        assert gate._depth_locked() == 0         # left the queue
        assert gate.in_flight == 0

    def test_expired_deadline_while_queued(self):
        gate = _gate(**{"tsd.query.admission.permits": "1"})
        clock = ManualClock()
        d = Deadline(100, clock=clock)
        clock.t += 0.2                           # already past budget
        with gate.acquire(None, "interactive"):
            with pytest.raises(QueryException) as ei:
                gate.acquire(d, "interactive")
        assert ei.value.status == 413
        assert gate.in_flight == 0

    def test_interactive_drains_before_batch(self):
        gate = _gate(**{"tsd.query.admission.permits": "1"})
        order = []
        queued = []

        def waiter(cls):
            with gate.acquire(None, cls):
                order.append(cls)

        holder = gate.acquire(None, "interactive")
        for cls in ("batch", "interactive"):     # batch queues FIRST
            t = threading.Thread(target=waiter, args=(cls,))
            t.start()
            queued.append(t)
            deadline = time.time() + 5
            while time.time() < deadline \
                    and gate._depth_locked() < len(queued):
                time.sleep(0.01)
            assert gate._depth_locked() == len(queued)
        holder.release()
        for t in queued:
            t.join(5)
        assert order == ["interactive", "batch"]

    def test_unknown_priority_lands_interactive(self):
        gate = _gate()
        with gate.acquire(None, "nonsense"):
            assert gate.in_flight == 1


# --------------------------------------------------------------------- #
# Degradation ladder                                                    #
# --------------------------------------------------------------------- #

def _ts_query(m: str, span_s: int = 600) -> TSQuery:
    q = TSQuery(start=str(BASE), end=str(BASE + span_s),
                queries=[parse_m_subquery(m)])
    q.validate()
    return q


class TestDegradationLadder:
    def test_coarsens_downsample_first(self, monkeypatch):
        q = _ts_query("sum:10s-avg:adm.m")
        original_ms = q.queries[0].downsample_spec.interval_ms
        # fake cost: inversely proportional to the interval — fits once
        # coarsened x4
        monkeypatch.setattr(
            admission, "estimate_plan_cost_ms",
            lambda tsdb, tq: 4000.0 * original_ms
            / tq.queries[0].downsample_spec.interval_ms)
        note = admission.try_degrade(None, q, budget_ms=1000.0,
                                     queue_wait_ms=0.0)
        assert note == {"coarsenedIntervalFactor": 4,
                        "coarsenedIntervalMs": original_ms * 4}
        assert q.queries[0].downsample_spec.interval_ms == original_ms * 4
        # the string form (stats, duplicate detection, a re-validate)
        # stays in lockstep with the mutated spec
        assert q.queries[0].downsample == "%dms-avg" % (original_ms * 4)
        q.validate()                     # re-parse must NOT revert
        assert q.queries[0].downsample_spec.interval_ms == original_ms * 4

    def test_truncates_range_when_not_coarsenable(self, monkeypatch):
        q = _ts_query("sum:adm.m")               # no downsample to coarsen
        span = q.end_time - q.start_time
        monkeypatch.setattr(
            admission, "estimate_plan_cost_ms",
            lambda tsdb, tq: (tq.end_time - tq.start_time) / span * 2000.0)
        note = admission.try_degrade(None, q, budget_ms=1000.0,
                                     queue_wait_ms=0.0)
        assert note["truncatedKeepFraction"] == 0.5
        assert q.end_time - q.start_time == span // 2
        # the string form travels to fan-out peers: kept in lockstep
        assert q.start == str(q.start_time)

    def test_returns_none_when_nothing_fits(self, monkeypatch):
        q = _ts_query("sum:adm.m")
        monkeypatch.setattr(admission, "estimate_plan_cost_ms",
                            lambda tsdb, tq: 1e12)
        assert admission.try_degrade(None, q, budget_ms=1000.0,
                                     queue_wait_ms=0.0) is None


# --------------------------------------------------------------------- #
# End-to-end through RpcManager.handle_http                             #
# --------------------------------------------------------------------- #

def _manager(**cfg):
    # mesh pinned off: this environment's jax has no shard_map (the
    # known tier-1 mesh failure set) and grouped plans probe the mesh
    props = {"tsd.core.auto_create_metrics": True,
             "tsd.query.mesh.enable": "false"}
    props.update({k: str(v) for k, v in cfg.items()})
    tsdb = TSDB(Config(props))
    for k in range(20):
        tsdb.add_point("adm.m", BASE + k * 15, float(k), {"host": "a"})
    return tsdb, RpcManager(tsdb)


def ask(mgr, uri, headers=None):
    q = mgr.handle_http(HttpRequest(method="GET", uri=uri,
                                    headers=headers or {}))
    body = q.response.body
    text = body.decode() if isinstance(body, (bytes, bytearray)) else body
    return q.response.status, json.loads(text), q.response.headers


QUERY_URI = "/api/query?start=%d&end=%d&m=sum:adm.m" % (BASE, BASE + 600)


class TestEndToEndAdmission:
    def test_full_queue_sheds_503_with_retry_after(self):
        tsdb, mgr = _manager(**{"tsd.query.admission.permits": "0",
                                "tsd.query.admission.queue_limit": "0"})
        status, payload, headers = ask(mgr, QUERY_URI)
        assert status == 503
        assert "Retry-After" in headers
        assert int(headers["Retry-After"]) >= 1
        assert "full" in payload["error"]["message"]

    def test_predicted_cost_sheds_when_degrade_denied(self, monkeypatch):
        tsdb, mgr = _manager(**{"tsd.query.timeout": "5000"})
        monkeypatch.setattr(admission, "estimate_plan_cost_ms",
                            lambda *_: 1e9)
        before = counter_value("tsd.query.admission.shed",
                               reason="predicted_cost")
        status, payload, headers = ask(mgr, QUERY_URI)
        assert status == 503
        assert "Retry-After" in headers
        assert "predicted cost" in payload["error"]["message"]
        assert counter_value("tsd.query.admission.shed",
                             reason="predicted_cost") == before + 1

    def test_degrade_allow_answers_200_partial(self, monkeypatch):
        tsdb, mgr = _manager(**{"tsd.query.degrade": "allow"})
        # predicted cost collapses once the ladder coarsens x4
        monkeypatch.setattr(
            admission, "estimate_plan_cost_ms",
            lambda tsdb_, tq: (1e9 if tq.queries[0].downsample_spec
                               .interval_ms < 40_000 else 1.0))
        before = counter_value("tsd.query.admission.degraded",
                               reason="predicted_cost")
        uri = ("/api/query?start=%d&end=%d&m=sum:10s-avg:adm.m"
               % (BASE, BASE + 600))
        status, payload, _ = ask(mgr, uri,
                                 headers={"x-tsdb-deadline-ms": "5000"})
        assert status == 200
        trailer = next((e for e in payload
                        if isinstance(e, dict) and e.get("partialResults")),
                       None)
        assert trailer is not None
        assert trailer["degraded"]["coarsenedIntervalFactor"] == 4
        series = [e for e in payload if isinstance(e, dict)
                  and "metric" in e]
        assert series and series[0]["dps"]
        assert counter_value("tsd.query.admission.degraded",
                             reason="predicted_cost") == before + 1

    def test_admitted_query_unaffected(self):
        tsdb, mgr = _manager()
        status, payload, headers = ask(
            mgr, QUERY_URI, headers={"x-tsdb-deadline-ms": "60000"})
        assert status == 200
        assert "Retry-After" not in headers
        assert not any(isinstance(e, dict) and e.get("partialResults")
                       for e in payload)

    def test_mint_deadline_takes_min_of_config_and_header(self):
        tsdb, mgr = _manager(**{"tsd.query.timeout": "10000"})
        req = HttpRequest(method="GET", uri=QUERY_URI,
                          headers={"x-tsdb-deadline-ms": "500"})
        assert mgr._mint_deadline(req).timeout_ms == 500
        req = HttpRequest(method="GET", uri=QUERY_URI, headers={})
        assert mgr._mint_deadline(req).timeout_ms == 10000
        tsdb2, mgr2 = _manager()                 # tsd.query.timeout = 0
        req = HttpRequest(method="GET", uri=QUERY_URI,
                          headers={"x-tsdb-deadline-ms": "700"})
        assert mgr2._mint_deadline(req).timeout_ms == 700
        req = HttpRequest(method="GET", uri=QUERY_URI,
                          headers={"x-tsdb-deadline-ms": "garbage"})
        assert not mgr2._mint_deadline(req).bounded

    def test_fanout_subrequest_sheds_instead_of_degrading(self,
                                                          monkeypatch):
        """A peer's raw-extraction sub-request (X-TSDB-Cluster header)
        must never degrade — the coordinator merges raw points
        verbatim and would drop the annotation, so a peer-side
        truncation becomes an unmarked wrong answer.  It sheds; the
        coordinator's own partial_results machinery marks the loss."""
        tsdb, mgr = _manager(**{"tsd.query.degrade": "allow"})
        monkeypatch.setattr(admission, "estimate_plan_cost_ms",
                            lambda *_: 1e9)
        uri = ("/api/query?start=%d&end=%d&m=sum:10s-avg:adm.m"
               % (BASE, BASE + 600))
        status, payload, headers = ask(
            mgr, uri, headers={"x-tsdb-deadline-ms": "5000",
                               "x-tsdb-cluster": "fanout"})
        assert status == 503
        assert "Retry-After" in headers

    def test_mint_deadline_rejects_non_finite_header(self):
        """'inf'/'1e309' parse to float inf — a bounded-looking
        deadline with an infinite remainder would overflow the peer
        header int; it must mint as absent instead."""
        tsdb, mgr = _manager()
        for bad in ("inf", "Infinity", "1e309", "nan", "-inf"):
            req = HttpRequest(method="GET", uri=QUERY_URI,
                              headers={"x-tsdb-deadline-ms": bad})
            assert not mgr._mint_deadline(req).bounded, bad

    def test_graph_route_is_gated_too(self):
        """/q dispatches the same device work as /api/query — the gate
        sheds it identically."""
        tsdb, mgr = _manager(**{"tsd.query.admission.permits": "0",
                                "tsd.query.admission.queue_limit": "0"})
        status, payload, headers = ask(
            mgr, "/q?start=%d&end=%d&m=sum:adm.m&json" % (BASE, BASE + 600))
        assert status == 503
        assert "Retry-After" in headers

    def test_ambient_deadline_cleared_after_request(self):
        tsdb, mgr = _manager()
        ask(mgr, QUERY_URI, headers={"x-tsdb-deadline-ms": "60000"})
        assert limits.active_deadline() is None


# --------------------------------------------------------------------- #
# Deadline propagation to fan-out peers                                 #
# --------------------------------------------------------------------- #

class TestDeadlinePropagation:
    @pytest.fixture()
    def peer(self):
        from tests.fault_fixtures import FaultyPeer, series_payload
        p = FaultyPeer(series_payload(
            "adm.m", {"host": "remote"},
            {str((BASE + 5) * 1000): 11.0}))
        yield p
        p.close()

    def test_remainder_forwarded_and_slow_peer_aborted(self, peer):
        """The coordinator forwards its remaining ms via
        x-tsdb-deadline-ms and the clamped fetch timeout ends a
        slow-body peer WITHIN the remainder.  Without the propagation
        this test fails on elapsed time: the cluster fetch budget below
        is 30s and the peer needs > 30s to finish its dribble."""
        from tests import fault_fixtures as ff
        peer.mode = ff.SLOW_BODY
        peer.slow_body_step_s = 5.0
        tsdb, mgr = _manager(**{
            "tsd.network.cluster.peers": peer.address,
            "tsd.network.cluster.timeout_ms": "30000",
            "tsd.network.cluster.retry.max_attempts": "1",
        })
        t0 = time.monotonic()
        status, payload, _ = ask(mgr, QUERY_URI,
                                 headers={"x-tsdb-deadline-ms": "1200"})
        elapsed = time.monotonic() - t0
        assert status >= 500                     # error mode: fail fast
        assert elapsed < 8.0, elapsed            # aborted ~at the remainder
        assert peer.requests >= 1
        forwarded = peer.seen_headers[0].get("x-tsdb-deadline-ms")
        assert forwarded is not None
        assert 0 < int(forwarded) <= 1200

    def test_peer_receiving_header_aborts_its_own_work(self):
        """The receiving side of the propagation: a TSD handed an
        already-tiny x-tsdb-deadline-ms refuses/aborts instead of doing
        the work — its minted deadline is checked at admission."""
        tsdb, mgr = _manager()
        status, payload, _ = ask(mgr, QUERY_URI,
                                 headers={"x-tsdb-deadline-ms": "1"})
        assert status in (413, 503)

    def test_expired_coordinator_never_contacts_peer(self, peer):
        """A fan-out whose deadline is already spent must not even
        connect (tsd/cluster.py checks before the request goes out)."""
        tsdb, mgr = _manager(**{
            "tsd.network.cluster.peers": peer.address,
            "tsd.network.cluster.retry.max_attempts": "1",
        })
        d = Deadline(0.5)                        # all but expired
        time.sleep(0.01)
        limits.activate_deadline(d)
        try:
            from opentsdb_tpu.tsd.cluster import run_clustered
            q = _ts_query("sum:adm.m")
            with pytest.raises(QueryException):
                run_clustered(tsdb, q)
        finally:
            limits.deactivate_deadline()
        assert peer.requests == 0

    def test_cancelled_unbounded_deadline_stops_fanout(self, peer):
        """The default config mints an UNBOUNDED deadline
        (tsd.query.timeout=0) — it is still a cancellation token, and
        a flipped token must stop peer fetches before they connect."""
        tsdb, mgr = _manager(**{
            "tsd.network.cluster.peers": peer.address,
            "tsd.network.cluster.retry.max_attempts": "1",
        })
        d = Deadline(0)                          # unbounded
        d.cancel("client disconnected")
        limits.activate_deadline(d)
        try:
            from opentsdb_tpu.tsd.cluster import run_clustered
            q = _ts_query("sum:adm.m")
            with pytest.raises(QueryCancelledException):
                run_clustered(tsdb, q)
        finally:
            limits.deactivate_deadline()
        assert peer.requests == 0


# --------------------------------------------------------------------- #
# Live server: disconnect cancellation + bounded drain                  #
# --------------------------------------------------------------------- #

def _spawn_server(cfg: dict):
    props = {"tsd.core.auto_create_metrics": True}
    props.update(cfg)
    tsdb = TSDB(Config(props))
    for k in range(20):
        tsdb.add_point("adm.m", BASE + k * 15, float(k), {"host": "a"})
    from opentsdb_tpu.tsd.server import TSDServer
    srv = TSDServer(tsdb, port=0, bind="127.0.0.1", worker_threads=4)
    started = threading.Event()
    stopped = threading.Event()
    holder = {}

    def run():
        async def main():
            await srv.start()
            holder["port"] = srv._server.sockets[0].getsockname()[1]
            holder["loop"] = asyncio.get_running_loop()
            started.set()
            await srv.serve_forever()
            # set INSIDE the loop: asyncio.run's own teardown joins the
            # default executor, which a wedged-handler test would wait
            # on for the full wedge — stop() itself is what's bounded
            stopped.set()
        asyncio.run(main())
        stopped.set()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    assert started.wait(10)
    srv.test_port = holder["port"]
    return srv, holder, stopped


def _http_get(port, path, timeout=30):
    import http.client
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


class TestClientDisconnect:
    def test_disconnected_query_releases_without_dispatching(self):
        """Client B queues behind A's held permit, then hangs up: B's
        token flips, B leaves the queue WITHOUT being admitted, and
        only A dispatches."""
        faults.install([{"site": "rpc.slow_handler", "kind": "latency",
                         "ms": 2500, "times": 1}])
        srv, holder, stopped = _spawn_server({
            "tsd.query.admission.permits": "1",
            "tsd.query.admission.max_wait_ms": "30000",
        })
        gate = admission.gate_for(srv.tsdb)
        cancelled_before = counter_value("tsd.query.admission.cancelled",
                                         reason="client_disconnect")
        path = QUERY_URI
        a_result = {}

        def client_a():
            a_result["resp"] = _http_get(srv.test_port, path)

        try:
            ta = threading.Thread(target=client_a)
            ta.start()
            # wait until A holds the permit (inside its stall)
            deadline = time.time() + 5
            while time.time() < deadline and gate.in_flight < 1:
                time.sleep(0.01)
            assert gate.in_flight == 1
            # B: send the request, then hang up while queued
            sock = socket.create_connection(
                ("127.0.0.1", srv.test_port), timeout=10)
            sock.sendall(("GET %s HTTP/1.1\r\nHost: x\r\n\r\n"
                          % path).encode())
            deadline = time.time() + 5
            while time.time() < deadline and not gate._depth_locked():
                time.sleep(0.01)
            assert gate._depth_locked() == 1
            sock.close()                         # the hang-up
            deadline = time.time() + 5
            while time.time() < deadline and counter_value(
                    "tsd.query.admission.cancelled",
                    reason="client_disconnect") <= cancelled_before:
                time.sleep(0.02)
            assert counter_value(
                "tsd.query.admission.cancelled",
                reason="client_disconnect") > cancelled_before
            ta.join(15)
            assert a_result["resp"][0] == 200    # A unaffected
            # B never dispatched: one admission total (A's)
            assert gate.admitted == 1
            assert gate.in_flight == 0
        finally:
            faults.clear()
            holder["loop"].call_soon_threadsafe(srv._shutdown_event.set)
            stopped.wait(15)


class TestBoundedDrain:
    def test_stop_force_cancels_at_drain_timeout(self, monkeypatch):
        """One wedged responder thread must not block shutdown forever:
        at tsd.network.drain_timeout_ms every in-flight token flips
        (the cooperative queued query unwinds), and teardown proceeds
        after the short post-cancel grace even though the wedged
        handler never looks at its token."""
        from opentsdb_tpu.tsd import server as server_mod
        monkeypatch.setattr(server_mod, "POST_CANCEL_GRACE_S", 1.0)
        # A = deliberately stuck (non-cooperative sleep inside its
        # permit); B = cooperative, parked in the admission queue
        faults.install([{"site": "rpc.slow_handler", "kind": "latency",
                         "ms": 9000, "times": 1}])
        srv, holder, stopped = _spawn_server({
            "tsd.query.admission.permits": "1",
            "tsd.query.admission.max_wait_ms": "0",
            "tsd.network.drain_timeout_ms": "300",
        })
        gate = admission.gate_for(srv.tsdb)
        drain_before = counter_value("tsd.query.admission.cancelled",
                                     reason="drain_timeout")
        results = []

        def client(tag):
            try:
                results.append((tag, _http_get(srv.test_port, QUERY_URI)))
            except OSError:
                results.append((tag, None))

        try:
            ta = threading.Thread(target=client, args=("a",), daemon=True)
            ta.start()
            deadline = time.time() + 5
            while time.time() < deadline and gate.in_flight < 1:
                time.sleep(0.01)
            tb = threading.Thread(target=client, args=("b",), daemon=True)
            tb.start()
            deadline = time.time() + 5
            while time.time() < deadline and not gate._depth_locked():
                time.sleep(0.01)
            assert gate._depth_locked() == 1
            t0 = time.monotonic()
            holder["loop"].call_soon_threadsafe(srv._shutdown_event.set)
            assert stopped.wait(10), "stop() did not come back"
            stop_s = time.monotonic() - t0
            # bounded: 0.3s drain + 1s post-cancel grace + the <= 5s
            # reply-flush wait + teardown slack — well under the 9s
            # wedge (the old behavior: stop waits the whole wedge out)
            assert stop_s < 7.5, stop_s
            assert counter_value(
                "tsd.query.admission.cancelled",
                reason="drain_timeout") > drain_before
        finally:
            faults.clear()
