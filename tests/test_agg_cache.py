"""Partial-aggregate cache (storage/agg_cache.py, ISSUE 9).

The correctness gate is BIT-identity, not closeness: a cache hit
replays arrays a cold run computed with the very same per-block
compiled programs, so

  * cold == warm == invalidated-and-recomputed, bitwise, on random
    float data (the strongest transparency guarantee);
  * cache-enabled == cache-disabled, bitwise, on exactly-representable
    (integer) data — where the monolithic and block-decomposed
    summation orders are both exact;

plus eviction-under-budget, incremental ingest invalidation (an acked
write is never served stale), the degraded-query keying pins (ISSUE 9
small fix), concurrent ingest-vs-query races (TSDBSAN-armed when the
sanitized subset runs this file), and the lint pin that gutting the
ingest-side invalidator fails the tree.
"""

import os
import shutil
import threading

import numpy as np
import pytest

from opentsdb_tpu.core.tsdb import TSDB
from opentsdb_tpu.models import TSQuery, parse_m_subquery
from opentsdb_tpu.utils.config import Config

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASE = 1_356_998_400


def make_tsdb(**over):
    cfg = {
        "tsd.core.auto_create_metrics": True,
        "tsd.query.mesh.enable": False,
        "tsd.storage.fix_duplicates": True,
        "tsd.query.cache.block_windows": 8,
        "tsd.query.cache.min_repeats": 1,
        # CI-scale data sits at the dispatch floor where the honest
        # costmodel would (correctly) refuse to cache — zero the
        # per-dispatch charge so the decision reduces to the repeat
        # gate and the tests exercise the machinery
        "tsd.query.cache.dispatch_overhead_us": 0,
    }
    cfg.update(over)
    return TSDB(Config(cfg))


def feed_float(tsdb, n=6000, hosts=("a", "b"), seed=3):
    rng = np.random.default_rng(seed)
    for host in hosts:
        for i in range(n):
            tsdb.add_point("sys.f", BASE + i,
                           float(rng.standard_normal()), {"host": host})


def feed_int(tsdb, n=6000, hosts=("a", "b"), metric="sys.i"):
    for host in hosts:
        key = tsdb._series_key(metric, {"host": host}, create=True)
        ts = (np.arange(n, dtype=np.int64) + BASE) * 1000
        vals = (np.arange(n, dtype=np.int64) * 7) % 101
        tsdb.store.add_batch(key, ts, vals, True)


def run_q(tsdb, m, start=BASE, end=BASE + 6000):
    q = TSQuery(start=str(start), end=str(end),
                queries=[parse_m_subquery(m)])
    q.validate()
    runner = tsdb.new_query_runner()
    out = [r.to_json() for r in runner.run(q)]
    return out, dict(runner.exec_stats)


class TestBitIdentity:
    def test_cold_warm_and_recompute_bitwise_on_floats(self):
        tsdb = make_tsdb()
        feed_float(tsdb)
        m = "sum:60s-sum:sys.f{host=*}"
        cold, s_cold = run_q(tsdb, m)       # populates (min_repeats=1)
        warm, s_warm = run_q(tsdb, m)
        warm2, s_warm2 = run_q(tsdb, m)
        assert s_cold.get("aggCacheComputedWindows", 0) > 0
        assert s_warm.get("aggCacheHitWindows", 0) > 0
        assert cold == warm == warm2        # float dps, bit-for-bit
        # drop everything and recompute from the store: the fresh
        # per-block programs must reproduce the cached bits exactly
        tsdb.agg_cache.invalidate()
        recomputed, s_re = run_q(tsdb, m)
        assert s_re.get("aggCacheComputedWindows", 0) > 0
        assert recomputed == cold

    @pytest.mark.parametrize("m", [
        "sum:60s-sum:sys.i{host=*}",
        "sum:60s-count:sys.i",
        "max:60s-max:sys.i{host=*}",
        "min:60s-min:sys.i",
        "sum:60s-last:sys.i{host=*}",
        "sum:rate:60s-sum:sys.i{host=*}",
    ])
    def test_enabled_equals_disabled_bitwise_on_ints(self, m):
        on, off = make_tsdb(), make_tsdb(**{
            "tsd.query.cache.enable": False})
        feed_int(on)
        feed_int(off)
        run_q(on, m)                         # populate
        warm, s = run_q(on, m)
        plain, _ = run_q(off, m)
        assert s.get("aggCacheHitWindows", 0) > 0
        assert warm == plain                 # integer sums: both exact

    def test_unaligned_and_sliding_ranges(self):
        """Partial edge windows recompute per query; interior blocks
        reuse across overlapping (sliding) ranges — and every answer
        matches a cache-disabled control on integer data."""
        on, off = make_tsdb(), make_tsdb(**{
            "tsd.query.cache.enable": False})
        feed_int(on)
        feed_int(off)
        m = "sum:60s-sum:sys.i{host=*}"
        windows = [(BASE + 7, BASE + 5003),       # unaligned both ends
                   (BASE + 607, BASE + 5603),     # slid by 10 windows
                   (BASE + 1207, BASE + 5999)]
        run_q(on, m, *windows[0])                 # populate family
        for start, end in windows:
            got, stats = run_q(on, m, start, end)
            want, _ = run_q(off, m, start, end)
            assert got == want, (start, end)
        assert stats.get("aggCacheHitWindows", 0) > 0


class TestInvalidation:
    def test_acked_write_never_served_stale(self):
        on, off = make_tsdb(), make_tsdb(**{
            "tsd.query.cache.enable": False})
        feed_int(on)
        feed_int(off)
        m = "sum:60s-sum:sys.i{host=*}"
        for _ in range(3):
            run_q(on, m)                     # fully warm
        # land a write in the MIDDLE of the cached range on both
        for t in (on, off):
            t.add_point("sys.i", BASE + 3000, 424242, {"host": "a"})
        got, stats = run_q(on, m)
        want, _ = run_q(off, m)
        assert got == want
        # only the dirtied block recomputed — history still serves
        assert stats.get("aggCacheHitWindows", 0) > 0
        assert stats.get("aggCacheComputedWindows", 0) > 0

    def test_delete_and_new_series_invalidate(self):
        on, off = make_tsdb(), make_tsdb(**{
            "tsd.query.cache.enable": False})
        feed_int(on)
        feed_int(off)
        m = "sum:60s-sum:sys.i{host=*}"
        for _ in range(2):
            run_q(on, m)
        # a series born after the blocks were built must join the
        # answer (the block entries lack its row -> recompute)
        for t in (on, off):
            for i in range(0, 6000, 10):
                t.add_point("sys.i", BASE + i, 5, {"host": "c"})
        got, _ = run_q(on, m)
        want, _ = run_q(off, m)
        assert got == want
        # delete the series again: answers must drop it immediately
        for t in (on, off):
            key = t._series_key("sys.i", {"host": "c"}, create=False)
            t.store.delete_series(key)
        got, _ = run_q(on, m)
        want, _ = run_q(off, m)
        assert got == want

    def test_mark_ring_overflow_invalidates_conservatively(self):
        """When the per-(store, metric) mark ring overflows, the floor
        generation rises and entries older than the evicted marks are
        unconditionally invalid — the bound can hide history, never
        serve stale."""
        from opentsdb_tpu.storage.agg_cache import (AggregateCache,
                                                    _Block, _MARK_RING)
        cache = AggregateCache(Config({}))
        store = object()
        entry = _Block(store=store, metric=1, rows={}, val=np.zeros(
            (1, 8)), mask=np.zeros((1, 8), bool), gen=0,
            lo_ms=0, hi_ms=7999)
        with cache._lock:
            assert cache._valid_locked(entry)
        for i in range(_MARK_RING + 50):
            # distinct non-overlapping ranges far from the entry; a
            # plan snapshot between marks defeats coalescing
            with cache._lock:
                cache._planned_gen = cache._gen
            cache.invalidate(store=store, metric=1,
                             lo_ms=10_000_000 + i * 10,
                             hi_ms=10_000_000 + i * 10 + 5)
        with cache._lock:
            assert not cache._valid_locked(entry)

    def test_gutting_the_agg_invalidator_fails_lint(self, tmp_path):
        """ISSUE 9 acceptance: the ingest-side invalidation is a
        checked contract — deleting the backing-store drop inside
        `AggregateCache.invalidate` must re-fire the cache-coherence
        analyzer (cache-invalidator-gutted)."""
        import sys
        sys.path.insert(0, REPO)
        from tools.lint import cache_coherence
        from tools.lint.core import LintContext
        from tools.lint.run import run_lint
        dst = tmp_path / "opentsdb_tpu"
        shutil.copytree(os.path.join(REPO, "opentsdb_tpu"), dst)
        mod = dst / "storage" / "agg_cache.py"
        src = mod.read_text()
        needle = ("            if metric is None:\n"
                  "                self.invalidations += 1\n"
                  "                self._blocks = {}\n")
        assert needle in src, "expected the full-drop inside invalidate"
        mod.write_text(src.replace(
            needle, "            if metric is None:\n"
                    "                self.invalidations += 1\n"))
        ctx = LintContext(str(tmp_path))
        findings = run_lint(["opentsdb_tpu"], root=str(tmp_path),
                            analyzers=[cache_coherence.ANALYZER],
                            ctx=ctx)
        assert any(f.rule == "cache-invalidator-gutted"
                   and "agg-blocks" in f.message for f in findings), (
            "gutting the agg-cache invalidator went undetected:\n"
            + "\n".join(f.render() for f in findings))


class TestPolicy:
    def test_min_repeats_gates_materialization(self):
        tsdb = make_tsdb(**{"tsd.query.cache.min_repeats": 3})
        feed_int(tsdb)
        m = "sum:60s-sum:sys.i{host=*}"
        run_q(tsdb, m)
        run_q(tsdb, m)
        assert tsdb.agg_cache.collect_stats()[
            "tsd.query.agg_cache.populated"] == 0
        run_q(tsdb, m)                       # third occurrence: populate
        assert tsdb.agg_cache.collect_stats()[
            "tsd.query.agg_cache.populated"] > 0

    def test_dispatch_floor_plans_honestly_refuse(self):
        """With the real per-dispatch overhead charged, a tiny plan's
        per-hit saving goes non-positive and the costmodel refuses to
        materialize — the cache must not tax workloads it cannot
        help."""
        tsdb = make_tsdb(**{
            "tsd.query.cache.dispatch_overhead_us": 100000})
        feed_int(tsdb, n=600)
        m = "sum:60s-sum:sys.i{host=*}"
        for _ in range(3):
            _, stats = run_q(tsdb, m, BASE, BASE + 600)
        assert "aggCacheHitWindows" not in stats
        assert tsdb.agg_cache.collect_stats()[
            "tsd.query.agg_cache.populated"] == 0

    def test_eviction_under_byte_budget(self):
        tsdb = make_tsdb(**{"tsd.query.cache.mb": 1})
        # 64-series x 8-window blocks are ~4.6KB each; 24 metrics x 12
        # full blocks ~= 1.3MB, past the 1MB budget
        for g in range(24):
            metric = "evict.m%d" % g
            for host in range(64):
                key = tsdb._series_key(metric, {"h": str(host)},
                                       create=True)
                ts = (np.arange(2000, dtype=np.int64) + BASE) * 1000
                tsdb.store.add_batch(key, ts,
                                     np.arange(2000, dtype=np.int64),
                                     True)
            run_q(tsdb, "sum:20s-sum:%s{h=*}" % metric,
                  BASE, BASE + 2000)
        stats = tsdb.agg_cache.collect_stats()
        assert stats["tsd.query.agg_cache.bytes"] <= 2 ** 20
        assert stats["tsd.query.agg_cache.evictions"] > 0
        # evicted families still answer correctly (recompute)
        off = make_tsdb(**{"tsd.query.cache.enable": False})
        for host in range(64):
            key = off._series_key("evict.m0", {"h": str(host)},
                                  create=True)
            ts = (np.arange(2000, dtype=np.int64) + BASE) * 1000
            off.store.add_batch(key, ts,
                                np.arange(2000, dtype=np.int64), True)
        got, _ = run_q(tsdb, "sum:20s-sum:evict.m0{h=*}",
                       BASE, BASE + 2000)
        want, _ = run_q(off, "sum:20s-sum:evict.m0{h=*}",
                        BASE, BASE + 2000)
        assert got == want

    def test_device_tier_promotes_hot_blocks(self):
        tsdb = make_tsdb(**{"tsd.query.cache.promote_hits": 2})
        feed_int(tsdb)
        m = "sum:60s-sum:sys.i{host=*}"
        results = [run_q(tsdb, m)[0] for _ in range(3)]
        # served-enough blocks queue for the maintenance thread; the
        # upload is never paid on the query path (stand in for the
        # maintenance tick here)
        assert tsdb.agg_cache.promote_pending(max_uploads=64) > 0
        stats = tsdb.agg_cache.collect_stats()
        assert stats["tsd.query.agg_cache.device_bytes"] > 0
        # device-tier replays are still bit-identical
        got, s = run_q(tsdb, m)
        assert got == results[1] == results[2]
        assert s.get("aggCacheHitWindows", 0) > 0

    def test_consulted_but_recomputed_plans_never_promote(self):
        """Review pin: a plan that consults the cache but ends in
        recompute must not accrue serve-hits — never-serving blocks
        must not earn device mirrors."""
        tsdb = make_tsdb(**{"tsd.query.cache.promote_hits": 1})
        feed_int(tsdb)
        m = "sum:60s-sum:sys.i{host=*}"
        run_q(tsdb, m)                       # populate (serves: cold)
        # force every later plan to refuse via an absurd overhead
        tsdb.agg_cache.dispatch_overhead_s = 10.0
        for t in (tsdb,):
            t.add_point("sys.i", BASE + 3000, 1, {"host": "a"})
        for _ in range(3):
            _, s = run_q(tsdb, m)
        assert "aggCacheHitWindows" not in s   # plans recomputed
        assert tsdb.agg_cache.promote_pending(max_uploads=64) == 0

    def test_mode_policy_epoch_keys_blocks(self):
        """An autotune/kernel-mode flip bumps the mode-policy epoch;
        cached blocks from the old epoch must never splice into
        new-epoch answers (the block key carries the epoch)."""
        from opentsdb_tpu.ops import downsample as ds
        tsdb = make_tsdb()
        feed_int(tsdb)
        m = "sum:60s-sum:sys.i{host=*}"
        run_q(tsdb, m)
        _, s_warm = run_q(tsdb, m)
        assert s_warm.get("aggCacheHitWindows", 0) > 0
        prev = ds._SCAN_MODE
        try:
            ds.set_scan_mode("subblock" if prev != "subblock"
                             else "flat")
            _, s_flip = run_q(tsdb, m)
            assert "aggCacheHitWindows" not in s_flip  # old epoch dead
            got, s_warm2 = run_q(tsdb, m)
            assert s_warm2.get("aggCacheHitWindows", 0) > 0
            off = make_tsdb(**{"tsd.query.cache.enable": False})
            feed_int(off)
            want, _ = run_q(off, m)
            assert got == want
        finally:
            ds.set_scan_mode(prev)

    def test_admission_estimate_prices_the_rewritten_plan(self):
        """ISSUE 9: estimate_plan_cost_ms must price the rewritten
        plan — a warm cache shrinks the predicted cost."""
        from opentsdb_tpu.tsd.admission import estimate_plan_cost_ms
        tsdb = make_tsdb()
        feed_int(tsdb)

        def parsed():
            q = TSQuery(start=str(BASE), end=str(BASE + 6000),
                        queries=[parse_m_subquery(
                            "sum:60s-sum:sys.i{host=*}")])
            q.validate()
            return q
        cold = estimate_plan_cost_ms(tsdb, parsed())
        run_q(tsdb, "sum:60s-sum:sys.i{host=*}")
        run_q(tsdb, "sum:60s-sum:sys.i{host=*}")
        warm = estimate_plan_cost_ms(tsdb, parsed())
        assert cold > 0
        assert warm < cold


class TestDegradedQueries:
    """ISSUE 9 small fix: the degradation ladder (PR 8) mutates the
    downsample spec in place — the cache must key on the MUTATED spec,
    and a truncated degraded run must never pollute the full-range
    answer."""

    def _query(self, start=BASE, end=BASE + 6000):
        q = TSQuery(start=str(start), end=str(end),
                    queries=[parse_m_subquery(
                        "sum:60s-sum:sys.i{host=*}")])
        q.validate()
        return q

    def test_coarsened_spec_is_its_own_family(self):
        on, off = make_tsdb(), make_tsdb(**{
            "tsd.query.cache.enable": False})
        feed_int(on)
        feed_int(off)
        # the ladder's rung-1 mutation: interval x2, string in lockstep
        for _ in range(3):
            q = self._query()
            sub = q.queries[0]
            sub.downsample_spec.interval_ms *= 2
            sub.downsample = "120000ms-sum"
            out = [r.to_json() for r in on.new_query_runner().run(q)]
        # coarsened blocks are under the 120s family; the 60s query
        # must not hit them — and must answer exactly
        got, stats = run_q(on, "sum:60s-sum:sys.i{host=*}")
        want, _ = run_q(off, "sum:60s-sum:sys.i{host=*}")
        assert got == want
        assert "aggCacheHitWindows" not in stats    # first 60s sight
        # and the coarsened family answers exactly too
        qq = self._query()
        qq.queries[0].downsample_spec.interval_ms *= 2
        qq.queries[0].downsample = "120000ms-sum"
        got2 = [r.to_json() for r in on.new_query_runner().run(qq)]
        assert got2 == out

    def test_truncated_run_never_pollutes_the_full_range(self):
        on, off = make_tsdb(), make_tsdb(**{
            "tsd.query.cache.enable": False})
        feed_int(on)
        feed_int(off)
        # the ladder's rung-2 mutation: range truncated toward now
        for _ in range(3):
            q = self._query(start=BASE + 3000)
            [r.to_json() for r in on.new_query_runner().run(q)]
        got, _ = run_q(on, "sum:60s-sum:sys.i{host=*}")
        want, _ = run_q(off, "sum:60s-sum:sys.i{host=*}")
        assert got == want      # full range: no truncated leftovers


class TestConcurrency:
    def test_ingest_vs_cached_query_race(self):
        """Concurrent writers against warm cached queries (TSDBSAN
        verifies the lock discipline when the sanitized subset runs
        this file): after the dust settles, the final answer must
        equal a cache-disabled control ingested identically — no
        stale window survives an acked append."""
        on, off = make_tsdb(), make_tsdb(**{
            "tsd.query.cache.enable": False})
        feed_int(on, n=4000)
        feed_int(off, n=4000)
        m = "sum:60s-sum:sys.i{host=*}"
        for _ in range(2):
            run_q(on, m, BASE, BASE + 4000)
        errors = []
        stop = threading.Event()

        def ingest(host):
            try:
                i = 0
                while not stop.is_set() and i < 400:
                    for t in (on, off):
                        t.add_point("sys.i", BASE + (i * 13) % 4000,
                                    i, {"host": host})
                    i += 1
            except Exception as e:  # pragma: no cover - fail the test
                errors.append(e)

        def reader():
            try:
                for _ in range(30):
                    run_q(on, m, BASE, BASE + 4000)
            except Exception as e:  # pragma: no cover - fail the test
                errors.append(e)

        threads = [threading.Thread(target=ingest, args=("a",)),
                   threading.Thread(target=ingest, args=("b",)),
                   threading.Thread(target=reader),
                   threading.Thread(target=reader)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        stop.set()
        assert not errors, errors
        got, _ = run_q(on, m, BASE, BASE + 4000)
        want, _ = run_q(off, m, BASE, BASE + 4000)
        assert got == want


class TestMetrics:
    def test_tier_labeled_families_scrapeable(self):
        """ISSUE 9 satellite: DeviceSeriesCache and the agg cache
        share the tsd.query.cache.* families, tier-labeled, on the
        prometheus registry."""
        from opentsdb_tpu.obs.registry import REGISTRY
        tsdb = make_tsdb()
        feed_int(tsdb)
        m = "sum:60s-sum:sys.i{host=*}"
        for _ in range(3):
            run_q(tsdb, m)
        text = REGISTRY.prometheus_text()
        assert 'tsd_query_cache_hits_total{tier="agg_host"' in text
        assert 'tier="device_series"' in text
        assert 'tsd_query_cache_bytes{tier="agg_host"' in text
        # the stats walk carries the agg-cache records too
        stats = tsdb.collect_stats()
        assert stats["tsd.query.agg_cache.rewrites"] > 0


@pytest.mark.slow
def test_cache_hit_speedup_at_scale():
    """ISSUE 9 acceptance: >= 5x wall reduction on cache-hit queries
    vs cold at a compute-dominated shape — the aligned dashboard
    repeat (full block coverage), the same measurement the committed
    BENCH_AGG_CACHE.json artifact records via
    tools/bench_agg_cache.py (which also reports trace-span device
    ms)."""
    import statistics
    import time
    tsdb = make_tsdb(**{"tsd.query.cache.min_repeats": 1,
                        "tsd.query.cache.block_windows": 32})
    rng = np.random.default_rng(5)
    t0_s = 84813 * 16000        # aligned to the 32x500s block grid
    points = 400_000
    for host in range(8):
        key = tsdb._series_key("bench.m", {"h": str(host)}, create=True)
        ts = (np.arange(points, dtype=np.int64) + t0_s) * 1000
        tsdb.store.add_batch(key, ts, rng.standard_normal(points),
                             False)
    m = "sum:500s-sum:bench.m{h=*}"
    end = t0_s + (points // 16000) * 16000
    run_q(tsdb, m, t0_s, end)          # jit warmup (not what we time)

    def timed():
        t0 = time.perf_counter()
        out, _ = run_q(tsdb, m, t0_s, end)
        return time.perf_counter() - t0, out

    colds, warms = [], []
    for _ in range(3):
        tsdb.agg_cache.invalidate()
        colds.append(timed())          # repopulates
        warms.append(timed())
        warms.append(timed())
    cold_s = statistics.median(c[0] for c in colds)
    warm_s = statistics.median(w[0] for w in warms)
    assert all(w[1] == colds[0][1] for w in warms)   # bit-identical
    assert cold_s / warm_s >= 5.0, (cold_s, warm_s)
