"""Auth + plugin infrastructure tests.

Models /root/reference/test/auth/TestAllowAllAuthenticatingAuthorizer,
test/plugin/ dummy-plugin SPI exercises, and TestUniqueIdWhitelistFilter."""

import json

import pytest

from opentsdb_tpu.auth import (
    AllowAllAuthenticatingAuthorizer, AuthState, AuthStatus, Authentication,
    Permissions, Roles)
from opentsdb_tpu.core import TSDB
from opentsdb_tpu.plugins import (
    RTPublisher, StorageExceptionHandler, WriteableDataPointFilterPlugin,
    load_plugin)
from opentsdb_tpu.tsd.http import HttpRequest
from opentsdb_tpu.tsd.rpc_manager import RpcManager
from opentsdb_tpu.uid.whitelist import UniqueIdWhitelistFilter
from opentsdb_tpu.uid import FailedToAssignUniqueIdException
from opentsdb_tpu.utils.config import Config
from tests.plugin_fixtures import (
    RecordingPublisher, RecordingSEH, EvenOnlyFilter, DenyAuth)

BASE = 1_356_998_400


class TestRolesPermissions:
    def test_roles(self):
        r = Roles({Permissions.HTTP_PUT})
        assert r.has_permission(Permissions.HTTP_PUT)
        assert not r.has_permission(Permissions.HTTP_QUERY)
        r.grant(Permissions.HTTP_QUERY)
        assert r.has_permission(Permissions.HTTP_QUERY)
        r.revoke(Permissions.HTTP_QUERY)
        assert not r.has_permission(Permissions.HTTP_QUERY)

    def test_allow_all(self):
        auth = AllowAllAuthenticatingAuthorizer()
        state = auth.authenticate_telnet(None, ["anything"])
        assert state.status == AuthStatus.SUCCESS
        assert state.roles.has_permission(Permissions.TELNET_PUT)
        assert auth.authorization() is auth


class TestPluginLoader:
    def test_load_by_colon_path(self):
        p = load_plugin("tests.plugin_fixtures:RecordingPublisher",
                        RTPublisher)
        assert isinstance(p, RecordingPublisher)

    def test_load_by_dotted_path(self):
        p = load_plugin("tests.plugin_fixtures.RecordingSEH")
        assert isinstance(p, RecordingSEH)

    def test_wrong_type_rejected(self):
        with pytest.raises(ValueError, match="not an instance"):
            load_plugin("tests.plugin_fixtures:RecordingSEH", RTPublisher)

    def test_missing_module(self):
        with pytest.raises(ValueError, match="Unable to locate plugin"):
            load_plugin("no.such.module:Thing")


class TestPluginWiring:
    def test_rt_publisher(self):
        t = TSDB(Config({
            "tsd.core.auto_create_metrics": True,
            "tsd.rtpublisher.enable": True,
            "tsd.rtpublisher.plugin":
                "tests.plugin_fixtures:RecordingPublisher"}))
        t.add_point("m", BASE, 5, {"h": "a"})
        assert t.rt_publisher.points == [("m", BASE * 1000, 5)]

    def test_rt_publisher_enabled_without_plugin_fails(self):
        with pytest.raises(ValueError):
            TSDB(Config({"tsd.rtpublisher.enable": True}))

    def test_write_filter(self):
        t = TSDB(Config({
            "tsd.core.auto_create_metrics": True,
            "tsd.timeseriesfilter.enable": True,
            "tsd.timeseriesfilter.plugin":
                "tests.plugin_fixtures:EvenOnlyFilter"}))
        t.add_point("m", BASE, 2, {"h": "a"})
        t.add_point("m", BASE + 1, 3, {"h": "a"})  # filtered out
        assert t.store.total_datapoints == 1

    def test_seh_on_write_error(self):
        t = TSDB(Config({
            "tsd.core.auto_create_metrics": True,
            "tsd.core.storage_exception_handler.enable": True,
            "tsd.core.storage_exception_handler.plugin":
                "tests.plugin_fixtures:RecordingSEH"}))
        m = RpcManager(t)

        # Force a storage-layer error via a broken store method (the bulk
        # put path lands points through add_batch).
        orig = t.store.add_batch
        def boom(*a, **k):
            raise RuntimeError("storage down")
        t.store.add_batch = boom
        q = m.handle_http(HttpRequest(
            method="POST", uri="/api/put?details",
            body=json.dumps({"metric": "m", "timestamp": BASE,
                             "value": 1, "tags": {"h": "a"}}).encode()))
        t.store.add_batch = orig
        assert len(t.storage_exception_handler.errors) == 1
        assert "storage down" in t.storage_exception_handler.errors[0][1]

    def test_uid_whitelist_filter(self):
        t = TSDB(Config({
            "tsd.core.auto_create_metrics": True,
            "tsd.uidfilter.enable": True,
            "tsd.uidfilter.plugin":
                "opentsdb_tpu.uid.whitelist:UniqueIdWhitelistFilter",
            "tsd.uidfilter.metric_whitelist": "^sys\\..*",
        }))
        t.add_point("sys.ok", BASE, 1, {"h": "a"})
        with pytest.raises(FailedToAssignUniqueIdException):
            t.add_point("other.metric", BASE, 1, {"h": "a"})


class TestHttpAuth:
    @pytest.fixture
    def manager(self):
        t = TSDB(Config({"tsd.core.auto_create_metrics": True}))
        t.authentication = DenyAuth()
        return RpcManager(t)

    def test_unauthenticated_401(self, manager):
        q = manager.handle_http(HttpRequest(
            method="GET", uri="/api/version"))
        assert q.response.status == 401

    def test_authenticated_passes(self, manager):
        q = manager.handle_http(HttpRequest(
            method="GET", uri="/api/version",
            headers={"x-token": "secret"}))
        assert q.response.status == 200
        assert q.auth_state.user == "u"
